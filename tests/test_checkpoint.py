"""Checkpoint/resume helpers (SURVEY §5 checkpoint subsystem).

The reference persists nothing mid-task; these tests pin down the new
capability: atomic saves, latest-step discovery, restore round-trips (with
jax arrays materialised to host), and the workdir contract — an electron
re-dispatched into the same unique workdir resumes from its own state.
"""

import numpy as np
import pytest

from covalent_tpu_plugin.utils import (
    checkpoint_dir,
    latest_step,
    prune_checkpoints,
    register_snapshot,
    reshard_tree,
    restore_checkpoint,
    resume_state,
    save_checkpoint,
    unregister_snapshot,
)


def test_save_restore_roundtrip(tmp_path):
    tree = {"w": np.arange(6.0).reshape(2, 3), "step": 7, "name": "mlp"}
    save_checkpoint(tree, step=7, base=tmp_path)
    restored = restore_checkpoint(step=7, base=tmp_path)
    np.testing.assert_array_equal(restored["w"], tree["w"])
    assert restored["step"] == 7
    assert restored["name"] == "mlp"


def test_latest_step_and_default_restore(tmp_path):
    assert latest_step(tmp_path) is None
    for step in (1, 5, 3):
        save_checkpoint({"s": step}, step=step, base=tmp_path)
    assert latest_step(tmp_path) == 5
    assert restore_checkpoint(base=tmp_path)["s"] == 5


def test_restore_missing_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        restore_checkpoint(base=tmp_path)
    with pytest.raises(FileNotFoundError):
        restore_checkpoint(step=9, base=tmp_path)


def test_jax_arrays_materialise_to_host(tmp_path):
    import jax.numpy as jnp

    tree = {"p": jnp.ones((4, 4))}
    save_checkpoint(tree, step=0, base=tmp_path)
    restored = restore_checkpoint(step=0, base=tmp_path)
    np.testing.assert_array_equal(np.asarray(restored["p"]), np.ones((4, 4)))


def test_checkpoint_dir_honors_cwd_workdir_contract(tmp_path, monkeypatch):
    """Default base is <cwd>/checkpoints — the harness chdirs into the
    per-task workdir (reference exec.py:33-35), so resume is automatic."""
    monkeypatch.chdir(tmp_path)
    save_checkpoint({"x": 1}, step=2)
    assert (tmp_path / "checkpoints" / "step_2").exists()
    assert restore_checkpoint()["x"] == 1
    assert checkpoint_dir() == tmp_path / "checkpoints"


def test_format_mismatch_raises_descriptive_error(tmp_path, monkeypatch):
    """Orbax availability can differ between save and restore environments;
    a dir-vs-file mismatch must be a clear error, not IsADirectoryError
    (ADVICE r1)."""
    from covalent_tpu_plugin.utils import checkpoint as ckpt_mod

    # Simulate an orbax-written step (directory), then an orbax-less stack.
    (tmp_path / "step_1").mkdir()
    monkeypatch.setattr(ckpt_mod, "_ORBAX", False)
    with pytest.raises(RuntimeError, match="orbax"):
        restore_checkpoint(step=1, base=tmp_path)
    with pytest.raises(RuntimeError, match="orbax"):
        save_checkpoint({"x": 1}, step=1, base=tmp_path)


def test_nonzero_process_skips_write(tmp_path, monkeypatch):
    """Replicated electrons: process 0 is the single writer."""
    from covalent_tpu_plugin.utils import checkpoint as ckpt_mod

    monkeypatch.setattr(ckpt_mod, "_process_index", lambda: 1)
    target = save_checkpoint({"x": 1}, step=3, base=tmp_path)
    assert not target.exists()
    # The documented escape hatch: per-process state writes from any rank.
    target = save_checkpoint({"x": 1}, step=3, base=tmp_path / "proc1",
                             per_process=True)
    assert target.exists()


def test_keep_n_prunes_old_steps(tmp_path):
    """keep_n garbage collection: only the newest N complete steps
    survive, and interrupted saves (tmp files) are invisible to
    latest_step by construction."""
    for step in range(6):
        save_checkpoint({"s": step}, step=step, base=tmp_path, keep_n=3)
    steps = sorted(
        int(p.name.split("_")[1])
        for p in tmp_path.iterdir()
        if p.name.startswith("step_")
    )
    assert steps == [3, 4, 5]
    # A torn tmp file (killed mid-save) is never selected nor counted.
    (tmp_path / ".tmp_step_9.123.deadbeef").write_bytes(b"torn")
    assert latest_step(tmp_path) == 5
    assert prune_checkpoints(tmp_path, keep_n=1) == [4, 3]
    assert latest_step(tmp_path) == 5


def test_snapshot_registry_roundtrip():
    from covalent_tpu_plugin.utils import checkpoint as ckpt_mod

    assert ckpt_mod.take_snapshot() is None  # no hook registered
    state = {"acc": 1.5}
    register_snapshot(lambda: (dict(state), 4))
    try:
        tree, step = ckpt_mod.take_snapshot()
        assert tree == {"acc": 1.5} and step == 4
        with pytest.raises(TypeError):
            register_snapshot("not-callable")
    finally:
        unregister_snapshot()
    assert ckpt_mod.take_snapshot() is None


def test_resume_state_env_contract(tmp_path, monkeypatch):
    """resume_state: digest-verified bundle -> (step, tree); a torn
    artifact (wrong digest) returns None so the electron recomputes."""
    import hashlib

    import cloudpickle

    from covalent_tpu_plugin.utils import checkpoint as ckpt_mod

    payload = cloudpickle.dumps(
        {"v": 1, "step": 11, "tree": {"w": np.ones(3)}, "meta": {}}
    )
    bundle = tmp_path / "bundle.ckpt"
    bundle.write_bytes(payload)
    monkeypatch.delenv(ckpt_mod.RESUME_PATH_ENV, raising=False)
    assert resume_state() is None  # cold start: nothing shipped
    monkeypatch.setenv(ckpt_mod.RESUME_PATH_ENV, str(bundle))
    monkeypatch.setenv(
        ckpt_mod.RESUME_DIGEST_ENV, hashlib.sha256(payload).hexdigest()
    )
    step, tree = resume_state()
    assert step == 11
    np.testing.assert_array_equal(tree["w"], np.ones(3))
    # Torn bundle: digest mismatch -> None, never garbage state.
    bundle.write_bytes(payload[: len(payload) // 2])
    assert resume_state() is None


def test_reshard_tree_across_mesh_sizes():
    """Elastic re-meshing: state saved under a 2-device mesh restores
    bit-equal onto 1- and 4-device replacement meshes (CPU virtual mesh),
    sharded leaves included."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    devices = jax.devices()
    assert len(devices) >= 4  # conftest forces an 8-device CPU mesh
    w = np.arange(32.0).reshape(8, 4)
    mesh2 = Mesh(np.array(devices[:2]), ("data",))
    saved = {
        "w": jax.device_put(w, NamedSharding(mesh2, PartitionSpec("data"))),
        "step": 7,
    }
    from covalent_tpu_plugin.utils.checkpoint import host_tree

    host = host_tree(saved)  # what a checkpoint bundle holds
    np.testing.assert_array_equal(np.asarray(host["w"]), w)
    for n in (1, 4):
        mesh_n = Mesh(np.array(devices[:n]), ("data",))
        restored = reshard_tree(
            host, mesh_n,
            shardings={"w": PartitionSpec("data"), "step": PartitionSpec()},
        )
        assert restored["step"] == 7
        assert len(restored["w"].sharding.mesh.devices) == n
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(restored["w"])), w
        )
        # Replicated default (no shardings): same bytes, full copy per
        # device — the train-state restore path.
        replicated = reshard_tree(host, mesh_n)
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(replicated["w"])), w
        )


def test_resume_across_electron_dispatches(tmp_path, run_async):
    """End-to-end: electron 1 checkpoints, electron 2 (same unique workdir)
    resumes — the framework-level resume story."""
    import os
    import pathlib

    from .helpers import make_local_executor

    repo_root = str(pathlib.Path(__file__).resolve().parents[1])
    ex = make_local_executor(
        tmp_path,
        create_unique_workdir=True,
        remote_workdir=str(tmp_path / "wd"),
        # Workers normally have the package installed; the subprocess in this
        # test gets it via PYTHONPATH (same pattern as bench.py).
        task_env={
            "PYTHONPATH": repo_root + os.pathsep + os.environ.get("PYTHONPATH", "")
        },
    )

    def train_until(stop):
        from covalent_tpu_plugin.utils import (
            latest_step as latest,
            restore_checkpoint as restore,
            save_checkpoint as save,
        )

        start = (latest() + 1) if latest() is not None else 0
        state = restore()["acc"] if start else 0
        for step in range(start, stop):
            state += step
            save({"acc": state}, step=step)
        return state

    metadata = {"dispatch_id": "resume", "node_id": 0}

    async def flow():
        first = await ex.run(train_until, [3], {}, metadata)
        second = await ex.run(train_until, [6], {}, metadata)  # same workdir
        await ex.close()
        return first, second

    first, second = run_async(flow())
    assert first == 0 + 1 + 2
    assert second == first + 3 + 4 + 5  # resumed, not recomputed
