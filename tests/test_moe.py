"""Mixture-of-experts: routing semantics, capacity, aux loss, EP training.

Oracle for the dispatch/combine einsums: per-token python routing — every
kept token's MoE output must equal ``gate * expert_mlp(token)`` for its
argmax expert, and dropped tokens must contribute exactly zero.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from covalent_tpu_plugin.models import TransformerConfig, TransformerLM
from covalent_tpu_plugin.models.moe import MoEMlp, lm_loss_with_moe_aux
from covalent_tpu_plugin.models.train import (
    make_sharded_train_state,
    make_train_step,
)
from covalent_tpu_plugin.parallel import MeshPlan, make_mesh, shard_batch

CFG = TransformerConfig(
    vocab_size=64,
    d_model=16,
    n_layers=2,
    n_heads=2,
    d_ff=32,
    max_seq=16,
    dtype=jnp.float32,
    attention="reference",
    moe_experts=4,
    moe_capacity_factor=2.0,
)


def moe_oracle(params, x, capacity_factor, n_experts):
    """Per-token reference routing in plain numpy-ish jax."""
    batch, seq_len, d = x.shape
    tokens = x.reshape(-1, d)
    gates = jax.nn.softmax(tokens @ params["router"]["kernel"], axis=-1)
    idx = jnp.argmax(gates, axis=-1)
    gate = jnp.max(gates, axis=-1)
    n_tokens = tokens.shape[0]
    capacity = max(1, min(int(-(-capacity_factor * n_tokens // n_experts)),
                          n_tokens))
    counts = {e: 0 for e in range(n_experts)}
    outs = []
    for n in range(n_tokens):
        e = int(idx[n])
        if counts[e] < capacity:
            counts[e] += 1
            h = jax.nn.gelu(tokens[n] @ params["wi"][e])
            outs.append(gate[n] * (h @ params["wo"][e]))
        else:
            outs.append(jnp.zeros(d))
    return jnp.stack(outs).reshape(batch, seq_len, d)


def unboxed(params):
    from covalent_tpu_plugin.parallel.sharding import unbox

    return unbox(params)


@pytest.mark.parametrize("capacity_factor", [4.0, 0.25], ids=["roomy", "tight"])
def test_moe_matches_per_token_oracle(capacity_factor):
    cfg = dataclasses.replace(CFG, moe_capacity_factor=capacity_factor)
    module = MoEMlp(cfg)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, cfg.d_model))
    variables = module.init(jax.random.PRNGKey(1), x)
    out = module.apply(variables, x)
    ref = moe_oracle(
        unboxed(variables["params"]), x, capacity_factor, cfg.moe_experts
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
    if capacity_factor < 1:  # tight: some tokens must actually be dropped
        dropped = np.isclose(np.asarray(out).reshape(-1, cfg.d_model), 0).all(axis=1)
        assert dropped.any()


def test_moe_aux_loss_sown_and_near_one_when_uniform():
    module = MoEMlp(CFG)
    x = jax.random.normal(jax.random.PRNGKey(2), (2, 8, CFG.d_model)) * 1e-3
    variables = module.init(jax.random.PRNGKey(3), x)
    _, state = module.apply(variables, x, mutable=["intermediates"])
    (aux,) = jax.tree_util.tree_leaves(state["intermediates"])
    # Near-zero router logits -> near-uniform gates -> aux ~= 1 (its min).
    assert 0.9 < float(aux) < 1.6


def test_moe_aux_survives_scanned_layers():
    """The aux loss must reach the loss function through nn.scan (scan
    silently drops undeclared collections) and ignore unrelated sows."""
    from covalent_tpu_plugin.models.moe import collect_moe_aux

    model = TransformerLM(CFG)  # scan_layers=True default
    tokens = jnp.ones((2, 9), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), tokens[:, :-1])
    _, state = model.apply(
        variables, tokens[:, :-1], mutable=["intermediates"]
    )
    aux = collect_moe_aux(state["intermediates"])
    assert float(aux) > 0.5  # one near-1 term per layer
    # key filter: foreign intermediates must not leak into the loss
    assert float(collect_moe_aux({"other": (jnp.ones((3,)),)})) == 0.0


def test_moe_lm_trains_with_expert_parallelism():
    """The full model with MoE blocks, experts sharded over tensor=2,
    trained through the standard sharded step with the aux-aware loss."""
    mesh = make_mesh(MeshPlan(data=2, tensor=2))
    model = TransformerLM(CFG)
    rng = np.random.default_rng(5)
    tokens = rng.integers(0, 64, size=(8, 17)).astype(np.int32)
    batch = shard_batch({"tokens": tokens}, mesh)
    state, shardings = make_sharded_train_state(
        model, optax.adamw(1e-2), jax.random.PRNGKey(0),
        batch["tokens"][:, :-1], mesh,
    )
    # Expert weights really are expert-sharded over the tensor axis.
    wi_sharding = jax.tree_util.tree_leaves(
        shardings.params["layers"]["moe"]["wi"]
    )[0]
    # scan prepends the (replicated) layers axis; the expert axis follows.
    flat_axes = [
        axis
        for entry in wi_sharding.spec
        for axis in ((entry,) if isinstance(entry, str) else (entry or ()))
    ]
    assert "tensor" in flat_axes, wi_sharding.spec

    step = make_train_step(lm_loss_with_moe_aux, mesh, shardings)
    losses = []
    for _ in range(5):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses


def test_moe_composes_with_scan_and_remat():
    cfg = dataclasses.replace(CFG, remat=True, scan_layers=True)
    model = TransformerLM(cfg)
    tokens = jnp.ones((2, 9), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens[:, :-1])["params"]
    loss, grads = jax.value_and_grad(
        lambda p: lm_loss_with_moe_aux(p, model.apply, {"tokens": tokens})
    )(params)
    assert np.isfinite(float(loss))
    assert all(
        bool(jnp.isfinite(g).all()) for g in jax.tree_util.tree_leaves(grads)
    )


def test_moe_model_generates():
    """KV-cache decoding through MoE blocks: jittable, valid tokens.

    No exact-match oracle here on purpose: capacity-based top-1 routing
    is computed over the tokens present in the call, so a single-token
    decode step can keep a token a full teacher-forced forward would
    have dropped at capacity (the standard train/serve routing mismatch
    of capacity MoEs) — greedy continuations may legitimately diverge.
    """
    from covalent_tpu_plugin.models import generate

    cfg = CFG  # max_seq 16 covers prompt 4 + 5 new tokens
    model = TransformerLM(cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(6), (2, 4), 0, cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]
    out = jax.jit(lambda p, t: generate(model, p, t, max_new_tokens=5))(
        params, prompt
    )
    assert out.shape == (2, 9)
    arr = np.asarray(out)
    np.testing.assert_array_equal(arr[:, :4], np.asarray(prompt))
    assert (arr >= 0).all() and (arr < cfg.vocab_size).all()
