"""Observability subsystem tests: registry semantics, Prometheus exposition,
span nesting/ids, JSONL event sink round-trip, and the integration contract —
a full ``TPUExecutor.run()`` over the local transport leaves the expected
ordered span set with consistent trace/parent ids (ISSUE 1 acceptance)."""

from __future__ import annotations

import json
import time

import pytest

from covalent_tpu_plugin.obs import dump_metrics
from covalent_tpu_plugin.obs import events as obs_events
from covalent_tpu_plugin.obs.metrics import (
    Counter,
    Gauge,
    Histogram,
    Registry,
)
from covalent_tpu_plugin.obs.trace import SPAN_HISTOGRAM, Span, current_span, span

from .helpers import make_local_executor


@pytest.fixture()
def events_file(tmp_path):
    """Point the process-wide event sink at a fresh JSONL file.

    Teardown is reset(), not configure(None): a process-wide
    COVALENT_TPU_EVENTS_PATH (CI's telemetry artifact) must resume
    collecting for the test files that run after this one.
    """
    path = tmp_path / "events.jsonl"
    obs_events.configure(str(path))
    yield path
    obs_events.reset()


def read_events(path) -> list[dict]:
    if not path.exists():
        return []
    return [json.loads(line) for line in path.read_text().splitlines()]


# --------------------------------------------------------------------- #
# Metrics registry semantics
# --------------------------------------------------------------------- #


def test_counter_semantics():
    reg = Registry()
    c = reg.counter("requests_total", "total requests")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    with pytest.raises(ValueError, match="only go up"):
        c.inc(-1)


def test_counter_labels_are_separate_series():
    reg = Registry()
    c = reg.counter("tasks_total", "", label_names=("outcome",))
    c.labels(outcome="ok").inc()
    c.labels(outcome="ok").inc()
    c.labels(outcome="err").inc()
    assert c.labels(outcome="ok").value == 2
    assert c.labels(outcome="err").value == 1
    with pytest.raises(ValueError, match="expected labels"):
        c.labels(wrong="x")
    with pytest.raises(ValueError, match="use .labels"):
        c.inc()


def test_gauge_semantics():
    reg = Registry()
    g = reg.gauge("active", "")
    g.set(5)
    g.inc()
    g.dec(2)
    assert g.value == 4


def test_histogram_buckets_and_quantiles():
    reg = Registry()
    h = reg.histogram("latency_seconds", "", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0):
        h.observe(v)
    assert h.count == 4
    assert h.sum == pytest.approx(6.05)
    child = h._default_child()
    # cumulative le counts: 0.1 -> 1, 1.0 -> 3, 10.0 -> 4, +Inf -> 4
    assert child.cumulative() == [1, 3, 4, 4]
    assert h.quantile(0.5) == 1.0  # upper-bound estimate of the median
    assert h.quantile(1.0) == 10.0


def test_registry_get_or_create_returns_same_metric():
    reg = Registry()
    a = reg.counter("x_total", "")
    b = reg.counter("x_total", "")
    assert a is b
    with pytest.raises(ValueError, match="different type"):
        reg.gauge("x_total", "")


def test_histogram_bucket_mismatch_rejected():
    reg = Registry()
    a = reg.histogram("h_seconds", "", buckets=(0.1, 1.0))
    assert reg.histogram("h_seconds", "", buckets=(1.0, 0.1)) is a  # order-free
    with pytest.raises(ValueError, match="different buckets"):
        reg.histogram("h_seconds", "", buckets=(0.5, 2.0))


def test_snapshot_shape():
    reg = Registry()
    reg.counter("c_total", "help c").inc(3)
    reg.histogram("h_seconds", "", buckets=(1.0,)).observe(0.5)
    snap = reg.snapshot()
    assert snap["metrics"]["c_total"]["kind"] == "counter"
    assert snap["metrics"]["c_total"]["series"][0]["value"] == 3
    hist = snap["metrics"]["h_seconds"]["series"][0]
    assert hist["count"] == 1
    assert hist["buckets"]["1"] == 1
    assert hist["buckets"]["+Inf"] == 1
    json.dumps(snap)  # JSON-serializable end to end


# --------------------------------------------------------------------- #
# Prometheus text exposition
# --------------------------------------------------------------------- #


def test_prometheus_text_counter_and_gauge():
    reg = Registry()
    reg.counter("jobs_total", "jobs", label_names=("state",)).labels(
        state="done"
    ).inc(2)
    reg.gauge("pool_size", "live transports").set(3)
    text = reg.prometheus_text()
    assert "# HELP jobs_total jobs" in text
    assert "# TYPE jobs_total counter" in text
    assert 'jobs_total{state="done"} 2' in text
    assert "# TYPE pool_size gauge" in text
    assert "pool_size 3" in text


def test_prometheus_text_histogram_format():
    reg = Registry()
    h = reg.histogram("rt_seconds", "round trips", buckets=(0.5, 2.0))
    h.observe(0.1)
    h.observe(1.0)
    h.observe(100.0)
    text = reg.prometheus_text()
    assert 'rt_seconds_bucket{le="0.5"} 1' in text
    assert 'rt_seconds_bucket{le="2"} 2' in text
    assert 'rt_seconds_bucket{le="+Inf"} 3' in text
    assert "rt_seconds_sum 101.1" in text
    assert "rt_seconds_count 3" in text


def test_prometheus_label_values_escaped():
    reg = Registry()
    reg.counter("e_total", "", label_names=("msg",)).labels(
        msg='bad "quote"\nline'
    ).inc()
    text = reg.prometheus_text()
    assert 'msg="bad \\"quote\\"\\nline"' in text


def test_dump_metrics_both_formats(tmp_path):
    reg = Registry()
    reg.counter("d_total", "").inc()
    json_path = tmp_path / "m.json"
    prom_path = tmp_path / "m.prom"
    dump_metrics(str(json_path), reg)
    dump_metrics(str(prom_path), reg)
    assert json.loads(json_path.read_text())["metrics"]["d_total"]
    assert "# TYPE d_total counter" in prom_path.read_text()


# --------------------------------------------------------------------- #
# Spans: nesting, ids, status, stage accounting
# --------------------------------------------------------------------- #


def test_span_nesting_and_parent_ids(events_file):
    with span("outer") as outer:
        assert current_span() is outer
        with span("middle") as middle:
            with span("inner.leaf") as inner:
                assert inner.trace_id == outer.trace_id
                assert inner.parent_id == middle.span_id
        assert middle.parent_id == outer.span_id
    assert current_span() is None
    assert outer.parent_id is None
    events = [e for e in read_events(events_file) if e["type"] == "span"]
    # Children end before parents: leaf-first order in the stream.
    assert [e["name"] for e in events] == ["inner.leaf", "middle", "outer"]
    assert len({e["trace_id"] for e in events}) == 1


def test_span_error_status_propagates(events_file):
    with pytest.raises(RuntimeError):
        with span("boom"):
            raise RuntimeError("bad")
    (event,) = [e for e in read_events(events_file) if e["type"] == "span"]
    assert event["status"] == "ERROR"
    assert "bad" in event["attributes"]["error"]


def test_span_stage_durations_accumulate():
    with Span("root", emit=False) as root:
        with Span("root.step", emit=False):
            time.sleep(0.01)
        with Span("root.step", emit=False):
            time.sleep(0.01)
        with Span("root.execute", emit=False):
            time.sleep(0.01)
    # Same leaf name accumulates; overhead excludes the execute stage.
    assert root.stage_durations["step"] >= 0.02
    assert root.overhead() == pytest.approx(
        root.stage_durations["step"], rel=0.01
    )
    summary = root.summary()
    assert set(summary) == {"step", "execute", "total", "overhead"}


def test_span_durations_land_in_histogram():
    from covalent_tpu_plugin.obs.metrics import REGISTRY

    with span("obs-test-unique-span"):
        pass
    hist = REGISTRY.get(SPAN_HISTOGRAM)
    child = hist.labels(span="obs-test-unique-span")
    assert child.count >= 1


def test_stagetimer_shim_matches_old_api():
    from covalent_tpu_plugin.utils.timing import StageTimer

    t = StageTimer()
    with t.stage("validate"):
        time.sleep(0.005)
    with t.stage("execute"):
        time.sleep(0.005)
    s = t.summary()
    assert set(s) == {"validate", "execute", "total", "overhead"}
    assert s["overhead"] == pytest.approx(s["validate"])
    assert s["total"] >= s["validate"] + s["execute"]
    assert t.stages["validate"] == s["validate"]


# --------------------------------------------------------------------- #
# Event sink round-trip
# --------------------------------------------------------------------- #


def test_event_sink_roundtrip(events_file):
    obs_events.emit("custom.event", key="value", n=3)
    (event,) = read_events(events_file)
    assert event["type"] == "custom.event"
    assert event["key"] == "value"
    assert event["n"] == 3
    assert event["ts"] > 0 and event["pid"] > 0


def test_event_sink_disabled_is_noop(tmp_path):
    # The no-op contract is "disabled AND unobserved": once any executor
    # has wired the flight recorder's process-wide listener, events are
    # observed and must be built even with no JSONL path configured.
    obs_events.configure(None)
    listeners = obs_events._listeners[:]
    obs_events._listeners[:] = []
    try:
        assert obs_events.emit("ignored") is None
    finally:
        obs_events._listeners[:] = listeners
        obs_events.reset()


def test_event_sink_serializes_unserializable_payloads(events_file):
    obs_events.emit("weird", obj=object())
    (event,) = read_events(events_file)
    assert event["type"] == "weird"  # repr fallback, never a crash


def test_terminal_events_survive_sigkill(tmp_path):
    """Terminal ``task.state`` and ``slo.burn`` events fsync inline: a
    process SIGKILLed the instant after the emit still leaves them on
    disk (the whole point of a crash record)."""
    import subprocess
    import sys as sys_mod

    path = tmp_path / "events.jsonl"
    code = (
        "import os, signal\n"
        "from covalent_tpu_plugin.obs import events\n"
        f"events.configure({str(path)!r})\n"
        "events.emit('task.state', operation_id='op-1', state='starting')\n"
        "events.emit('task.state', operation_id='op-1', state='failed')\n"
        "events.emit('slo.burn', slo='serve_ttft', burn=14.4)\n"
        "os.kill(os.getpid(), signal.SIGKILL)\n"
    )
    proc = subprocess.run(
        [sys_mod.executable, "-c", code], timeout=60,
        cwd="/root/repo", capture_output=True,
    )
    assert proc.returncode == -9  # died by SIGKILL, no cleanup ran
    types = [e["type"] for e in read_events(path)]
    assert "slo.burn" in types
    states = [
        e["state"] for e in read_events(path) if e["type"] == "task.state"
    ]
    assert "failed" in states


def test_event_listener_sees_events_without_a_path():
    obs_events.configure(None)
    seen: list[dict] = []
    obs_events.add_listener(seen.append)
    try:
        obs_events.emit("listener.test", x=1)
    finally:
        obs_events.remove_listener(seen.append)
        obs_events.reset()
    assert seen and seen[0]["type"] == "listener.test"


def test_event_sink_reset_restores_env_path(tmp_path, monkeypatch):
    """reset() after a configure() resumes the env-configured stream."""
    env_path = tmp_path / "env.jsonl"
    monkeypatch.setenv("COVALENT_TPU_EVENTS_PATH", str(env_path))
    obs_events.configure(str(tmp_path / "override.jsonl"))
    obs_events.emit("to.override")
    sink = obs_events.reset()
    try:
        assert sink.path == str(env_path)
        obs_events.emit("to.env")
        assert [e["type"] for e in read_events(env_path)] == ["to.env"]
    finally:
        monkeypatch.delenv("COVALENT_TPU_EVENTS_PATH")
        obs_events.reset()


def test_metrics_env_dump_at_exit(tmp_path):
    """COVALENT_TPU_METRICS dumps a snapshot at interpreter exit."""
    import subprocess
    import sys

    out = tmp_path / "exit_metrics.json"
    code = (
        "from covalent_tpu_plugin.obs.metrics import REGISTRY\n"
        "REGISTRY.counter('exit_probe_total', '').inc(7)\n"
    )
    env = dict(__import__("os").environ)
    env["COVALENT_TPU_METRICS"] = str(out)
    subprocess.run(
        [sys.executable, "-c", code], check=True, env=env,
        cwd="/root/repo", timeout=60,
    )
    snap = json.loads(out.read_text())
    assert snap["metrics"]["exit_probe_total"]["series"][0]["value"] == 7


# --------------------------------------------------------------------- #
# Integration: one full run() over the local transport
# --------------------------------------------------------------------- #

EXPECTED_LIFECYCLE = [
    "executor.validate",
    "executor.connect",
    "executor.preflight",
    "executor.stage",
    "executor.upload",
    "executor.submit",
    "executor.execute",
    "executor.fetch",
    "executor.cleanup",
]


def test_full_run_produces_ordered_span_set(tmp_path, run_async, events_file):
    ex = make_local_executor(tmp_path)
    out = run_async(ex.run(lambda x: x + 1, [1], {},
                           {"dispatch_id": "obs", "node_id": 0}))
    assert out == 2
    events = read_events(events_file)
    spans = [e for e in events if e["type"] == "span"]
    (root,) = [s for s in spans if s["name"] == "executor.run"]
    assert root["attributes"]["outcome"] == "completed"
    children = [s for s in spans if s.get("parent_id") == root["span_id"]]
    # Every lifecycle stage present, all in the root's trace.  The stage
    # span is pipelined (serialization overlaps the connect/pre-flight
    # round trips), so only the strictly-sequential stages keep a fixed
    # completion order.
    assert sorted(s["name"] for s in children) == sorted(EXPECTED_LIFECYCLE)
    sequential = [
        s["name"] for s in children if s["name"] != "executor.stage"
    ]
    assert sequential == [
        n for n in EXPECTED_LIFECYCLE if n != "executor.stage"
    ]
    assert all(s["trace_id"] == root["trace_id"] for s in children)
    assert all(s["status"] == "OK" for s in children)
    # Task-state transitions bracket the trace.
    states = [e["state"] for e in events if e["type"] == "task.state"]
    assert states == ["starting", "submitted", "completed"]
    # The worker harness joined the same JSONL stream (shared fs).
    # Heartbeats interleave on their own cadence (covered in
    # test_fleetobs); the lifecycle pair must bracket them.
    worker = [e for e in events if e["type"].startswith("worker.")]
    assert [
        e["type"] for e in worker if e["type"] != "worker.heartbeat"
    ] == ["worker.task_started", "worker.task_finished"]
    assert all(e["operation_id"] == "obs_0" for e in worker)
    # Trace propagation: every worker-side record joined the dispatch
    # trace stamped into the task spec.
    assert all(e["trace_id"] == root["trace_id"] for e in worker)
    # last_timings kept its pre-obs contract, fed by the same spans.
    assert ex.last_timings["overhead"] == pytest.approx(
        sum(s["duration_s"] for s in children if s["name"] != "executor.execute"),
        rel=0.05,
    )


def test_failed_run_still_accounts(tmp_path, run_async, events_file):
    """Error paths populate last_timings, the outcome counter, and a
    terminal failure event (ISSUE 1 satellite)."""
    from covalent_tpu_plugin.obs.metrics import REGISTRY

    # Defined in-test so cloudpickle serializes it by value — the harness
    # subprocess cannot import the tests package.
    def exploding_electron():
        raise ValueError("electron exploded")

    ex = make_local_executor(tmp_path)
    before = REGISTRY.counter(
        "covalent_tpu_tasks_total", "", ("outcome",)
    ).labels(outcome="remote_exception").value
    with pytest.raises(ValueError, match="electron exploded"):
        run_async(ex.run(exploding_electron, [], {},
                         {"dispatch_id": "obsfail", "node_id": 0}))
    assert "overhead" in ex.last_timings and ex.last_timings["overhead"] > 0
    after = REGISTRY.counter(
        "covalent_tpu_tasks_total", "", ("outcome",)
    ).labels(outcome="remote_exception").value
    assert after == before + 1
    events = read_events(events_file)
    (root,) = [e for e in events if e["type"] == "span"
               and e["name"] == "executor.run"]
    assert root["status"] == "ERROR"
    terminal = [e for e in events if e["type"] == "task.state"][-1]
    assert terminal["state"] == "remote_exception"
    assert terminal["overhead_s"] > 0


def test_workflow_nodes_emit_events(tmp_path, events_file):
    """Dispatch + node state transitions ride the same stream."""
    from covalent_tpu_plugin.workflow import electron, lattice
    from covalent_tpu_plugin.workflow.runner import dispatch_sync

    @electron
    def add(a, b):
        return a + b

    @lattice
    def flow(a, b):
        return add(add(a, b), b)

    result = dispatch_sync(flow)(1, 2)
    assert result.status.value == "COMPLETED"
    assert result.result == 5
    events = read_events(events_file)
    node_states = [e["state"] for e in events if e["type"] == "node.state"]
    assert node_states.count("running") == 2
    assert node_states.count("completed") == 2
    dispatch_states = [e["state"] for e in events if e["type"] == "dispatch.state"]
    assert dispatch_states == ["running", "COMPLETED"]
    node_spans = [e for e in events if e["type"] == "span"
                  and e["name"] == "workflow.node"]
    dispatch_spans = [e for e in events if e["type"] == "span"
                      and e["name"] == "workflow.dispatch"]
    assert len(node_spans) == 2 and len(dispatch_spans) == 1
    # One trace per dispatch: nodes parent under the dispatch root.
    assert {s["trace_id"] for s in node_spans} == {
        dispatch_spans[0]["trace_id"]
    }
    assert all(
        s["parent_id"] == dispatch_spans[0]["span_id"] for s in node_spans
    )


def test_pool_metrics_hit_and_miss(tmp_path, run_async, events_file):
    from covalent_tpu_plugin.obs.metrics import REGISTRY
    from covalent_tpu_plugin.transport import LocalTransport, TransportPool

    hits = REGISTRY.counter(
        "covalent_tpu_pool_acquires_total", "", ("result",)
    )
    h0, m0 = hits.labels(result="hit").value, hits.labels(result="miss").value

    async def flow():
        pool = TransportPool()

        async def factory():
            return LocalTransport()

        first = await pool.acquire("k", factory)
        second = await pool.acquire("k", factory)
        assert first is second
        await pool.close_all()

    run_async(flow())
    assert hits.labels(result="miss").value == m0 + 1
    assert hits.labels(result="hit").value == h0 + 1
