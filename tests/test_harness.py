"""Harness tests — the reference never executes ``exec.py`` in its test
suite (excluded from coverage, ``codecov.yml:1-3``); here the harness is a
real importable module, so the file protocol (``exec.py:29-46``) is tested
directly *and* via a true subprocess round-trip.
"""

import json
import os
import pickle
import subprocess
import sys

import cloudpickle
import pytest

from covalent_tpu_plugin import harness
from covalent_tpu_plugin.utils.serialize import dump_task, load_result


def _stage(tmp_path, fn, args=(), kwargs=None, **spec_extra):
    function_file = tmp_path / "function.pkl"
    result_file = tmp_path / "result.pkl"
    dump_task(fn, args, kwargs or {}, function_file)
    spec = {
        "function_file": str(function_file),
        "result_file": str(result_file),
        "workdir": str(tmp_path / "workdir"),
        **spec_extra,
    }
    return spec, result_file


def test_run_task_success(tmp_path):
    spec, result_file = _stage(tmp_path, lambda a, b: a + b, (2, 3))
    assert harness.run_task(spec) == 0
    result, exception = load_result(result_file)
    assert result == 5 and exception is None


def test_run_task_transports_user_exception(tmp_path):
    def boom():
        raise ValueError("user error")

    spec, result_file = _stage(tmp_path, boom)
    assert harness.run_task(spec) == 0  # harness itself succeeds (exec.py:45-46)
    result, exception = load_result(result_file)
    assert result is None
    assert isinstance(exception, ValueError) and "user error" in str(exception)


def test_run_task_chdirs_into_workdir_and_restores(tmp_path):
    spec, result_file = _stage(tmp_path, lambda: os.getcwd())
    before = os.getcwd()
    harness.run_task(spec)
    assert os.getcwd() == before  # cwd restored (exec.py:41-42)
    result, _ = load_result(result_file)
    assert result == str(tmp_path / "workdir")
    assert (tmp_path / "workdir").is_dir()  # created on demand (exec.py:33-35)


def test_run_task_applies_env(tmp_path, monkeypatch):
    monkeypatch.delenv("CTPU_TEST_VAR", raising=False)
    spec, result_file = _stage(
        tmp_path, lambda: os.environ.get("CTPU_TEST_VAR"), env={"CTPU_TEST_VAR": "42"}
    )
    harness.run_task(spec)
    result, _ = load_result(result_file)
    assert result == "42"


def test_run_task_nonzero_process_writes_done_marker(tmp_path, monkeypatch):
    import jax

    calls = {}

    def fake_init(**kwargs):
        calls.update(kwargs)

    monkeypatch.setattr(jax.distributed, "initialize", fake_init)
    spec, result_file = _stage(
        tmp_path,
        lambda: "replicated",
        distributed={
            "coordinator_address": "w0:8476",
            "num_processes": 2,
            "process_id": 1,
        },
    )
    assert harness.run_task(spec) == 0
    # Only process 0 writes the result pickle; others drop a done marker.
    assert not result_file.exists()
    assert (tmp_path / "result.pkl.done.1").exists()
    assert calls == {
        "coordinator_address": "w0:8476",
        "num_processes": 2,
        "process_id": 1,
    }


def test_result_write_is_atomic_no_tmp_left(tmp_path):
    spec, result_file = _stage(tmp_path, lambda: 1)
    harness.run_task(spec)
    assert result_file.exists()
    assert not (tmp_path / "result.pkl.tmp").exists()


def test_to_host_materialises_jax_arrays(tmp_path):
    import jax.numpy as jnp
    import numpy as np

    out = harness._to_host({"x": jnp.ones((4,)), "y": 3})
    assert isinstance(out["x"], np.ndarray)
    assert out["y"] == 3


@pytest.mark.functional_tests
def test_harness_subprocess_roundtrip(tmp_path):
    """Full machine-boundary simulation: fresh python process runs the staged
    harness file exactly as a worker would (reference flow ssh.py:377-383)."""
    spec, result_file = _stage(tmp_path, lambda x: x * 10, (7,))
    spec_file = tmp_path / "spec.json"
    spec_file.write_text(json.dumps(spec))
    proc = subprocess.run(
        [sys.executable, harness.__file__, str(spec_file)],
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    result, exception = pickle.loads(result_file.read_bytes())
    assert result == 70 and exception is None


def test_run_task_pythonpath_env_reaches_sys_path(tmp_path):
    """task_env PYTHONPATH must affect imports inside the electron, not just
    child processes (the interpreter is already running when env applies)."""
    pkg = tmp_path / "extra_pkg"
    pkg.mkdir()
    (pkg / "task_env_probe_mod.py").write_text("VALUE = 'found-me'\n")

    def electron():
        import task_env_probe_mod

        return task_env_probe_mod.VALUE

    spec, result_file = _stage(tmp_path, electron, env={"PYTHONPATH": str(pkg)})
    assert harness.run_task(spec) == 0
    result, exception = load_result(result_file)
    assert exception is None
    assert result == "found-me"


def test_run_task_writes_profiler_trace(tmp_path):
    """profile_dir in the spec turns on jax.profiler around the electron."""

    def electron():
        import jax.numpy as jnp

        return float(jnp.ones((8, 8)).sum())

    profile_dir = tmp_path / "traces"
    spec, result_file = _stage(tmp_path, electron, profile_dir=str(profile_dir))
    assert harness.run_task(spec) == 0
    result, exception = load_result(result_file)
    assert exception is None and result == 64.0
    # jax writes plugins/profile/<ts>/*.xplane.pb under the trace dir
    assert any(profile_dir.rglob("*.xplane.pb"))
