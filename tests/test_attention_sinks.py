"""Attention sinks (StreamingLLM): kernel exactness, decode-band
agreement, pinned rolling-cache slots, long-decode stability.

The decisive properties: the flash kernels match a handwritten
window+sinks oracle at tile geometries where sink tiles and band tiles
are distinct; cached decode (standard AND rolling) reproduces the
training forward's mask token-for-token; the rolling ring never evicts a
sink slot; and sinks genuinely change long-range behavior (position 0
stays visible past the band, where window-only masks it).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from covalent_tpu_plugin.models import TransformerConfig, TransformerLM, generate
from covalent_tpu_plugin.ops.attention import flash_attention, mha_reference


def sink_window_oracle(q, k, v, window, sinks):
    """Straight-line windowed+sinks softmax, no shared code with the
    implementations under test."""
    scores = jnp.einsum(
        "bhqd,bhkd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * (q.shape[-1] ** -0.5)
    s_q, s_k = q.shape[2], k.shape[2]
    qi = np.arange(s_q)[:, None]
    ki = np.arange(s_k)[None, :]
    visible = (qi >= ki) & ((qi - ki < window) | (ki < sinks))
    scores = jnp.where(jnp.asarray(visible), scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v.astype(jnp.float32))


def qkv(b=1, h=2, s=256, d=64, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(key, (b, h, s, d), jnp.float32) for key in ks)


@pytest.mark.parametrize("window,sinks", [(37, 4), (64, 1), (128, 70), (30, 30)])
def test_reference_matches_oracle(window, sinks):
    q, k, v = qkv()
    want = np.asarray(sink_window_oracle(q, k, v, window, sinks))
    got = np.asarray(
        mha_reference(q, k, v, causal=True, window=window, sinks=sinks),
        np.float32,
    )
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("window,sinks", [(37, 4), (100, 65), (200, 8)])
def test_flash_forward_matches_reference(window, sinks):
    # 64x64 tiles at s=256: sink tiles, band tiles, and dead tiles all
    # occur, so the tile-skip predicate's sink clause really executes.
    q, k, v = qkv()
    want = np.asarray(
        mha_reference(q, k, v, causal=True, window=window, sinks=sinks),
        np.float32,
    )
    got = np.asarray(
        flash_attention(
            q, k, v, causal=True, window=window, sinks=sinks,
            block_q=64, block_k=64,
        ),
        np.float32,
    )
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


def test_flash_backward_matches_reference():
    q, k, v = qkv(s=256)

    def loss(fn):
        return lambda q, k, v: (
            fn(q, k, v).astype(jnp.float32) * jnp.cos(jnp.arange(64.0))
        ).sum()

    g_ref = jax.grad(
        loss(lambda q, k, v: mha_reference(
            q, k, v, causal=True, window=50, sinks=6
        )),
        argnums=(0, 1, 2),
    )(q, k, v)
    g_flash = jax.grad(
        loss(lambda q, k, v: flash_attention(
            q, k, v, causal=True, window=50, sinks=6, block_q=64, block_k=64
        )),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            atol=5e-5, rtol=5e-5,
        )


@pytest.mark.parametrize("sinks", [6, 140])
def test_banded_dkdv_sink_split_exact(monkeypatch, sinks):
    """The dk/dv sinks SPLIT (sink-tile full-sweep call + banded
    remainder call, r4) must match the dense oracle.  The default
    backward tile (1024) covers s=512 in one tile, so shrink it to 128:
    kt_full=4, the sink run is 1 tile (sinks=6) or 2 tiles (sinks=140,
    non-tile-aligned so the second sink tile mixes sink and band
    columns), and the remainder call runs the offset banded grid."""
    import covalent_tpu_plugin.ops.attention as att

    monkeypatch.setattr(att, "_DEFAULT_BWD_BLOCK", 128)
    # Split preconditions really hold at this geometry.
    nst = att._sink_tiles(sinks, 128)
    assert 0 < nst < 512 // 128
    assert att._banded_n_inner_qt(512, 512, 128, 128, 100) is not None

    q, k, v = qkv(s=512)

    def loss(fn):
        return lambda q, k, v: (
            fn(q, k, v).astype(jnp.float32) * jnp.cos(jnp.arange(64.0))
        ).sum()

    g_ref = jax.grad(
        loss(lambda q, k, v: mha_reference(
            q, k, v, causal=True, window=100, sinks=sinks
        )),
        argnums=(0, 1, 2),
    )(q, k, v)
    g_flash = jax.grad(
        loss(lambda q, k, v: flash_attention(
            q, k, v, causal=True, window=100, sinks=sinks,
            block_q=128, block_k=128,
        )),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            atol=5e-5, rtol=5e-5,
        )


def test_sinks_change_long_range_behavior():
    """Position 0's value must influence rows past the band with sinks on,
    and must NOT without them — the defining sink property."""
    q, k, v = qkv(s=128)
    bumped_v = v.at[:, :, 0, :].add(10.0)
    window = 16
    no_sinks = mha_reference(q, k, v, causal=True, window=window)
    no_sinks_bumped = mha_reference(q, k, bumped_v, causal=True, window=window)
    # Rows far past the band: insensitive to position 0 without sinks.
    np.testing.assert_allclose(
        np.asarray(no_sinks[:, :, 64:]), np.asarray(no_sinks_bumped[:, :, 64:]),
        atol=1e-6,
    )
    with_sinks = mha_reference(
        q, k, v, causal=True, window=window, sinks=2
    )
    with_sinks_bumped = mha_reference(
        q, k, bumped_v, causal=True, window=window, sinks=2
    )
    delta = np.abs(
        np.asarray(with_sinks[:, :, 64:]) - np.asarray(with_sinks_bumped[:, :, 64:])
    )
    assert delta.max() > 1e-3  # sink column visibly feeds far rows


def test_validation():
    q, k, v = qkv(s=128)
    with pytest.raises(ValueError, match="require a window"):
        flash_attention(q, k, v, causal=True, sinks=4)
    with pytest.raises(ValueError, match="require a window"):
        mha_reference(q, k, v, causal=True, sinks=4)
    with pytest.raises(ValueError, match="attention_sinks require"):
        TransformerConfig(attention_sinks=4)
    with pytest.raises(ValueError, match="attention_sinks must be"):
        TransformerConfig(sliding_window=8, attention_sinks=-1)


def test_ring_rejects_sinks():
    from covalent_tpu_plugin.parallel import MeshPlan, make_mesh

    mesh = make_mesh(MeshPlan(seq=2, data=4))
    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=1, n_heads=4, d_ff=64,
        max_seq=32, dtype=jnp.float32, attention="ring", mesh=mesh,
        sliding_window=6, attention_sinks=2,
    )
    model = TransformerLM(cfg)
    with pytest.raises(ValueError, match="unsupported with attention='ring'"):
        model.init(jax.random.PRNGKey(0), jnp.zeros((4, 8), jnp.int32))


BASE = TransformerConfig(
    vocab_size=64,
    d_model=32,
    n_layers=2,
    n_heads=4,
    d_ff=64,
    max_seq=32,
    dtype=jnp.float32,
    attention="reference",
    sliding_window=6,
    attention_sinks=2,
)


def test_cached_decode_matches_recompute():
    """The decode cache's sink-aware band mask must agree with the
    training forward's window+sinks mask token-for-token."""
    model = TransformerLM(BASE)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 5), 0, BASE.vocab_size)
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]
    got = generate(model, params, prompt, 8)
    tokens = prompt
    for _ in range(8):  # naive full-recompute oracle
        logits = model.apply({"params": params}, tokens)
        nxt = jnp.argmax(logits[:, -1].astype(jnp.float32), axis=-1)
        tokens = jnp.concatenate([tokens, nxt[:, None].astype(jnp.int32)], axis=1)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(tokens))


def test_sinks_model_differs_from_window_only():
    model = TransformerLM(BASE)
    tokens = jax.random.randint(jax.random.PRNGKey(2), (1, 30), 0, BASE.vocab_size)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    window_only = TransformerLM(
        dataclasses.replace(BASE, attention_sinks=0)
    )
    assert not np.allclose(
        np.asarray(model.apply({"params": params}, tokens)),
        np.asarray(window_only.apply({"params": params}, tokens)),
    )


ROLLING = dataclasses.replace(BASE, rolling_cache=True)


def test_rolling_with_sinks_matches_standard_within_max_seq():
    """The pinned-sink ring is a memory layout, not a semantics change:
    token-for-token (and logit-for-logit at prefill) equal to the
    standard full-length cache while everything fits."""
    from covalent_tpu_plugin.models.decode import _decode_model, init_cache

    model = TransformerLM(BASE)
    rolling = TransformerLM(ROLLING)
    for seed in (1, 2):
        prompt = jax.random.randint(
            jax.random.PRNGKey(seed), (2, 4), 0, BASE.vocab_size
        )
        params = model.init(jax.random.PRNGKey(0), prompt)["params"]
        std_logits, _ = _decode_model(model).apply(
            {"params": params, "cache": init_cache(model, 2)}, prompt,
            mutable=["cache"],
        )
        roll_logits, _ = _decode_model(rolling).apply(
            {"params": params, "cache": init_cache(rolling, 2)}, prompt,
            mutable=["cache"],
        )
        np.testing.assert_allclose(
            np.asarray(roll_logits), np.asarray(std_logits),
            atol=1e-5, rtol=1e-5,
        )
        want = generate(model, params, prompt, 20)  # wraps the band ring
        got = generate(rolling, params, prompt, 20)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_rolling_with_sinks_past_max_seq_and_pinned_slots():
    """Generation beyond max_seq at O(window + sinks) memory; the sink
    slots still hold absolute positions 0..sinks-1 after many wraps."""
    from covalent_tpu_plugin.models.decode import _decode_model, init_cache

    model = TransformerLM(ROLLING)
    prompt = jax.random.randint(jax.random.PRNGKey(3), (1, 5), 0, BASE.vocab_size)
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]
    n_new = BASE.max_seq + 10
    out = jax.jit(lambda p, t: generate(model, p, t, n_new))(params, prompt)
    assert out.shape == (1, 5 + n_new)
    arr = np.asarray(out)
    np.testing.assert_array_equal(arr[:, :5], np.asarray(prompt))
    assert (arr >= 0).all() and (arr < BASE.vocab_size).all()

    # Drive the raw decoder far past several wraps and inspect the ring.
    decoder = _decode_model(model)
    cache = init_cache(model, 1)
    token = prompt[:, :1]
    for step in range(20):
        _, mutated = decoder.apply(
            {"params": params, "cache": cache}, token, mutable=["cache"]
        )
        cache = mutated["cache"]
    slot_leaves = [
        leaf for path, leaf in jax.tree_util.tree_flatten_with_path(cache)[0]
        if any(getattr(e, "key", None) == "slot_positions" for e in path)
    ]
    assert slot_leaves
    sinks = BASE.attention_sinks
    for leaf in slot_leaves:
        flat = np.asarray(leaf).reshape(-1, leaf.shape[-1])
        for row in flat:
            # Pinned: first `sinks` slots hold absolute positions 0..s-1.
            np.testing.assert_array_equal(row[:sinks], np.arange(sinks))
            # Band region: positions from the recent window only.
            assert (row[sinks:] >= sinks).all()
    # Cache length really is window + sinks, not max_seq.
    k_leaves = [
        leaf for path, leaf in jax.tree_util.tree_flatten_with_path(cache)[0]
        if any(getattr(e, "key", None) == "cached_k" for e in path)
    ]
    assert all(
        leaf.shape[-3] == BASE.sliding_window + sinks for leaf in k_leaves
    )
