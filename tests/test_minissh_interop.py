"""Cross-interop: the vendored SSH2 stack against asyncssh, both roles.

The point of these tests is to prove ``transport/minissh.py`` speaks the
actual SSH protocol rather than a self-consistent private dialect: an
independent implementation (asyncssh) must kex, authenticate, and run
exec channels against it in BOTH directions.  The build sandbox has no
asyncssh (that absence is why minissh exists), so these skip there and
run in CI's interop job, which installs asyncssh
(``.github/workflows/tests.yml`` interop step).
"""

from __future__ import annotations

import asyncio

import pytest

asyncssh = pytest.importorskip("asyncssh")

from cryptography.hazmat.primitives import serialization  # noqa: E402
from cryptography.hazmat.primitives.asymmetric import ed25519  # noqa: E402

from covalent_tpu_plugin.transport import minissh  # noqa: E402


def run(coro):
    return asyncio.run(coro)


def test_asyncssh_client_against_minissh_server(tmp_path):
    """asyncssh (independent implementation) connects TO our server."""

    async def flow():
        server = await minissh.serve(users={"u": "pw"})
        try:
            conn = await asyncssh.connect(
                "127.0.0.1",
                port=server.port,
                username="u",
                password="pw",
                known_hosts=None,
                client_keys=None,
            )
            result = await conn.run("echo interop; exit 5")
            assert result.stdout == "interop\n"
            assert result.exit_status == 5
            conn.close()
            await conn.wait_closed()
        finally:
            server.close()
            await server.wait_closed()

    run(flow())


def test_minissh_client_against_asyncssh_server(tmp_path):
    """Our client connects TO an asyncssh-served sshd."""

    class Server(asyncssh.SSHServer):
        def begin_auth(self, username):
            return True

        def password_auth_supported(self):
            return True

        def validate_password(self, username, password):
            return username == "u" and password == "pw"

    def session_factory(process):
        process.stdout.write("from-asyncssh\n")
        process.exit(9)

    async def flow():
        host_key = asyncssh.generate_private_key("ssh-ed25519")
        server = await asyncssh.create_server(
            Server,
            "127.0.0.1",
            0,
            server_host_keys=[host_key],
            process_factory=session_factory,
        )
        port = server.sockets[0].getsockname()[1]
        try:
            conn = await minissh.connect(
                "127.0.0.1", port, "u", password="pw"
            )
            res = await conn.run("anything")
            assert res.stdout == "from-asyncssh\n"
            assert res.exit_status == 9
            conn.close()
            await conn.wait_closed()
        finally:
            server.close()
            await server.wait_closed()

    run(flow())


def test_publickey_interop_asyncssh_client(tmp_path):
    """asyncssh authenticates to our server with an ed25519 key written by
    the cryptography library — the full key-file format chain."""

    async def flow():
        key = ed25519.Ed25519PrivateKey.generate()
        key_path = tmp_path / "id_ed25519"
        key_path.write_bytes(
            key.private_bytes(
                serialization.Encoding.PEM,
                serialization.PrivateFormat.OpenSSH,
                serialization.NoEncryption(),
            )
        )
        server = await minissh.serve(authorized_keys=[key])
        try:
            conn = await asyncssh.connect(
                "127.0.0.1",
                port=server.port,
                username="bob",
                client_keys=[str(key_path)],
                known_hosts=None,
            )
            result = await conn.run("printf pk-interop")
            assert result.stdout == "pk-interop"
            assert result.exit_status == 0
            conn.close()
            await conn.wait_closed()
        finally:
            server.close()
            await server.wait_closed()

    run(flow())
