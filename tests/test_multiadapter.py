"""Multi-adapter LoRA serving: bank exactness, CAS registry, live attach.

The engine-level contract is EXACTNESS: a lane decoding under adapter X
inside the multiplexed bank must be bit-equal to a dedicated
single-adapter engine serving (base + X) alone — the bank gather is an
implementation detail, never a numeric one.  On top of that ride the
registry's wire form (pack/unpack + the content digest both sides of
the wire must agree on), the adapter-scoped prefix tree, the
quantize_then_lora refusal through a REAL ``open_session`` (PERMANENT,
one factory invocation — never a retry storm), and the live
``serve_attach`` path's fault classification.  The full control plane
(supervisor journal/replay, recovery re-attach) is covered in
``test_recovery.py``; the throughput claim in the bench's
``serve_multilora`` phase.
"""

import os
import pathlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from covalent_tpu_plugin.models import TransformerConfig, TransformerLM
from covalent_tpu_plugin.models import lora as lora_mod
from covalent_tpu_plugin.models.serve import (
    AdapterUnsupported,
    ContinuousEngine,
)
from covalent_tpu_plugin.resilience import FaultClass, classify_error
from covalent_tpu_plugin.serving import open_session
from covalent_tpu_plugin.serving.registry import (
    AdapterRegistry,
    adapter_content_digest,
    pack_adapter,
    unpack_adapter,
)
from covalent_tpu_plugin.serving.supervisor import ServeError

from .test_serving import make_serve_executor

CFG = TransformerConfig(
    vocab_size=64,
    d_model=32,
    n_layers=2,
    n_heads=2,
    d_ff=64,
    max_seq=64,
    dtype=jnp.float32,
    attention="reference",
    scan_layers=False,
)

#: One shared base model/params and LoRA template for the module (the
#: per-test init + trace dominates CPU wall otherwise).
_SHARED: dict = {}


def shared():
    if not _SHARED:
        model = TransformerLM(CFG)
        params = model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
        )["params"]
        _SHARED["model"], _SHARED["params"] = model, params
    return _SHARED["model"], _SHARED["params"]


def make_adapter(seed, rank=2):
    """A "fine-tuned" adapter: randomized nonzero lora_a AND lora_b
    (``add_lora``'s fresh B is zero — the identity), so the adapter
    genuinely changes the argmax."""
    model, params = shared()
    lmodel, filled = lora_mod.add_lora(model, params, rank=rank, alpha=16.0)
    mask = jax.tree_util.tree_leaves(lora_mod.lora_mask(filled))
    leaves, treedef = jax.tree_util.tree_flatten(filled)
    key = jax.random.PRNGKey(seed)
    out = []
    for leaf, m in zip(leaves, mask):
        if m:
            key, sub = jax.random.split(key)
            out.append(jax.random.normal(sub, leaf.shape, leaf.dtype) * 0.05)
        else:
            out.append(leaf)
    return lmodel, jax.tree_util.tree_unflatten(treedef, out)


def run_single(model, params, prompt, cap=8, **kw):
    engine = ContinuousEngine(
        model, params, max_batch=2, sync_steps=4,
        max_new_tokens=cap, length=48, **kw,
    )
    engine.admit("r", prompt)
    tokens: list = []
    for _ in range(200):
        for event in engine.step():
            tokens += event["tokens"]
            if event["done"]:
                engine.close()
                return tokens
    engine.close()
    return tokens


def drain(engine, streams):
    for _ in range(400):
        for event in engine.step():
            streams[event["rid"]] += event["tokens"]
        if not engine.busy:
            return streams
    raise AssertionError("engine never drained")


PROMPTS = [
    np.arange(1, 6, dtype=np.int32),
    np.arange(3, 10, dtype=np.int32),
    np.arange(2, 7, dtype=np.int32),
]


# ---------------------------------------------------------------------------
# Registry: the wire form both sides of serve_attach must agree on
# ---------------------------------------------------------------------------


def test_registry_pack_unpack_roundtrip(tmp_path):
    leaves = [
        np.arange(8, dtype=np.float32).reshape(2, 4),
        np.ones((4, 2), dtype=np.float32),
    ]
    data = pack_adapter(leaves, name="fr", rank=4, alpha=8.0)
    bundle = unpack_adapter(data)
    assert bundle["name"] == "fr"
    assert bundle["rank"] == 4 and bundle["alpha"] == 8.0
    assert bundle["digest"] == adapter_content_digest(leaves)
    for got, want in zip(bundle["leaves"], leaves):
        np.testing.assert_array_equal(got, want)


def test_registry_digest_matches_jax_side():
    """The numpy-side content digest (registry, journal, scheduler
    affinity) must be bit-identical to the jax-side one the engine
    computes (``models.lora.adapter_digest``) — a drift here would make
    every recovered adapter look stale."""
    _, tuned = make_adapter(3)
    leaves = lora_mod.adapter_leaves(tuned)
    assert adapter_content_digest(leaves) == lora_mod.adapter_digest(leaves)


def test_registry_put_get_remove(tmp_path):
    registry = AdapterRegistry(str(tmp_path))
    leaves = [np.ones((2, 3), dtype=np.float32)]
    record = registry.put("fr", leaves)
    assert record["name"] == "fr" and record["digest"]
    assert record["content"] == adapter_content_digest(leaves)
    assert "fr" in registry and registry.get("fr")["path"] == record["path"]
    # Re-registering the same leaves keeps the same CONTENT identity
    # (the file digest may differ — bundle metadata like the embedded
    # name is part of the pickled bytes, not of the semantic identity).
    again = registry.put("fr", pack_adapter(leaves))
    assert again["content"] == record["content"]
    registry.remove("fr")
    assert "fr" not in registry
    with pytest.raises(ValueError):
        registry.put("bad", object())


# ---------------------------------------------------------------------------
# Engine: multiplexed lanes bit-equal to single-adapter oracles
# ---------------------------------------------------------------------------


def test_bank_lanes_bit_equal_single_adapter_engines():
    """Base lane + two adapter lanes co-batched in ONE bank engine must
    each match the dedicated engine for that (base|adapter) alone, and
    an unknown adapter name must refuse at admission — PERMANENT, no
    lane consumed."""
    model, params = shared()
    lmodel, tuned_a = make_adapter(1)
    _, tuned_b = make_adapter(2)
    oracle_base = run_single(model, params, PROMPTS[0])
    oracle_a = run_single(lmodel, tuned_a, PROMPTS[1])
    oracle_b = run_single(lmodel, tuned_b, PROMPTS[2])

    mux = ContinuousEngine(
        model, params, max_batch=4, sync_steps=4, max_new_tokens=8,
        length=48,
        adapters={
            "a": lora_mod.adapter_leaves(tuned_a),
            "b": lora_mod.adapter_leaves(tuned_b),
        },
    )
    assert mux.adapters == ("a", "b")
    mux.admit("base", PROMPTS[0], {})
    mux.admit("a", PROMPTS[1], {"adapter": "a"})
    mux.admit("b", PROMPTS[2], {"adapter": "b"})
    streams = drain(mux, {"base": [], "a": [], "b": []})
    assert streams["base"] == oracle_base
    assert streams["a"] == oracle_a
    assert streams["b"] == oracle_b

    with pytest.raises(ValueError) as info:
        mux.admit("x", PROMPTS[0], {"adapter": "ghost"})
    fault, _ = classify_error(info.value)
    assert fault is FaultClass.PERMANENT
    assert mux.busy == 0
    mux.close()


def test_hot_swap_in_flight_old_generation_new_admissions_new():
    """Re-attaching a live name mid-decode is the zero-drop hot swap:
    the in-flight lane finishes on the OLD generation byte-equal, the
    next admission decodes the NEW one."""
    model, params = shared()
    lmodel, tuned_a = make_adapter(1)
    _, tuned_a2 = make_adapter(7)
    oracle_old = run_single(lmodel, tuned_a, PROMPTS[1])
    oracle_new = run_single(lmodel, tuned_a2, PROMPTS[1])

    mux = ContinuousEngine(
        model, params, max_batch=4, sync_steps=4, max_new_tokens=8,
        length=48, adapters={"a": lora_mod.adapter_leaves(tuned_a)},
    )
    mux.admit("old", PROMPTS[1], {"adapter": "a"})
    streams = {"old": [], "new": []}
    for _ in range(2):
        for event in mux.step():
            streams[event["rid"]] += event["tokens"]
    mux.attach_adapter("a", lora_mod.adapter_leaves(tuned_a2))
    mux.admit("new", PROMPTS[1], {"adapter": "a"})
    drain(mux, streams)
    assert streams["old"] == oracle_old
    assert streams["new"] == oracle_new
    assert mux.stats["adapter_swaps"] == 1
    mux.close()


def test_prefix_tree_scoped_by_adapter():
    """The SAME prompt under two adapters must never share a KV lane:
    the cross-adapter reuse is blocked (counted), and the blocked
    admission full-prefills byte-equal."""
    model, params = shared()
    lmodel, tuned_a = make_adapter(1)
    _, tuned_b = make_adapter(2)
    long_prompt = np.arange(1, 12, dtype=np.int32)
    oracle_b = run_single(lmodel, tuned_b, long_prompt, cap=6)

    mux = ContinuousEngine(
        model, params, max_batch=2, sync_steps=4, max_new_tokens=6,
        length=48, prefix_min_tokens=3,
        adapters={
            "a": lora_mod.adapter_leaves(tuned_a),
            "b": lora_mod.adapter_leaves(tuned_b),
        },
    )
    mux.admit("pa", long_prompt, {"adapter": "a"})
    drain(mux, {"pa": []})
    mux.admit("pb", long_prompt, {"adapter": "b"})
    streams = drain(mux, {"pb": []})
    assert mux.stats["adapter_prefix_blocked"] >= 1
    assert streams["pb"] == oracle_b
    mux.close()


def test_kv_bundle_carries_adapter_identity():
    """A disagg KV bundle prefilled under adapter X admits only into an
    engine whose X generation matches; the decoded stream equals the
    single-adapter oracle."""
    model, params = shared()
    lmodel, tuned_a = make_adapter(1)
    prompt = np.arange(4, 11, dtype=np.int32)
    oracle = run_single(lmodel, tuned_a, prompt, cap=6)

    mux = ContinuousEngine(
        model, params, max_batch=2, sync_steps=4, max_new_tokens=6,
        length=48, adapters={"a": lora_mod.adapter_leaves(tuned_a)},
    )
    bundle = mux.prefill_only(prompt, {"adapter": "a"})
    mux.admit_from_kv("kv1", bundle, {"adapter": "a"})
    streams = drain(mux, {"kv1": []})
    assert streams["kv1"] == oracle
    mux.close()


# ---------------------------------------------------------------------------
# The quantize_then_lora refusal through a REAL open_session
# ---------------------------------------------------------------------------


def make_uncomposable_factory(marker_path):
    """A factory violating the quant.py:229 composition order — the
    model already carries baked-in adapters (lora_rank on the config),
    and an adapter bank on top is refused by the REAL engine
    (``AdapterUnsupported``).  Appends to ``marker_path`` per
    invocation so the test can prove the refusal never retry-storms."""

    def factory():
        with open(marker_path, "a") as f:
            f.write("invoked\n")
        import jax as jax_mod
        import jax.numpy as jnp_mod

        from covalent_tpu_plugin.models import (
            TransformerConfig as Config,
            TransformerLM as LM,
        )
        from covalent_tpu_plugin.models.serve import (
            ContinuousEngine as Engine,
        )

        cfg = Config(
            vocab_size=32, d_model=16, n_layers=1, n_heads=2, d_ff=32,
            max_seq=32, dtype=jnp_mod.float32, attention="reference",
            scan_layers=False, lora_rank=2,
        )
        model = LM(cfg)
        params = model.init(
            jax_mod.random.PRNGKey(0), jnp_mod.zeros((1, 4), jnp_mod.int32)
        )["params"]
        return Engine(
            model, params, max_batch=2, max_new_tokens=4, length=16,
            adapter_rank=2,
        )

    return factory


@pytest.mark.slow
def test_open_session_refuses_uncomposable_adapter_stack(
    tmp_path, run_async
):
    """An engine construction that violates quantize_then_lora order
    refuses through a real ``open_session`` as PERMANENT
    (``serve_model_unsupported``) after exactly ONE factory invocation
    — a deterministic misconfiguration must never burn gang retries."""
    marker = tmp_path / "invocations.log"
    marker.write_text("")
    repo_root = str(pathlib.Path(__file__).parents[1])

    async def flow():
        # The factory imports the real package in the worker (stub
        # factories deliberately avoid this), so the worker needs the
        # repo on its path.
        ex = make_serve_executor(
            tmp_path,
            task_env={
                "PYTHONPATH": repo_root + os.pathsep
                + os.environ.get("PYTHONPATH", "")
            },
        )
        try:
            with pytest.raises(Exception) as info:
                await open_session(
                    ex, make_uncomposable_factory(str(marker))
                )
        finally:
            await ex.close()
        return info.value

    error = run_async(flow())
    fault, label = classify_error(error)
    assert fault is FaultClass.PERMANENT
    assert label == "serve_model_unsupported"
    assert marker.read_text().count("invoked") == 1


# ---------------------------------------------------------------------------
# Live serve_attach fault classification through a real session
# ---------------------------------------------------------------------------


def make_bank_stub_factory():
    """Closure-local stub with the duck-typed adapter surface: attach
    refuses geometry mismatches exactly the way the real bank does
    (``fault_label``/``fault_transient`` PERMANENT duck tags)."""

    def factory():
        class Refused(ValueError):
            fault_label = "serve_model_unsupported"
            fault_transient = False

        class Engine:
            def __init__(self):
                self.slots = 2
                self.lanes = {}
                self.book = {}

            def attach_adapter(self, name, payload):
                rank = int(payload.get("rank") or 0)
                if rank != 2:
                    raise Refused(
                        f"adapter {name!r} rank {rank} does not match "
                        "the bank template rank 2"
                    )
                self.book[name] = str(payload["digest"])
                return payload["digest"]

            def detach_adapter(self, name):
                if name not in self.book:
                    raise ValueError(f"unknown adapter {name!r}")
                del self.book[name]

            @property
            def adapter_digests(self):
                return dict(self.book)

            def admit(self, rid, prompt, params):
                cap = int((params or {}).get("max_new_tokens", 4))
                base = int(prompt[-1])
                self.lanes[rid] = [base + i + 1 for i in range(cap)]

            def step(self):
                events = []
                for rid in list(self.lanes):
                    taken, self.lanes[rid] = (
                        self.lanes[rid][:2], self.lanes[rid][2:]
                    )
                    done = not self.lanes[rid]
                    if done:
                        del self.lanes[rid]
                    events.append(
                        {"rid": rid, "tokens": taken, "done": done}
                    )
                return events

            def cancel(self, rid):
                self.lanes.pop(rid, None)

        return Engine()

    return factory


def test_live_attach_geometry_refusal_is_permanent(tmp_path, run_async):
    """A rank-mismatched bundle through the live ``serve_attach`` verb
    refuses as PERMANENT with the engine's own label; a well-formed one
    lands, shows in the handle's book, and detaches cleanly."""

    async def flow():
        ex = make_serve_executor(tmp_path)
        try:
            handle = await open_session(ex, make_bank_stub_factory())
            good = [np.zeros((4, 2), dtype=np.float32)]
            ack = await handle.attach_adapter("ok", payload=good)
            assert "ok" in handle.adapters
            with pytest.raises(ServeError) as info:
                await handle.attach_adapter(
                    "bad", payload=[np.zeros((4, 3), dtype=np.float32)]
                )
            assert "bad" not in handle.adapters
            await handle.detach_adapter("ok")
            assert "ok" not in handle.adapters
            await handle.close()
        finally:
            await ex.close()
        return ack, info.value

    ack, error = run_async(flow())
    assert ack.get("digest")
    fault, label = classify_error(error)
    assert fault is FaultClass.PERMANENT
    assert label == "serve_model_unsupported"
