"""Closed-loop predictive autoscaling (ISSUE 15).

Controller decisions run against stub pools/sets under a fake clock —
every cooldown, dwell, and TTL is reachable without sleeping — while the
end-to-end tier drives a REAL replica set over the local transport
through scale-to-zero and demand re-warm, asserting the streams stay
exactly-once across the suspension.
"""

from __future__ import annotations

import asyncio

import pytest

from covalent_tpu_plugin.fleet import (
    AutoscaleController,
    LocalPoolAutoscaler,
    PoolRegistry,
    PoolSpec,
    ReplicaSetPolicy,
)
from covalent_tpu_plugin.obs.history import MetricsHistory
from covalent_tpu_plugin.obs.metrics import Registry


# ---------------------------------------------------------------------------
# history: trend/slope queries (satellite)
# ---------------------------------------------------------------------------


def make_history(clock):
    registry = Registry()
    history = MetricsHistory(
        registry=registry, interval_s=1.0, capacity=64, clock=clock
    )
    return registry, history


def test_trend_gauge_slope_under_fake_clock():
    now = [1000.0]
    registry, history = make_history(lambda: now[0])
    depth = registry.gauge("queue_depth", "", ("tenant",))
    for value in (0, 2, 4, 6, 8):
        depth.labels(tenant="a").set(value)
        history.sample(force=True)
        now[0] += 1.0
    view = history.query("queue_depth", window_s=10.0, agg="trend")
    assert view["agg"] == "trend"
    series = view["series"]['{"tenant": "a"}']
    # 2 units per second, fit exactly by least squares.
    assert series["slope_per_s"] == pytest.approx(2.0)
    assert series["last"] == 8.0


def test_trend_counter_reports_rate_slope():
    now = [0.0]
    registry, history = make_history(lambda: now[0])
    total = registry.counter("reqs_total", "")
    # Rate accelerates 1/s -> 2/s -> 3/s -> 4/s: slope of the RATE is
    # +1 per second, even though the value slope is much larger.
    value = 0.0
    for rate in (0, 1, 2, 3, 4):
        value += rate
        total.inc(rate)
        history.sample(force=True)
        now[0] += 1.0
    view = history.query("reqs_total", window_s=10.0, agg="trend")
    series = view["series"][""]
    assert series["slope_per_s"] == pytest.approx(1.0)
    assert series["increase"] == pytest.approx(10.0)


def test_trend_flat_and_sparse_series_have_zero_slope():
    now = [0.0]
    registry, history = make_history(lambda: now[0])
    gauge = registry.gauge("flat", "")
    gauge.set(5.0)
    history.sample(force=True)
    view = history.query("flat", window_s=10.0, agg="trend")
    # One point has no trend; a constant series has slope 0.
    assert view["series"][""]["slope_per_s"] == 0.0
    now[0] += 1.0
    gauge.set(5.0)
    history.sample(force=True)
    view = history.query("flat", window_s=10.0, agg="trend")
    assert view["series"][""]["slope_per_s"] == 0.0


def test_trend_counter_reset_skips_torn_interval():
    now = [0.0]
    registry, history = make_history(lambda: now[0])
    total = registry.counter("resets_total", "")
    total.inc(10)
    history.sample(force=True)
    now[0] += 1.0
    # Simulate a registry reset: new child starts from zero.
    registry.unregister("resets_total")
    total = registry.counter("resets_total", "")
    total.inc(1)
    history.sample(force=True)
    now[0] += 1.0
    total.inc(1)
    history.sample(force=True)
    view = history.query("resets_total", window_s=10.0, agg="trend")
    series = view["series"][""]
    # The 10 -> 1 drop is a reset, not a negative burst.
    assert series["increase"] == pytest.approx(1.0)


# ---------------------------------------------------------------------------
# LocalPoolAutoscaler: anti-thrash cooldown (satellite)
# ---------------------------------------------------------------------------


def test_local_autoscaler_cooldown_suppresses_thrash():
    """Repeated high/low watermark crossings inside the dwell resize
    once, not once per crossing (the PR-7 hook thrashed on consecutive
    pump ticks)."""
    now = [0.0]
    registry = PoolRegistry()
    registry.register(
        PoolSpec(name="p", capacity=2, transport="local"), executor=object()
    )
    scaler = LocalPoolAutoscaler(
        "p", step=1, max_capacity=8, min_capacity=1,
        cooldown_s=10.0, clock=lambda: now[0],
    )
    scaler.on_high(10, registry)
    assert registry.get("p").capacity == 3
    # Flapping crossings 1s apart: all suppressed inside the dwell.
    for _ in range(3):
        now[0] += 1.0
        scaler.on_low(0, registry)
        now[0] += 1.0
        scaler.on_high(10, registry)
    assert registry.get("p").capacity == 3
    assert scaler.scale_ups == 1 and scaler.scale_downs == 0
    assert scaler.suppressed == 6
    # Past the dwell the next crossing acts again.
    now[0] += 10.0
    scaler.on_low(0, registry)
    assert registry.get("p").capacity == 2
    assert scaler.scale_downs == 1


# ---------------------------------------------------------------------------
# Controller stubs
# ---------------------------------------------------------------------------


class StubHistory:
    """query(agg='trend') answered from canned slopes.

    A plain float lands on the unlabelled series; a dict maps the JSON
    series key (as the real ring produces) to its slope, for tests of
    the controller's label filtering.
    """

    def __init__(self):
        self.slopes: dict = {}

    def query(self, metric, window_s=60.0, labels=None, agg=""):
        spec = self.slopes.get(metric, 0.0)
        if isinstance(spec, dict):
            return {
                "series": {
                    key: {"slope_per_s": value}
                    for key, value in spec.items()
                }
            }
        return {"series": {"": {"slope_per_s": spec}}}


class StubQueue:
    depth = 0


class StubScheduler:
    def __init__(self, registry):
        self.registry = registry
        self.queue = StubQueue()


class StubGang:
    """Pool-side executor stub with warmth + teardown/prewarm hooks."""

    def __init__(self, warm=True):
        self.warm = warm
        self.teardowns = 0
        self.prewarms = 0

    @property
    def is_warm(self):
        return self.warm

    def serve_sessions(self):
        return {}

    async def teardown_gang(self):
        self.warm = False
        self.teardowns += 1
        return True

    async def prewarm(self):
        self.warm = True
        self.prewarms += 1
        return True


class StubEngine:
    def __init__(self):
        self.hooks = []
        self.view = {"slos": {}}

    def add_alert_hook(self, hook):
        self.hooks.append(hook)

    def status(self):
        return self.view

    def burn(self, name, metric):
        self.view["slos"][name] = {"state": "burning", "metric": metric}

    def recover(self, name):
        self.view["slos"][name] = {"state": "ok", "metric": ""}


class StubSet:
    def __init__(self, name="s", replicas=1, slots_per=2):
        self.name = name
        self.slots_per = slots_per
        self._live = replicas
        self.in_flight = 0
        self.queued = 0
        self.state = "open"
        self.prefer_stable = False
        self._suspended = False
        self.scaled: list[int] = []

    @property
    def live_replicas(self):
        return self._live

    @property
    def suspended(self):
        return self._suspended and self._live == 0

    @property
    def decode_slots(self):
        return self._live * self.slots_per

    async def scale_to(self, n):
        self.scaled.append(n)
        self._suspended = n == 0
        self._live = n
        return n

    def rewarm(self, replicas=1):
        """What the request path does on first demand after suspension."""
        self._suspended = False
        self._live = replicas


def make_controller(clock, registry=None, engine=None, **kwargs):
    history = StubHistory()
    scheduler = (
        StubScheduler(registry) if registry is not None else None
    )
    defaults = dict(
        interval_s=1.0,
        up_cooldown_s=3.0,
        down_cooldown_s=10.0,
        idle_ttl_s=20.0,
        lead_s=2.0,
        clock=clock,
    )
    defaults.update(kwargs)
    controller = AutoscaleController(
        scheduler=scheduler,
        registry=registry,
        history=history,
        slo_engine=engine,
        **defaults,
    )
    return controller, history


def spot_and_stable_registry():
    registry = PoolRegistry()
    gangs = {"spot": StubGang(warm=True), "stable": StubGang(warm=True)}
    registry.register(
        PoolSpec(name="spot", capacity=1, transport="local",
                 preemptible=True),
        executor=gangs["spot"],
    )
    registry.register(
        PoolSpec(name="stable", capacity=1, transport="local"),
        executor=gangs["stable"],
    )
    return registry, gangs


# ---------------------------------------------------------------------------
# Controller: predictive pool scaling
# ---------------------------------------------------------------------------


def test_pool_scale_up_is_predictive_from_queue_trend(run_async):
    """Zero backlog + a rising queue-depth trend scales capacity BEFORE
    demand arrives: predicted = depth + slope * measured lead."""
    now = [0.0]
    registry, _gangs = spot_and_stable_registry()
    controller, history = make_controller(lambda: now[0], registry)
    controller.manage_pool("spot", max_capacity=4)
    controller.manage_pool("stable", max_capacity=4)

    async def go():
        decisions = await controller.tick()
        assert decisions == []  # flat trend, no demand
        # Queue depth rising 2 items/s; lead 2s -> predicted backlog 4.
        history.slopes["covalent_tpu_queue_depth"] = 2.0
        return await controller.tick()

    decisions = run_async(go())
    ups = [d for d in decisions if d["action"] == "pool_up"]
    assert ups and ups[0]["reason"] == "queue_trend"
    # Batch overflow lands on the SPOT pool first (stable stays free for
    # SLO-critical serving).
    assert ups[0]["resource"] == "spot"
    assert registry.get("spot").capacity == 2


def test_pool_scale_up_and_down_hysteresis_no_flap(run_async):
    """Oscillating demand moves capacity at most once per dwell; the
    sustained-below requirement resets on every spike."""
    now = [0.0]
    registry, _gangs = spot_and_stable_registry()
    controller, history = make_controller(
        lambda: now[0], registry, down_cooldown_s=10.0
    )
    controller.manage_pool("spot", max_capacity=4)

    async def go():
        actions = []
        # 20 ticks of demand flapping high/low every second.
        for tick in range(20):
            history.slopes["covalent_tpu_queue_depth"] = (
                2.0 if tick % 2 == 0 else 0.0
            )
            for decision in await controller.tick():
                actions.append(decision["action"])
            now[0] += 1.0
        return actions

    actions = run_async(go())
    # Up-moves ratchet toward the peak, bounded by the up-cooldown (one
    # step per dwell, never one per spike), and the flapping never
    # produces a single scale-down: the sustained-below requirement
    # re-arms on every spike, so capacity cannot see-saw tick to tick.
    assert 1 <= actions.count("pool_up") <= 3
    assert actions.count("pool_down") == 0
    assert 2 <= registry.get("spot").capacity <= 4


def test_pool_scale_down_after_sustained_quiet(run_async):
    now = [0.0]
    registry, _gangs = spot_and_stable_registry()
    controller, history = make_controller(
        lambda: now[0], registry, down_cooldown_s=10.0, idle_ttl_s=0.0
    )
    controller.manage_pool("spot", min_capacity=1, max_capacity=4)

    async def go():
        history.slopes["covalent_tpu_queue_depth"] = 3.0
        await controller.tick()  # scale up to 2
        assert registry.get("spot").capacity == 2
        history.slopes["covalent_tpu_queue_depth"] = 0.0
        actions = []
        for _ in range(25):
            now[0] += 1.0
            for decision in await controller.tick():
                actions.append(decision["action"])
        return actions

    actions = run_async(go())
    assert "pool_down" in actions
    assert registry.get("spot").capacity == 1


def test_dispatch_burn_forces_pool_scale_up(run_async):
    now = [0.0]
    registry, _gangs = spot_and_stable_registry()
    engine = StubEngine()
    controller, _history = make_controller(
        lambda: now[0], registry, engine=engine
    )
    controller.manage_pool("stable", max_capacity=4)

    async def go():
        engine.burn("queue_wait", "covalent_tpu_wall_overhead_seconds")
        return await controller.tick()

    decisions = run_async(go())
    ups = [d for d in decisions if d["action"] == "pool_up"]
    assert ups and ups[0]["reason"] == "slo_burn"


# ---------------------------------------------------------------------------
# Controller: pool scale-to-zero + predictive re-warm
# ---------------------------------------------------------------------------


def test_idle_pool_gang_torn_down_after_ttl_and_prewarmed_on_trend(run_async):
    now = [0.0]
    registry, gangs = spot_and_stable_registry()
    controller, history = make_controller(
        lambda: now[0], registry, idle_ttl_s=20.0
    )
    controller.manage_pool("stable", max_capacity=4)

    async def go():
        await controller.tick()  # arms idle_since
        now[0] += 19.0
        assert not any(
            d["action"] == "gang_teardown" for d in await controller.tick()
        )
        now[0] += 2.0
        teardown = await controller.tick()
        assert any(d["action"] == "gang_teardown" for d in teardown)
        assert gangs["stable"].teardowns == 1
        assert not registry.get("stable").warm
        # Demand trends back in: the controller pays the cold start NOW
        # (predictive prewarm), not when placement already needs it.
        history.slopes["covalent_tpu_queue_depth"] = 1.0
        rewarm = await controller.tick()
        assert any(d["action"] == "prewarm" for d in rewarm)
        await asyncio.sleep(0)  # let the detached prewarm task run
        assert gangs["stable"].prewarms == 1

    run_async(go())


def test_busy_pool_never_torn_down(run_async):
    now = [0.0]
    registry, gangs = spot_and_stable_registry()
    controller, _history = make_controller(
        lambda: now[0], registry, idle_ttl_s=5.0
    )
    controller.manage_pool("stable")
    registry.get("stable").place()  # one slot in use

    async def go():
        for _ in range(10):
            now[0] += 5.0
            for decision in await controller.tick():
                assert decision["action"] != "gang_teardown"
        assert gangs["stable"].teardowns == 0

    run_async(go())


# ---------------------------------------------------------------------------
# Controller: replica sets
# ---------------------------------------------------------------------------


def test_set_scale_up_from_load_and_burn_override(run_async):
    now = [0.0]
    engine = StubEngine()
    controller, history = make_controller(lambda: now[0], engine=engine)
    rset = StubSet(replicas=1, slots_per=2)
    controller.manage_replica_set(rset, max_replicas=4)
    assert rset.prefer_stable is True  # SLO-critical pins to stable

    async def go():
        # Load within capacity: nothing happens.
        rset.in_flight = 1
        assert await controller.tick() == []
        # Load past the utilization target: proportional scale-up.
        rset.in_flight = 6
        decisions = await controller.tick()
        assert [d["action"] for d in decisions] == ["set_up"]
        assert decisions[0]["reason"] == "load_trend"
        assert rset.scaled[-1] == 4  # ceil(6 / (2 * 0.75)) = 4
        # A burning serving SLO forces growth even with load back down.
        rset2 = StubSet(name="s2", replicas=1)
        controller.manage_replica_set(rset2, max_replicas=3)
        engine.burn("serve_p95", "covalent_tpu_serve_request_seconds")
        now[0] += 5.0
        decisions = await controller.tick()
        burn_ups = [
            d for d in decisions
            if d["action"] == "set_up" and d["resource"] == "s2"
        ]
        assert burn_ups and burn_ups[0]["reason"] == "slo_burn"
        assert rset2.scaled[-1] == 2

    run_async(go())


def test_set_scale_up_is_predictive_from_in_flight_trend(run_async):
    now = [0.0]
    controller, history = make_controller(lambda: now[0])
    rset = StubSet(replicas=1, slots_per=2)
    controller.manage_replica_set(rset, max_replicas=4)

    async def go():
        rset.in_flight = 1  # half the slots: fine today
        history.slopes["covalent_tpu_serve_replica_in_flight"] = {
            '{"replica": "r0", "set": "s"}': 1.5,
            # A DIFFERENT set's rising trend must not leak in.
            '{"replica": "r0", "set": "other"}': 50.0,
        }
        decisions = await controller.tick()
        # predicted = 1 + 1.5 * 2s lead = 4 -> ceil(4 / 1.5) = 3
        assert [d["action"] for d in decisions] == ["set_up"]
        assert rset.scaled[-1] == 3

    run_async(go())


def test_set_scale_down_requires_sustained_low_and_no_burn(run_async):
    now = [0.0]
    engine = StubEngine()
    controller, _history = make_controller(
        lambda: now[0], engine=engine, down_cooldown_s=10.0,
        idle_ttl_s=0.0,
    )
    rset = StubSet(replicas=3, slots_per=2)
    # max_replicas == live: the burn override has no headroom to grow
    # into, isolating the scale-DOWN veto under test.
    controller.manage_replica_set(rset, min_replicas=1, max_replicas=3)

    async def go():
        rset.in_flight = 0
        # While a serving SLO burns, scale-down is vetoed outright.
        engine.burn("serve_p95", "covalent_tpu_serve_request_seconds")
        for _ in range(15):
            now[0] += 1.0
            assert await controller.tick() == []
        assert rset.scaled == []
        # Burn clears: the dwell starts NOW; one step down per dwell.
        engine.recover("serve_p95")
        actions = []
        for _ in range(12):
            now[0] += 1.0
            actions += [d["action"] for d in await controller.tick()]
        assert actions.count("set_down") == 1
        assert rset.scaled[-1] == 2

    run_async(go())


def test_set_scale_to_zero_after_idle_ttl_and_resume_decision(run_async):
    now = [0.0]
    controller, _history = make_controller(
        lambda: now[0], idle_ttl_s=20.0, down_cooldown_s=5.0
    )
    rset = StubSet(replicas=1, slots_per=2)
    controller.manage_replica_set(
        rset, min_replicas=0, max_replicas=3, slo_critical=False
    )

    async def go():
        rset.in_flight = 0
        await controller.tick()  # arms idle_since
        now[0] += 21.0
        decisions = await controller.tick()
        assert [d["action"] for d in decisions] == ["set_suspend"]
        assert rset.scaled[-1] == 0 and rset.suspended
        # Idle set stays suspended tick after tick.
        now[0] += 5.0
        assert await controller.tick() == []
        # First demand re-warms through the SET's request path; the
        # controller observes and records the resume.
        rset.rewarm(replicas=1)
        now[0] += 1.0
        decisions = await controller.tick()
        assert any(d["action"] == "set_resume" for d in decisions)

    run_async(go())


def test_controller_status_and_decision_counter(run_async):
    from covalent_tpu_plugin.fleet.autoscale import (
        AUTOSCALE_DECISIONS_TOTAL,
    )

    now = [0.0]
    registry, _gangs = spot_and_stable_registry()
    engine = StubEngine()
    controller, history = make_controller(
        lambda: now[0], registry, engine=engine
    )
    controller.manage_pool("spot", max_capacity=4)
    rset = StubSet(replicas=1)
    controller.manage_replica_set(rset, max_replicas=2)
    before = AUTOSCALE_DECISIONS_TOTAL.labels(action="pool_up").value

    async def go():
        history.slopes["covalent_tpu_queue_depth"] = 5.0
        await controller.tick()

    run_async(go())
    assert (
        AUTOSCALE_DECISIONS_TOTAL.labels(action="pool_up").value
        == before + 1
    )
    status = controller.status()
    assert status["pools"]["spot"]["capacity"] == 2
    assert status["pools"]["spot"]["lead_s"] == pytest.approx(2.0)
    assert "since_up_s" in status["pools"]["spot"]["cooldown"]
    assert status["sets"]["s"]["replicas"] == 1
    assert status["sets"]["s"]["slo_critical"] is True
    assert status["decision_counts"].get("pool_up", 0) >= 1
    assert any(
        d["action"] == "pool_up" for d in status["decisions"]
    )


def test_measured_prewarm_lead_time():
    """With no override, the lead comes from the per-pool prewarm
    histogram mean, clamped into [interval, max_lead]."""
    from covalent_tpu_plugin.tpu import _PREWARM_SECONDS

    now = [0.0]
    registry, _gangs = spot_and_stable_registry()
    controller, _history = make_controller(
        lambda: now[0], registry, lead_s=0.0
    )
    controller.lead_override_s = 0.0
    _PREWARM_SECONDS.labels(pool="stable").observe(4.0)
    _PREWARM_SECONDS.labels(pool="stable").observe(6.0)
    assert controller._lead_for("stable") == pytest.approx(5.0)
    # A pool with no measurements of its own rides the all-pools mean
    # (other tests may have observed pool="" in this process, so only
    # the clamp bounds are exact here).
    assert 1.0 <= controller._lead_for("spot") <= 30.0


def test_slo_alert_hook_wakes_controller(run_async):
    """The alert-hook path (engine thread) records the burn and the next
    tick acts on it without waiting for a status refresh."""
    now = [0.0]
    engine = StubEngine()
    controller, _history = make_controller(lambda: now[0], engine=engine)
    rset = StubSet(replicas=1)
    controller.manage_replica_set(rset, max_replicas=2)
    assert engine.hooks, "controller never subscribed an alert hook"

    async def go():
        engine.hooks[0](
            "serve_p95", "burning",
            {"metric": "covalent_tpu_serve_request_seconds"},
        )
        decisions = await controller.tick()
        assert any(d["action"] == "set_up" for d in decisions)
        # Recovery through the hook clears the veto state too.
        engine.hooks[0]("serve_p95", "ok", {"metric": ""})
        assert "serve_p95" not in controller._burning

    run_async(go())


# ---------------------------------------------------------------------------
# ReplicaSet: prefer_stable placement (SLO-driven pinning)
# ---------------------------------------------------------------------------


def test_replica_placement_prefers_stable_pools_when_pinned():
    from covalent_tpu_plugin.serving.replicas import ReplicaSet

    registry = PoolRegistry()
    spot = registry.register(
        PoolSpec(name="spot", capacity=4, transport="local",
                 preemptible=True),
        executor=StubGang(warm=True),
    )
    stable = registry.register(
        PoolSpec(name="stable", capacity=4, transport="local"),
        executor=StubGang(warm=False),  # colder AND stable must still win
    )
    rset = ReplicaSet([spot, stable], lambda: None, prefer_stable=True)
    ranked = rset._rank_targets()
    assert ranked[0][1] is stable
    rset_unpinned = ReplicaSet([spot, stable], lambda: None)
    # Without the pin, the warm spot pool ranks first (warmth wins).
    assert rset_unpinned._rank_targets()[0][1] is spot


# ---------------------------------------------------------------------------
# Scale-to-zero end to end: a REAL replica set over the local transport
# ---------------------------------------------------------------------------


def expected_stream(seed: int, cap: int = 6) -> list[int]:
    # test_serving.make_factory streams base+1..base+cap for prompt
    # [..., base].
    return [seed + j + 1 for j in range(cap)]


def test_scale_to_zero_rewarns_on_demand_exactly_once(run_async, tmp_path):
    """An idle set scaled to zero re-warms on the next request: the
    stream is byte-exact (no duplicate, no hole), the set reports
    suspended in between, and a SECOND round-trip proves the resumed
    set serves normally."""
    from covalent_tpu_plugin.serving import open_replica_set

    from .helpers import make_local_executor
    from .test_serving import make_factory

    async def go():
        ex = make_local_executor(
            tmp_path, use_agent="pool", heartbeat_interval=0.0,
            prewarm=False,
        )
        try:
            rset = await open_replica_set(
                [ex], make_factory(step_delay=0.01), name="s2z",
                stats_interval_s=0.1,
            )
            first = await rset.request(
                [1], params={"max_new_tokens": 6}
            )
            assert await first.result(timeout=30) == expected_stream(1)
            assert await rset.scale_to(0) == 0
            assert rset.suspended and rset.state == "suspended"
            assert rset.live_replicas == 0
            status = rset.status()
            assert status["suspended"] is True
            # First demand re-warms transparently; the stream is the
            # exact expected token sequence (exactly-once across the
            # suspension boundary).
            second = await rset.request(
                [2], params={"max_new_tokens": 6}
            )
            assert await second.result(timeout=60) == expected_stream(2)
            assert not rset.suspended and rset.live_replicas == 1
            third = await rset.request(
                [3], params={"max_new_tokens": 6}
            )
            assert await third.result(timeout=30) == expected_stream(3)
            assert rset.served >= 2  # post-resume replica's own count
            await rset.close()
        finally:
            await ex.close()

    run_async(go())


def test_request_racing_scale_to_zero_is_not_dropped(run_async, tmp_path):
    """A request arriving while scale_to(0) is mid-drain queues behind
    the scale lock, re-warms the set, and completes with its exact
    stream — never an error, never a drop."""
    from covalent_tpu_plugin.serving import open_replica_set

    from .helpers import make_local_executor
    from .test_serving import make_factory

    async def go():
        ex = make_local_executor(
            tmp_path, use_agent="pool", heartbeat_interval=0.0,
            prewarm=False,
        )
        try:
            rset = await open_replica_set(
                [ex], make_factory(step_delay=0.01), name="s2zrace",
                stats_interval_s=0.1,
            )
            warmup = await rset.request(
                [7], params={"max_new_tokens": 6}
            )
            assert await warmup.result(timeout=30) == expected_stream(7)
            teardown = asyncio.ensure_future(rset.scale_to(0))
            await asyncio.sleep(0)  # let the drain grab the scale lock
            racing = await rset.request(
                [9], params={"max_new_tokens": 6}
            )
            assert await racing.result(timeout=60) == expected_stream(9)
            await teardown
            # The race resolved by re-warming: the set is live again.
            assert rset.live_replicas == 1
            await rset.close()
        finally:
            await ex.close()

    run_async(go())


def test_scale_to_zero_with_router_backlog_rewarns_instead(
    run_async, tmp_path
):
    """scale_to(0) with a request still waiting in the router's DRR
    queue (admitted but never worker-assigned) must NOT suspend over
    it: queued requests are demand, so the drain re-warms immediately
    and the stream completes (the code-review hole: a suspended set
    never pumps its queue)."""
    from covalent_tpu_plugin.fleet.queue import WorkItem
    from covalent_tpu_plugin.serving import open_replica_set
    from covalent_tpu_plugin.serving.supervisor import ServeRequest

    from .helpers import make_local_executor
    from .test_serving import make_factory

    async def go():
        ex = make_local_executor(
            tmp_path, use_agent="pool", heartbeat_interval=0.0,
            prewarm=False,
        )
        try:
            rset = await open_replica_set(
                [ex], make_factory(step_delay=0.01), name="s2zq",
                stats_interval_s=0.1,
            )
            warmup = await rset.request([1], params={"max_new_tokens": 6})
            assert await warmup.result(timeout=30) == expected_stream(1)
            # Inject a router-queued request directly — the state a
            # request reaches when it races the drain while a replica
            # still looks alive but has no headroom.
            stranded = ServeRequest(
                "s2zq-stranded", [5], {"max_new_tokens": 6}, 0.0, ""
            )
            rset.router.submit(WorkItem(
                fn=None, args=(), kwargs={},
                task_metadata={
                    "request": stranded, "sticky": "", "prefix_key": "",
                },
            ))
            count = await rset.scale_to(0)
            # The drain saw the backlog and re-warmed instead of
            # suspending over it; the stranded stream completes.
            assert count >= 1 and not rset.suspended
            assert await stranded.result(timeout=60) == expected_stream(5)
            await rset.close()
        finally:
            await ex.close()

    run_async(go())


def test_controller_revives_dead_set_to_policy_floor(run_async):
    """A managed set whose replicas ALL died without a suspension (past
    retry budgets) cannot re-warm through its own request path — the
    controller must re-open it to the policy's replica floor, paced by
    the up-cooldown."""
    now = [0.0]
    controller, _history = make_controller(lambda: now[0])
    rset = StubSet(replicas=1)
    controller.manage_replica_set(rset, min_replicas=1, max_replicas=3)

    async def go():
        rset._live = 0  # dead, NOT suspended
        decisions = await controller.tick()
        revive = [d for d in decisions if d["action"] == "set_up"]
        assert revive and revive[0]["reason"] == "revive_dead"
        assert rset.scaled[-1] == 1 and rset.live_replicas == 1
        # A suspended set, by contrast, is left for its request path.
        rset2 = StubSet(name="s2", replicas=1)
        controller.manage_replica_set(
            rset2, min_replicas=0, max_replicas=3, slo_critical=False
        )
        rset2._live = 0
        rset2._suspended = True
        now[0] += 10.0
        assert all(
            d["resource"] != "s2" for d in await controller.tick()
        )
        assert rset2.scaled == []

    run_async(go())
