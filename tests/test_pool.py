"""Harness forkserver pool (`harness.py --serve`).

Drives the real pool protocol end-to-end: a resident interpreter that
preloads modules once and forks per task, speaking the native agent's JSON
protocol.  Verifies the fork path executes specs correctly, pushes exit
events, and that the executor's auto mode picks the pool and reuses it.
"""

import asyncio
import json
import sys

import pytest

from covalent_tpu_plugin import harness
from covalent_tpu_plugin.agent import AgentClient, start_pool_server
from covalent_tpu_plugin.transport import LocalTransport
from covalent_tpu_plugin.utils.serialize import dump_task, load_result

from .helpers import make_local_executor

METADATA = {"dispatch_id": "dP", "node_id": 0}


def _stage_spec(tmp_path, fn, args=(), name="t"):
    function_file = tmp_path / f"fn_{name}.pkl"
    result_file = tmp_path / f"res_{name}.pkl"
    dump_task(fn, args, {}, function_file)
    spec = {
        "function_file": str(function_file),
        "result_file": str(result_file),
        "workdir": str(tmp_path / "wd"),
    }
    spec_file = tmp_path / f"spec_{name}.json"
    spec_file.write_text(json.dumps(spec))
    return str(spec_file), result_file


def test_pool_server_runs_spec_and_pushes_exit(tmp_path, run_async):
    async def flow():
        conn = LocalTransport()
        client = await start_pool_server(
            conn, str(tmp_path / "remote"), sys.executable, preload="cloudpickle"
        )
        assert client.mode == "pool"
        spec_file, result_file = _stage_spec(tmp_path, lambda a: a + 1, (41,))
        pid = await client.run_task(
            "t1", spec=spec_file, log=str(tmp_path / "t1.log"), timeout=30.0
        )
        code, signal = await client.wait_exit("t1", timeout=30.0)
        await client.close()
        return pid, code, signal, load_result(result_file)

    pid, code, signal, (result, exception) = run_async(flow())
    assert pid > 0 and code == 0 and signal == 0
    assert result == 42 and exception is None


def test_pool_forks_are_concurrent_and_isolated(tmp_path, run_async):
    """Two tasks forked from one server run simultaneously and don't share
    mutable state (each fork gets its own copy-on-write interpreter)."""

    def slow_electron(marker_path, delay):
        import os
        import time

        time.sleep(delay)
        return os.getpid()

    async def flow():
        conn = LocalTransport()
        client = await start_pool_server(
            conn, str(tmp_path / "remote"), sys.executable, preload="cloudpickle"
        )
        spec_a, res_a = _stage_spec(tmp_path, slow_electron, ("a", 0.6), "a")
        spec_b, res_b = _stage_spec(tmp_path, slow_electron, ("b", 0.6), "b")
        import time

        t0 = time.perf_counter()
        await client.run_task("a", spec=spec_a, timeout=30.0)
        await client.run_task("b", spec=spec_b, timeout=30.0)
        await asyncio.gather(
            client.wait_exit("a", timeout=30.0), client.wait_exit("b", timeout=30.0)
        )
        elapsed = time.perf_counter() - t0
        await client.close()
        return elapsed, load_result(res_a)[0], load_result(res_b)[0]

    elapsed, pid_a, pid_b = run_async(flow())
    assert pid_a != pid_b  # separate forked processes
    # The property is OVERLAP, not absolute speed: two 0.6 s sleeps run
    # serially take STRICTLY more than 1.2 s once fork/round-trip overhead
    # is added, so any elapsed below the bare serial floor proves overlap.
    assert elapsed < 1.2


def test_pool_transports_electron_exception(tmp_path, run_async):
    def boom():
        raise ValueError("pool-boom")

    async def flow():
        conn = LocalTransport()
        client = await start_pool_server(
            conn, str(tmp_path / "remote"), sys.executable, preload="cloudpickle"
        )
        spec_file, result_file = _stage_spec(tmp_path, boom)
        await client.run_task("t", spec=spec_file, timeout=30.0)
        code, _ = await client.wait_exit("t", timeout=30.0)
        await client.close()
        return code, load_result(result_file)

    code, (result, exception) = run_async(flow())
    assert code == 0  # harness succeeded; the error travels in the pickle
    assert isinstance(exception, ValueError) and "pool-boom" in str(exception)


def test_pool_kill_terminates_fork(tmp_path, run_async):
    def sleeper():
        import time

        time.sleep(30)

    async def flow():
        conn = LocalTransport()
        client = await start_pool_server(
            conn, str(tmp_path / "remote"), sys.executable, preload="cloudpickle"
        )
        spec_file, _ = _stage_spec(tmp_path, sleeper)
        await client.run_task("victim", spec=spec_file, timeout=30.0)
        await client.kill("victim")
        code, signal = await client.wait_exit("victim", timeout=30.0)
        await client.close()
        return code, signal

    code, signal = run_async(flow())
    assert signal == 15 or code != 0


def test_executor_auto_mode_prefers_pool_and_reuses_it(tmp_path, run_async):
    async def flow():
        ex = make_local_executor(tmp_path, use_agent=True, pool_preload="cloudpickle")
        first = await ex.run(lambda: 1, [], {}, METADATA)
        client = ex._agents.get("localhost")
        second = await ex.run(lambda: 2, [], {}, {"dispatch_id": "dP", "node_id": 1})
        same = ex._agents.get("localhost") is client
        await ex.close()
        return first, second, client.mode if client else None, same

    first, second, mode, same = run_async(flow())
    assert (first, second) == (1, 2)
    assert mode == "pool"
    assert same


def test_executor_pinned_native_mode_still_works(tmp_path, run_async):
    import shutil

    if all(shutil.which(cc) is None for cc in ("g++", "c++", "clang++")):
        pytest.skip("no C++ compiler")

    async def flow():
        ex = make_local_executor(tmp_path, use_agent="native")
        result = await ex.run(lambda: "native", [], {}, METADATA)
        mode = ex._agents["localhost"].mode
        await ex.close()
        return result, mode

    result, mode = run_async(flow())
    assert result == "native"
    assert mode == "native"


def test_executor_reused_across_separate_dispatches(tmp_path):
    """A persistent TPUExecutor must serve MULTIPLE dispatches: pooled
    transports and resident agents are loop-bound, so this regression-tests
    the shared dispatcher loop (a per-dispatch loop left the second lattice
    talking to channels on a dead loop)."""
    import covalent_tpu_plugin.workflow as ct

    ex = make_local_executor(tmp_path, use_agent=True, pool_preload="cloudpickle")

    @ct.electron(executor=ex)
    def double(n):
        return n * 2

    @ct.lattice
    def flow(n):
        return double(n)

    first = ct.dispatch_sync(flow)(4)
    second = ct.dispatch_sync(flow)(5)  # same executor, new dispatch
    assert first.status is ct.Status.COMPLETED and first.result == 8
    assert second.status is ct.Status.COMPLETED and second.result == 10


def test_concurrent_electron_stress(tmp_path, run_async):
    """16-way fan-out through one executor + resident pool: every result
    lands, no cross-task contamination, per-task state fully released."""
    from .helpers import make_local_executor

    ex = make_local_executor(
        tmp_path, use_agent=True, poll_freq=0.05, defer_cleanup=True
    )

    def square(i):
        return i * i

    async def flow():
        results = await asyncio.gather(
            *(
                ex.run(square, [i], {}, {"dispatch_id": "stress", "node_id": i})
                for i in range(16)
            )
        )
        await ex.close()
        return results

    assert run_async(flow()) == [i * i for i in range(16)]
    assert not ex._active  # per-operation state all released
    assert not ex._cleanup_tasks
