"""Unit tests for bench.py's dispatcher-side helpers.

The bench is the round's evidence artifact; its preflight gate decides
whether the TPU electron budget is committed at all, so its behavior
under a pinned-CPU environment (the validation regime) is load-bearing:
round 3 lost every TPU metric to a hung backend init, and the fix's
whole point is that a probe subprocess honours ``JAX_PLATFORMS`` even
when a site hook re-pins the platform after interpreter start.
"""

from __future__ import annotations

import os
import sys
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402


def test_spread_stats_fields():
    out = bench.spread_stats([0.001, 0.002, 0.004], "x")
    assert out["x_ms_min"] == 1.0
    assert out["x_ms_max"] == 4.0
    assert out["x_ms_stdev"] == pytest.approx(1.528, abs=1e-3)


def test_spread_stats_single_value_has_no_stdev():
    out = bench.spread_stats([0.003], "y")
    assert out == {"y_ms_min": 3.0, "y_ms_max": 3.0}


def test_tpu_preflight_honours_cpu_pin():
    # conftest pins JAX_PLATFORMS=cpu for the whole test process; the
    # probe subprocess inherits it and must probe CPU (fast pass), not
    # dial whatever accelerator plugin the site hook registers.
    assert os.environ.get("JAX_PLATFORMS") == "cpu"
    ok, took, err = bench.tpu_preflight(60.0)
    assert ok, f"preflight failed under cpu pin: {err}"
    assert took < 60.0


def test_step_accounting_hand_computed():
    # Shared structural model consumed by bench.py's lm_serve phase and
    # benchmarks/serve_bench.py (one implementation, so the artifacts
    # cannot drift from the admission rule in models/serve.py).
    from covalent_tpu_plugin.models import step_accounting

    # One slot, sync=2: req(4) finishes at step 3, slot frees at the
    # NEXT boundary (4), req(2) adds 1 more step -> 5; unquantized
    # packing would chain them at 3 + 1 = 4; static waves pay 3 + 1.
    assert step_accounting([4, 2], 1, 2) == {
        "static_wave_steps": 4,
        "continuous_steps_ideal": 4,
        "continuous_steps_sync": 5,
    }
    # Two slots: the three short requests chain on slot 1 (1 step each,
    # quantized to 2-step boundaries) while the long one holds slot 0.
    assert step_accounting([8, 2, 2, 2], 2, 2) == {
        "static_wave_steps": 8,
        "continuous_steps_ideal": 7,
        "continuous_steps_sync": 7,
    }
    # sync=1 means no quantization: sync == ideal.
    acc = step_accounting([5, 3, 9, 2, 6], 2, 1)
    assert acc["continuous_steps_sync"] == acc["continuous_steps_ideal"]


def test_tpu_preflight_timeout_reports_false():
    # A zero-ish cap can't even start the interpreter: the probe must
    # report failure with the timeout reason, never hang or raise.
    ok, took, err = bench.tpu_preflight(0.01)
    assert not ok
    assert "timeout" in err
    # The staged probe attributes WHERE the budget died, not just that
    # it did — the r03 diagnosis in one field.
    assert "stage" in err


def test_tpu_preflight_fails_fast_off_tpu_host(monkeypatch):
    # The r03+ root cause: JAX_PLATFORMS=tpu on a host with no TPU
    # device nodes hangs inside libtpu backend init for the full budget.
    # The probe must now refuse in milliseconds with the actionable
    # reason, flagged permanent so the retry loop stops.
    monkeypatch.setenv("JAX_PLATFORMS", "tpu")
    monkeypatch.delenv("TPU_NAME", raising=False)
    monkeypatch.delenv("TPU_WORKER_ID", raising=False)
    monkeypatch.delenv("TPU_WORKER_HOSTNAMES", raising=False)
    if bench.tpu_host_signals()["accel_devices"]:
        pytest.skip("running on a real TPU host")
    t0 = time.monotonic()
    ok, took, err = bench.tpu_preflight(45.0)
    assert not ok
    assert time.monotonic() - t0 < 5.0  # no hang, no subprocess
    assert bench.PREFLIGHT_PERMANENT in err
    assert "libtpu" in err  # the double-install diagnostic rides along


def test_last_known_good_is_stamped_and_never_live_shaped():
    # VERDICT r4: an end-of-round outage must yield a self-describing
    # artifact, not silent nulls.  The sub-object must carry provenance
    # and must NOT look like live host-side measurements.
    lkg = bench.load_last_known_good()
    assert lkg is not None  # benchmarks/BENCH_SELF_r*.jsonl is committed
    assert lkg["source"].startswith("benchmarks/BENCH_SELF_r")
    assert "captured_at" in lkg and lkg["captured_at"]
    assert "stale" in lkg["provenance"]
    # Host-side fields are re-measured every run and excluded here.
    assert "dispatch_overhead_s" not in lkg
    assert not any(k.startswith("fanout") for k in lkg)
    # At least the headline accelerator fields travel.
    assert lkg.get("matmul4k_mfu") is not None


def test_stage_histogram_summary_reads_span_registry():
    # The bench report embeds per-stage latency distributions from the obs
    # registry (ISSUE 1: real histograms instead of one overhead scalar).
    from covalent_tpu_plugin.obs.trace import Span

    with Span("executor.bench_probe_stage", emit=False):
        pass
    out = bench.stage_histogram_summary()
    entry = out["executor.bench_probe_stage"]
    assert entry["count"] >= 1
    assert {"count", "sum_s", "p50_s", "p95_s"} <= set(entry)
    # Unprefixed spans (models, workflow internals) stay out of the report.
    assert all(k.startswith(("executor.", "pool.", "agent.", "dispatch_"))
               for k in out)


def test_metrics_totals_flat_and_json_safe():
    import json

    from covalent_tpu_plugin.obs.metrics import REGISTRY

    REGISTRY.counter("bench_probe_total", "", ("kind",)).labels(
        kind="x"
    ).inc(2)
    totals = bench.metrics_totals()
    assert totals["bench_probe_total{kind=x}"] == 2
    json.dumps(totals)
