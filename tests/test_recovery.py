"""Dispatcher crash recovery: journal replay → re-adoption, end to end.

The worker-side half (orphan mode, epoch fencing, ``serve_resume``,
inventories) is covered process-level in ``test_recovery_worker.py``;
the journal's framing/replay in ``test_journal.py``.  This file covers
the dispatcher side: a first executor incarnation journals its world
and "crashes" (channels torn down with no close handshake, supervision
tasks cancelled), a second incarnation replays the journal, re-dials,
adopts the orphaned pool server through the rendezvous + ``--attach``
splice, and resumes the in-flight stream from its journaled high-water
mark with exactly-once delivery.
"""

import asyncio
import time

import pytest

from covalent_tpu_plugin.fleet import journal as journal_mod
from covalent_tpu_plugin.fleet import recovery as recovery_mod
from covalent_tpu_plugin.obs.metrics import REGISTRY
from covalent_tpu_plugin.serving import open_session

from .test_serving import make_factory, make_serve_executor


def counter_value(name: str, **labels) -> float:
    metric = REGISTRY.get(name)
    if metric is None:
        return 0.0
    total = 0.0
    for series_labels, series in metric._series():
        if all(series_labels.get(k) == v for k, v in labels.items()):
            total += series.value
    return total


def crash_dispatcher(ex) -> None:
    """Tear the first incarnation down the way SIGKILL would.

    No ``serve_close``, no channel shutdown handshake: supervision tasks
    are cancelled and each agent channel's pipes are dropped cold, so
    the worker sees a bare stdin EOF — the orphan-mode trigger — while
    this process (standing in for the successor dispatcher) lives on.
    The writer is closed FIRST and the reader cancelled in the same
    synchronous block, so no supervision code can run a graceful
    teardown in between.
    """
    for handle in list(ex._serve_handles.values()):
        sup = getattr(handle, "supervisor", handle)
        task = getattr(sup, "_supervisor", None)
        if task is not None:
            task.cancel()
    for client in list(ex._agents.values()):
        client._process._writer.close()
        client._reader.cancel()
    ex._serve_handles.clear()
    ex._agents.clear()


@pytest.fixture()
def journal_dir(tmp_path, monkeypatch):
    path = tmp_path / "journal"
    monkeypatch.setenv("COVALENT_TPU_JOURNAL_DIR", str(path))
    monkeypatch.setenv("COVALENT_TPU_ORPHAN_TTL_S", "90")
    journal_mod.reset()
    yield str(path)
    journal_mod.reset()


def test_recover_is_noop_without_journal(tmp_path, run_async, monkeypatch):
    """With journaling off the recovery pass touches nothing — no dial,
    no subprocess, just a ``recovered=False`` report."""
    monkeypatch.delenv("COVALENT_TPU_JOURNAL_DIR", raising=False)
    journal_mod.reset()

    async def flow():
        ex = make_serve_executor(tmp_path)
        try:
            return await ex.recover()
        finally:
            await ex.close()

    report = run_async(flow())
    assert report["recovered"] is False
    assert report["adopted_sessions"] == []
    assert recovery_mod.last_report() is not None


def test_recover_adopts_orphan_and_resumes_stream_exactly_once(
    tmp_path, run_async, journal_dir
):
    """The full arc: journal → crash → replay → orphan adoption →
    stream resume.  The journaled prefix plus the resumed tail must be
    byte-equal to the uninterrupted stream, with no token repeated."""
    adopted0 = counter_value("covalent_tpu_recovery_adopted_total")
    orphaned0 = counter_value("covalent_tpu_recovery_orphaned_total")

    async def flow():
        # -- incarnation 1: open a session, get a stream mid-flight.
        journal_mod.configure(journal_dir)
        assert journal_mod.epoch() == 1
        ex_a = make_serve_executor(tmp_path)
        handle = await open_session(
            ex_a, make_factory(step_delay=0.2, chunk=2, default_cap=30),
            stats_interval_s=0.1,
        )
        sid = handle.sid
        req_a = await handle.request([100], params={"max_new_tokens": 30})
        deadline = time.monotonic() + 20
        while len(req_a.tokens) < 4:
            if time.monotonic() > deadline:
                raise AssertionError("stream never started")
            await asyncio.sleep(0.05)
        # A journaled session NO worker holds (its worker is long dead):
        # recovery must reap it, not hang on it.
        journal_mod.record(
            "session", sid="ghost", sid_g="serve-ghost.g0",
            address="ghost-host", digest="x", payload="", slots=1,
            sync=True,
        )
        crash_dispatcher(ex_a)
        prefix = list(req_a.tokens)

        # -- incarnation 2: fresh journal handle over the same directory
        # replays the dead incarnation's world and bumps the epoch.
        journal_mod.reset()
        journal = journal_mod.configure(journal_dir)
        assert journal.epoch == 2
        assert sid in (journal.recovered.get("sessions") or {})
        ex_b = make_serve_executor(tmp_path)
        try:
            report = await ex_b.recover()
            rid = next(
                r for s, r in report.requests if s == sid
            )
            req_b = report.requests[(sid, rid)]
            resumed = await req_b.result(timeout=60)
        finally:
            await ex_b.close()
        return sid, prefix, report, resumed

    sid, prefix, report, resumed = run_async(flow())

    assert report["recovered"] is True
    assert report["epoch"] == 2
    assert sid in report["adopted_sessions"]
    assert "ghost" in report["orphaned_sessions"]
    entry = next(r for r in report["resumed_streams"] if r["sid"] == sid)
    assert entry["state"] in ("streaming", "done")
    # The journaled high-water mark is exactly what incarnation 1 had
    # delivered — the splice point.
    assert entry["from"] == len(prefix)
    # Exactly-once across the crash: prefix + resumed tail, no overlap,
    # no gap, byte-equal to the uninterrupted stream.
    assert prefix + resumed == [100 + i + 1 for i in range(30)]

    assert counter_value("covalent_tpu_recovery_adopted_total") == adopted0 + 1
    assert counter_value("covalent_tpu_recovery_orphaned_total") >= orphaned0 + 1
    last = recovery_mod.last_report()
    assert last is not None and last["recovered"] is True
    assert last["duration_s"] > 0


def test_recovered_session_serves_new_requests(
    tmp_path, run_async, journal_dir
):
    """A re-adopted session is a first-class citizen: new requests stream
    through it after recovery (the supervisor owns reconnects, stats and
    close exactly as if it had opened the session itself)."""

    async def flow():
        journal_mod.configure(journal_dir)
        ex_a = make_serve_executor(tmp_path)
        handle = await open_session(
            ex_a, make_factory(step_delay=0.1, chunk=2, default_cap=6),
            stats_interval_s=0.1,
        )
        sid = handle.sid
        req_a = await handle.request([100], params={"max_new_tokens": 20})
        while len(req_a.tokens) < 2:
            await asyncio.sleep(0.05)
        crash_dispatcher(ex_a)

        journal_mod.reset()
        journal_mod.configure(journal_dir)
        ex_b = make_serve_executor(tmp_path)
        try:
            report = await ex_b.recover()
            sup = report.supervisors[sid]
            from covalent_tpu_plugin.serving.supervisor import ServeRequest

            fresh = ServeRequest(
                "r-fresh", [500], {"max_new_tokens": 3}, 0.0, ""
            )
            await sup.submit(fresh)
            fresh_tokens = await fresh.result(timeout=30)
            closed = await sup.close()
        finally:
            await ex_b.close()
        return fresh_tokens, closed

    fresh_tokens, closed = run_async(flow())
    assert fresh_tokens == [501, 502, 503]
    assert isinstance(closed, dict)


def make_adapter_factory(step_delay=0.0):
    """A stub engine with the duck-typed multi-adapter surface
    (attach/detach/adapter_digests), cloudpickled BY VALUE: adapter
    ``name`` offsets the deterministic stream by the first value of its
    bundle leaves, so a resumed adapter-routed splice is byte-checkable
    and a stream decoded WITHOUT the adapter is visibly different."""

    def factory():
        import time as time_mod

        import numpy as np_mod

        class Engine:
            def __init__(self):
                self.slots = 2
                self.lanes = {}
                self.book = {}
                self.stats = {}

            def attach_adapter(self, name, payload):
                leaves = payload["leaves"]
                self.book[name] = (
                    str(payload["digest"]),
                    int(np_mod.asarray(leaves[0]).ravel()[0]),
                )
                self.stats.setdefault(f"adapter_tokens_{name}", 0)
                return payload["digest"]

            def detach_adapter(self, name):
                if name not in self.book:
                    raise ValueError(f"unknown adapter {name!r}")
                del self.book[name]

            @property
            def adapter_digests(self):
                return {n: d for n, (d, _) in self.book.items()}

            def admit(self, rid, prompt, params):
                name = str((params or {}).get("adapter") or "")
                offset = 0
                if name:
                    if name not in self.book:
                        err = ValueError(f"unknown adapter {name!r}")
                        err.fault_label = "serve_adapter_unknown"
                        err.fault_transient = False
                        raise err
                    offset = self.book[name][1]
                cap = int((params or {}).get("max_new_tokens", 6))
                base = int(prompt[-1]) + offset
                self.lanes[rid] = [base + i + 1 for i in range(cap)]
                if name:
                    self.stats[f"adapter_tokens_{name}"] += cap

            def step(self):
                if step_delay:
                    time_mod.sleep(step_delay)
                events = []
                for rid in list(self.lanes):
                    taken = self.lanes[rid][:2]
                    self.lanes[rid] = self.lanes[rid][2:]
                    done = not self.lanes[rid]
                    if done:
                        del self.lanes[rid]
                    events.append(
                        {"rid": rid, "tokens": taken, "done": done}
                    )
                return events

            def cancel(self, rid):
                self.lanes.pop(rid, None)

        return Engine()

    return factory


def test_recover_reattaches_adapters_and_resumes_byte_equal(
    tmp_path, run_async, journal_dir
):
    """SIGKILL the dispatcher with TWO adapters attached and an
    adapter-routed stream mid-flight: ``recover()`` must restore both
    names into the successor supervisor's book from the journaled
    registry records (resident fast path — the surviving worker still
    holds them — or a full re-attach from the CAS path), the resumed
    stream must splice byte-equal on the ADAPTER's weights, and fresh
    adapter-routed requests must route through the recovered session."""
    import numpy as np

    async def flow():
        journal_mod.configure(journal_dir)
        ex_a = make_serve_executor(tmp_path)
        handle = await open_session(
            ex_a, make_adapter_factory(step_delay=0.2),
            stats_interval_s=0.1,
        )
        sid = handle.sid
        for name, offset in (("fr", 1000), ("de", 2000)):
            ack = await handle.attach_adapter(
                name, payload=[np.full((2, 2), offset, dtype=np.float32)]
            )
            assert ack.get("digest"), ack
        assert set(handle.adapters) == {"fr", "de"}
        req_a = await handle.request(
            [100], params={"max_new_tokens": 30, "adapter": "fr"}
        )
        deadline = time.monotonic() + 20
        while len(req_a.tokens) < 4:
            if time.monotonic() > deadline:
                raise AssertionError("stream never started")
            await asyncio.sleep(0.05)
        crash_dispatcher(ex_a)
        prefix = list(req_a.tokens)

        journal_mod.reset()
        journal = journal_mod.configure(journal_dir)
        meta = (journal.recovered.get("sessions") or {}).get(sid) or {}
        journaled = set((meta.get("adapters") or {}))
        ex_b = make_serve_executor(tmp_path)
        try:
            report = await ex_b.recover()
            sup = report.supervisors[sid]
            recovered_book = dict(sup.adapters)
            rid = next(r for s, r in report.requests if s == sid)
            resumed = await report.requests[(sid, rid)].result(timeout=60)
            from covalent_tpu_plugin.serving.supervisor import (
                ServeRequest,
            )

            fresh = ServeRequest(
                "r-de", [5], {"max_new_tokens": 4, "adapter": "de"},
                0.0, "",
            )
            await sup.submit(fresh)
            fresh_tokens = await fresh.result(timeout=30)
            await sup.close()
        finally:
            await ex_b.close()
        return (sid, journaled, prefix, resumed, report,
                recovered_book, fresh_tokens)

    (sid, journaled, prefix, resumed, report, recovered_book,
     fresh_tokens) = run_async(flow())

    # Both attachments were journaled sync and survived the crash.
    assert journaled == {"fr", "de"}
    assert set(recovered_book) == {"fr", "de"}
    states = {
        entry["adapter"]: entry["state"]
        for entry in report["reattached_adapters"]
        if entry["sid"] == sid
    }
    assert set(states) == {"fr", "de"}
    assert set(states.values()) <= {"resident", "attached"}
    # Exactly-once across the crash ON THE ADAPTER'S WEIGHTS: prefix +
    # resumed tail equals the uninterrupted adapter-offset stream.
    assert prefix + resumed == [100 + 1000 + i + 1 for i in range(30)]
    # The re-attached book serves fresh adapter-routed traffic.
    assert fresh_tokens == [5 + 2000 + i + 1 for i in range(4)]
