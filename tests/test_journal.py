"""Write-ahead journal: framing, fsync batching, rotation, replay fuzz.

The fuzz matrix is the crash-safety contract: replay must NEVER raise on
a damaged log — a torn tail truncates, a bit-flipped record skips, and
both leave counters behind.  Snapshot+tail compaction must replay to the
same state as the full log it replaced.
"""

import hashlib
import json
import os
import struct

import pytest

from covalent_tpu_plugin.fleet import journal as journal_mod
from covalent_tpu_plugin.fleet.journal import Journal, JournalState


@pytest.fixture(autouse=True)
def _isolate(monkeypatch):
    monkeypatch.delenv("COVALENT_TPU_JOURNAL_DIR", raising=False)
    journal_mod.reset()
    yield
    journal_mod.reset()


def _open(tmp_path, **kwargs):
    kwargs.setdefault("fsync_ms", 0)
    return Journal.open(str(tmp_path / "wal"), **kwargs)


def _segments(journal):
    return journal._scan()[0]


# -- framing + append --------------------------------------------------------


def test_append_and_replay_roundtrip(tmp_path):
    j = _open(tmp_path)
    j.record("pool", name="tpu-a", spec={"capacity": 4})
    j.record("session", sid="s1", address="w0", sid_g="s1.g0")
    j.record("stream", sid="s1", rid="r1", prompt=[1, 2, 3])
    j.record("stream_hwm", sid="s1", rid="r1", hwm=7)
    j.record("task", op="op-1", pool="tpu-a", attempt=1)
    epoch = j.epoch
    j.close()

    j2 = Journal.open(j.directory, fsync_ms=0)
    assert j2.epoch == epoch + 1  # reopen bumps the fence
    assert j2.state.pools["tpu-a"] == {"capacity": 4}
    assert j2.state.sessions["s1"]["address"] == "w0"
    assert j2.state.streams[("s1", "r1")]["hwm"] == 7
    assert j2.state.tasks["op-1"]["pool"] == "tpu-a"
    assert j2.replay_skipped == 0 and j2.replay_truncated == 0
    j2.close()


def test_terminal_records_clear_state(tmp_path):
    j = _open(tmp_path)
    j.record("session", sid="s1", address="w0")
    j.record("stream", sid="s1", rid="r1")
    j.record("stream_done", sid="s1", rid="r1", outcome="ok")
    j.record("task", op="op-1")
    j.record("task_terminal", op="op-1", outcome="ok")
    j.record("session_closed", sid="s1")
    j.close()

    j2 = Journal.open(j.directory, fsync_ms=0)
    assert not j2.state.sessions
    assert not j2.state.streams
    assert not j2.state.tasks
    j2.close()


def test_hwm_is_monotonic(tmp_path):
    j = _open(tmp_path)
    j.record("stream", sid="s", rid="r")
    j.record("stream_hwm", sid="s", rid="r", hwm=9)
    j.record("stream_hwm", sid="s", rid="r", hwm=4)  # stale update
    assert j.state.streams[("s", "r")]["hwm"] == 9
    j.close()


# -- fuzz: torn tail ---------------------------------------------------------


def _live_segment(j):
    segs = _segments(j)
    assert segs
    return segs[-1][1]


def test_torn_tail_truncates_cleanly(tmp_path):
    j = _open(tmp_path)
    for i in range(5):
        j.record("task", op=f"op-{i}")
    j.close()
    path = _live_segment(j)
    size = os.path.getsize(path)
    with open(path, "r+b") as fh:
        fh.truncate(size - 11)  # rip mid-record

    j2 = Journal.open(j.directory, fsync_ms=0)
    assert j2.replay_truncated == 1
    assert j2.replay_applied >= 4  # epoch + first four tasks survive
    assert "op-3" in j2.state.tasks and "op-4" not in j2.state.tasks
    # Post-truncation appends land on a clean boundary and replay fine.
    j2.record("task", op="op-new")
    j2.close()
    j3 = Journal.open(j.directory, fsync_ms=0)
    assert "op-new" in j3.state.tasks
    assert j3.replay_truncated == 0
    j3.close()


def test_truncated_length_prefix(tmp_path):
    j = _open(tmp_path)
    j.record("task", op="op-0")
    j.close()
    path = _live_segment(j)
    with open(path, "ab") as fh:
        fh.write(b"\x00\x00")  # two bytes of a would-be length prefix

    j2 = Journal.open(j.directory, fsync_ms=0)
    assert j2.replay_truncated == 1
    assert "op-0" in j2.state.tasks
    j2.close()


def test_garbage_length_treated_as_torn(tmp_path):
    j = _open(tmp_path)
    j.record("task", op="op-0")
    j.close()
    path = _live_segment(j)
    with open(path, "ab") as fh:
        fh.write(struct.pack(">I", 0x7FFFFFFF) + os.urandom(40))

    j2 = Journal.open(j.directory, fsync_ms=0)
    assert j2.replay_truncated == 1
    assert "op-0" in j2.state.tasks
    j2.close()


# -- fuzz: bit flips ---------------------------------------------------------


def test_bit_flip_skips_record_and_continues(tmp_path):
    j = _open(tmp_path)
    j.record("task", op="op-keep-1")
    j.record("task", op="op-flip")
    j.record("task", op="op-keep-2")
    j.close()
    path = _live_segment(j)
    data = bytearray(open(path, "rb").read())
    at = data.find(b"op-flip")
    assert at > 0
    data[at] ^= 0x40
    open(path, "wb").write(bytes(data))

    j2 = Journal.open(j.directory, fsync_ms=0)
    assert j2.replay_skipped == 1
    assert j2.replay_truncated == 0
    assert "op-keep-1" in j2.state.tasks and "op-keep-2" in j2.state.tasks
    assert "op-flip" not in j2.state.tasks
    j2.close()


def test_random_corruption_never_raises(tmp_path):
    import random

    rng = random.Random(18)
    j = _open(tmp_path)
    for i in range(50):
        j.record("stream", sid=f"s{i % 3}", rid=f"r{i}", prompt=[i])
    j.close()
    path = _live_segment(j)
    pristine = open(path, "rb").read()
    for trial in range(25):
        data = bytearray(pristine)
        for _ in range(rng.randrange(1, 6)):
            data[rng.randrange(len(data))] ^= 1 << rng.randrange(8)
        if rng.random() < 0.5:
            data = data[: rng.randrange(len(data))]
        open(path, "wb").write(bytes(data))
        j2 = Journal.open(j.directory, fsync_ms=0)  # must not raise
        j2.close()
        open(path, "wb").write(pristine)


# -- rotation + snapshot compaction ------------------------------------------


def test_rotation_compacts_behind_snapshot(tmp_path):
    j = _open(tmp_path, max_segment_bytes=600)
    for i in range(60):
        j.record("task", op=f"op-{i}", pool="p", attempt=1)
        j.record("task_terminal", op=f"op-{i}")
    j.record("task", op="op-live")
    j.close()
    segs, snaps = j._scan()
    assert snaps, "rotation must have written a snapshot"
    assert len(segs) <= 2, "covered segments must be compacted away"

    j2 = Journal.open(j.directory, fsync_ms=0)
    assert j2.state.tasks == {"op-live": {"op": "op-live"}}
    j2.close()


def test_snapshot_plus_tail_equals_full_log(tmp_path):
    # Same record sequence, rotated vs unrotated, must replay equal.
    recs = []
    for i in range(40):
        recs.append({"t": "session", "sid": f"s{i % 4}", "address": f"w{i}"})
        recs.append({"t": "stream", "sid": f"s{i % 4}", "rid": f"r{i}"})
        if i % 3 == 0:
            recs.append({"t": "stream_hwm", "sid": f"s{i % 4}",
                         "rid": f"r{i}", "hwm": i})
        if i % 5 == 0:
            recs.append({"t": "session_closed", "sid": f"s{(i + 2) % 4}"})

    j_small = Journal.open(str(tmp_path / "small"), fsync_ms=0,
                           max_segment_bytes=400)
    j_big = Journal.open(str(tmp_path / "big"), fsync_ms=0,
                         max_segment_bytes=1 << 30)
    for rec in recs:
        j_small.append(dict(rec))
        j_big.append(dict(rec))
    j_small.close()
    j_big.close()
    assert len(j_small._scan()[1]) >= 1  # compaction actually happened

    r_small = Journal.open(j_small.directory, fsync_ms=0)
    r_big = Journal.open(j_big.directory, fsync_ms=0)
    try:
        small, big = r_small.state.to_dict(), r_big.state.to_dict()
        # Epochs differ only by open() count on each dir; mask them.
        small.pop("epoch"), big.pop("epoch")
        assert small == big
    finally:
        r_small.close()
        r_big.close()


def test_corrupt_snapshot_falls_back(tmp_path):
    j = _open(tmp_path, max_segment_bytes=400)
    for i in range(40):
        j.record("pool", name=f"p{i}", spec={"capacity": i})
    j.close()
    _, snaps = j._scan()
    assert snaps
    # Corrupt the newest snapshot's embedded state.
    path = snaps[-1][1]
    doc = json.load(open(path))
    doc["state"]["pools"]["p0"] = {"capacity": 999}
    json.dump(doc, open(path, "w"))

    j2 = Journal.open(j.directory, fsync_ms=0)
    # Digest mismatch → snapshot rejected. Compaction deleted the covered
    # segments, so only the tail replays — but replay must not raise, and
    # the tail's records must be present.
    assert f"p39" in j2.state.pools
    assert j2.state.pools.get("p0") != {"capacity": 999}
    j2.close()


def test_interleaved_rotation_replay(tmp_path):
    """Writes striped across many rotations replay in order."""
    j = _open(tmp_path, max_segment_bytes=300)
    for i in range(30):
        j.record("stream", sid="s", rid=f"r{i}")
        j.record("stream_hwm", sid="s", rid=f"r{i}", hwm=i + 1)
        if i >= 2:
            j.record("stream_done", sid="s", rid=f"r{i - 2}")
    j.close()

    j2 = Journal.open(j.directory, fsync_ms=0)
    live = {rid for (_sid, rid) in j2.state.streams}
    assert live == {"r28", "r29"}
    assert j2.state.streams[("s", "r29")]["hwm"] == 30
    j2.close()


# -- epoch + singleton -------------------------------------------------------


def test_epoch_monotonic_across_opens(tmp_path):
    seen = []
    for _ in range(3):
        j = _open(tmp_path)
        seen.append(j.epoch)
        j.close()
    assert seen == sorted(seen) and len(set(seen)) == 3


def test_singleton_noop_when_unconfigured(tmp_path):
    assert journal_mod.get_journal() is None
    journal_mod.record("task", op="ignored")  # must be a silent no-op
    assert journal_mod.epoch() == 0


def test_singleton_configures_from_env(tmp_path, monkeypatch):
    monkeypatch.setenv("COVALENT_TPU_JOURNAL_DIR", str(tmp_path / "envwal"))
    journal_mod.record("task", op="op-env")
    j = journal_mod.get_journal()
    assert j is not None
    assert "op-env" in j.state.tasks
    assert journal_mod.epoch() == j.epoch >= 1


def test_fsync_batching_flusher(tmp_path):
    j = Journal.open(str(tmp_path / "wal"), fsync_ms=5)
    j.record("task", op="op-batched")
    import time

    deadline = time.time() + 2.0
    while j._dirty and time.time() < deadline:
        time.sleep(0.01)
    assert not j._dirty, "background flusher never fsynced"
    j.close()
