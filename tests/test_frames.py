"""Binary agent-channel frames: codec, negotiation, batching, fuzz.

The frame layer has three byte-compatible implementations — the
dispatcher (``transport/frames.py``), the standalone worker harness
(``harness.py``), and the native C++ agent (``native/agent.cc``, covered
in ``test_agent.py``).  This module cross-checks the first two against
each other, drives the negotiated fast path end to end (raw-pickle
invoke/result frames, multi-invoke batching, token coalescing), proves
the JSONL fallback is byte-equal in every direction the handshake can
degrade, and fuzzes the pool server's frame parser: malformed input must
fail loud as clean errors — permanent where torn — and never hang or
kill the resident runtime.
"""

import asyncio
import io
import json
import sys

import cloudpickle
import pytest

from covalent_tpu_plugin import TPUExecutor
from covalent_tpu_plugin import harness as harness_mod
from covalent_tpu_plugin.agent import start_pool_server
from covalent_tpu_plugin.cache import bytes_digest
from covalent_tpu_plugin.obs.metrics import REGISTRY
from covalent_tpu_plugin.resilience import FaultClass, classify_error
from covalent_tpu_plugin.transport import LocalTransport, frames

from .helpers import pin_cpu_task_env


def counter_value(name: str, **labels) -> float:
    metric = REGISTRY.get(name)
    if metric is None:
        return 0.0
    total = 0.0
    for series_labels, counter in metric._series():
        if all(series_labels.get(k) == v for k, v in labels.items()):
            total += counter.value
    return total


def make_rpc_executor(tmp_path, **kwargs):
    kwargs.setdefault("transport", "local")
    kwargs.setdefault("cache_dir", str(tmp_path / "cache"))
    kwargs.setdefault("remote_cache", str(tmp_path / "remote"))
    kwargs.setdefault("python_path", sys.executable)
    kwargs.setdefault("poll_freq", 0.2)
    kwargs.setdefault("use_agent", "pool")
    kwargs.setdefault("dispatch_mode", "rpc")
    kwargs.setdefault("heartbeat_interval", 0.0)
    kwargs.setdefault("prewarm", False)
    return TPUExecutor(**pin_cpu_task_env(kwargs))


def _make_square():
    def square(x):
        return x * x

    return square


def stage_payload(tmp_path, obj):
    payload = cloudpickle.dumps(obj)
    digest = bytes_digest(payload)
    path = tmp_path / f"{digest}.pkl"
    path.write_bytes(payload)
    return payload, digest, str(path)


class _HarnessStdout:
    """Capture harness emissions (text lines AND binary frames) in one
    byte stream, the way the real channel sees them."""

    def __init__(self):
        self.buffer = io.BytesIO()

    def write(self, text):
        self.buffer.write(text.encode())

    def flush(self):
        pass


class _FakeSysModule:
    """``sys`` stand-in for the harness module: a private stdout, the real
    module for everything else (pytest's capture plugin re-swaps the real
    ``sys.stdout`` between fixture setup and the test call, so patching
    the interpreter-wide attribute is unreliable)."""

    def __init__(self, fake_stdout):
        self.stdout = fake_stdout

    def __getattr__(self, name):
        return getattr(sys, name)


@pytest.fixture()
def harness_stdout(monkeypatch):
    fake = _HarnessStdout()
    monkeypatch.setattr(harness_mod, "sys", _FakeSysModule(fake))
    return fake


# ---------------------------------------------------------------------------
# Codec cross-compatibility: dispatcher encoder <-> harness parser and back.
# ---------------------------------------------------------------------------


def test_dispatcher_frame_parses_on_harness_side(harness_stdout):
    body = b"\x00\x01raw pickle bytes\xff" * 10
    wire = frames.encode_frame(
        frames.VERB_INVOKE,
        {"cmd": "invoke", "id": "op1", "digest": "d" * 64,
         "_body": "args_bytes"},
        body,
    )
    buf = bytearray(wire)
    commands = harness_mod._extract_commands(buf)
    assert len(commands) == 1 and not buf
    assert commands[0]["cmd"] == "invoke"
    assert commands[0]["args_bytes"] == body
    assert harness_stdout.buffer.getvalue() == b""  # no error emitted


def test_dispatcher_compressed_frame_parses_on_harness_side(harness_stdout):
    body = b"compressible " * 4096
    wire = frames.encode_frame(
        frames.VERB_INVOKE,
        {"cmd": "invoke", "id": "op1", "_body": "args_bytes"},
        body,
        codec="zlib",
    )
    assert len(wire) < len(body)  # compression actually engaged
    flags = wire[4]
    assert flags & frames.FLAG_BODY_ZLIB
    commands = harness_mod._extract_commands(bytearray(wire))
    assert commands[0]["args_bytes"] == body


def test_harness_frame_parses_on_dispatcher_side(harness_stdout, monkeypatch):
    monkeypatch.setitem(harness_mod._FRAMES, "out", True)
    monkeypatch.setitem(harness_mod._FRAMES, "codec", "zlib")
    body = b"result pickle " * 2048
    harness_mod._emit_frame(
        harness_mod._VERB_RESULT,
        {"event": "result", "id": "op1", "ok": True, "_body": "data_bytes"},
        body,
    )
    wire = harness_stdout.buffer.getvalue()
    magic, version, verb, flags, hlen, blen = frames.HEADER.unpack(
        wire[:frames.HEADER_LEN]
    )
    assert magic == frames.MAGIC and version == frames.VERSION
    assert verb == frames.VERB_RESULT
    header = wire[frames.HEADER_LEN:frames.HEADER_LEN + hlen]
    payload = wire[frames.HEADER_LEN + hlen:frames.HEADER_LEN + hlen + blen]
    event = frames.decode_payload(flags, header, payload)
    assert event["event"] == "result" and event["ok"] is True
    assert event["data_bytes"] == body


def test_frames_and_lines_interleave(harness_stdout):
    wire = (
        json.dumps({"cmd": "ping"}).encode() + b"\n"
        + frames.encode_frame(
            frames.VERB_SERVE, {"cmd": "serve_request", "id": "s1"}
        )
        + json.dumps({"cmd": "shutdown"}).encode() + b"\n"
    )
    commands = harness_mod._extract_commands(bytearray(wire))
    assert [c.get("cmd") for c in commands] == [
        "ping", "serve_request", "shutdown"
    ]


def test_torn_compressed_body_fails_permanent():
    with pytest.raises(frames.FrameIntegrityError):
        frames.decode_payload(
            frames.FLAG_BODY_ZLIB, b'{"event":"result"}', b"not deflate"
        )
    fault, _ = classify_error(frames.FrameIntegrityError("torn"))
    assert fault is FaultClass.PERMANENT


def test_oversized_encode_refused():
    with pytest.raises(frames.FrameError):
        frames.encode_frame(
            frames.VERB_CMD, {"cmd": "x"},
            b"\x00" * (frames.MAX_BODY_BYTES + 1),
        )


# ---------------------------------------------------------------------------
# Harness parser fuzz (in process): clean errors, resync, no hangs.
# ---------------------------------------------------------------------------


def _emitted_errors(harness_stdout):
    return [
        json.loads(line)
        for line in harness_stdout.buffer.getvalue().decode().splitlines()
        if line.strip()
    ]


def test_parser_bad_magic_resyncs_at_newline(harness_stdout):
    buf = bytearray(
        bytes([frames.MAGIC[0], 0x00]) + b"garbage-without-meaning\n"
        + json.dumps({"cmd": "ping"}).encode() + b"\n"
    )
    commands = harness_mod._extract_commands(buf)
    assert [c.get("cmd") for c in commands] == ["ping"]
    errors = _emitted_errors(harness_stdout)
    assert errors and errors[0]["code"] == "bad_frame"


def test_parser_bad_version_resyncs(harness_stdout):
    frame = bytearray(frames.encode_frame(frames.VERB_CMD, {"cmd": "ping"}))
    frame[2] = 99  # corrupt the version byte
    buf = bytearray(bytes(frame) + b"\n" + b'{"cmd":"ping"}\n')
    commands = harness_mod._extract_commands(buf)
    assert [c.get("cmd") for c in commands] == ["ping"]
    assert _emitted_errors(harness_stdout)[0]["code"] == "bad_frame"


def test_parser_oversized_length_refused(harness_stdout):
    header = frames.HEADER.pack(
        frames.MAGIC, frames.VERSION, 0, 0, 5, frames.MAX_BODY_BYTES + 1
    )
    buf = bytearray(header + b"\n" + b'{"cmd":"ping"}\n')
    commands = harness_mod._extract_commands(buf)
    assert [c.get("cmd") for c in commands] == ["ping"]
    assert "oversized" in _emitted_errors(harness_stdout)[0]["message"]


def test_parser_non_json_header_consumes_frame_in_sync(harness_stdout):
    bad = frames.HEADER.pack(frames.MAGIC, frames.VERSION, 0, 0, 7, 3)
    buf = bytearray(
        bad + b"not-js!" + b"\x01\x02\x03"
        + frames.encode_frame(frames.VERB_CMD, {"cmd": "ping"})
    )
    commands = harness_mod._extract_commands(buf)
    # The bad-header frame is length-consumable, so the NEXT frame (no
    # newline between them) still parses — sync was never lost.
    assert [c.get("cmd") for c in commands] == ["ping"]
    assert _emitted_errors(harness_stdout)[0]["code"] == "bad_frame"


def test_parser_torn_zlib_body_is_permanent_error(harness_stdout):
    head = json.dumps(
        {"cmd": "invoke", "id": "tornop", "_body": "args_bytes"}
    ).encode()
    body = b"definitely not deflate data"
    wire = frames.HEADER.pack(
        frames.MAGIC, frames.VERSION, frames.VERB_INVOKE,
        frames.FLAG_BODY_ZLIB, len(head), len(body),
    ) + head + body
    commands = harness_mod._extract_commands(bytearray(wire))
    assert commands == []
    errors = _emitted_errors(harness_stdout)
    assert errors[0]["code"] == "bad_frame"
    assert errors[0]["permanent"] is True
    assert errors[0]["id"] == "tornop"


def test_parser_torn_multi_invoke_body_fans_error_to_every_op(
    harness_stdout,
):
    """A torn batched frame must refuse EVERY waiting op id permanently —
    the ids live in ops, not at the header top level, and an id-less
    error is log-only on the client (each op would sit out its started
    timeout and burn a transient retry on deterministic corruption)."""
    head = json.dumps({
        "cmd": "multi_invoke", "digest": "d" * 64,
        "ops": [{"id": "mop1"}, {"id": "mop2"}, {"id": "mop3"}],
        "args_lens": [3, 3, 3], "_body": "args_bytes",
    }).encode()
    body = b"definitely not deflate"
    wire = frames.HEADER.pack(
        frames.MAGIC, frames.VERSION, frames.VERB_MULTI_INVOKE,
        frames.FLAG_BODY_ZLIB, len(head), len(body),
    ) + head + body
    assert harness_mod._extract_commands(bytearray(wire)) == []
    errors = _emitted_errors(harness_stdout)
    assert [e["id"] for e in errors] == ["mop1", "mop2", "mop3"]
    assert all(
        e["code"] == "bad_frame" and e["permanent"] is True for e in errors
    )


def test_parser_partial_frame_waits_for_more_bytes(harness_stdout):
    wire = frames.encode_frame(
        frames.VERB_INVOKE, {"cmd": "invoke", "id": "op",
                             "_body": "args_bytes"}, b"x" * 100,
    )
    buf = bytearray(wire[:40])  # mid-frame: channel death leaves this
    assert harness_mod._extract_commands(buf) == []
    assert len(buf) == 40  # retained, not misparsed
    buf.extend(wire[40:])
    commands = harness_mod._extract_commands(buf)
    assert commands[0]["id"] == "op" and commands[0]["args_bytes"] == b"x" * 100


# ---------------------------------------------------------------------------
# Live pool-server fuzz over a real channel: the runtime must survive.
# ---------------------------------------------------------------------------


async def _pool_client(tmp_path, frames_enabled=None):
    conn = LocalTransport()
    return await start_pool_server(
        conn, str(tmp_path / "remote"), sys.executable,
        frames_enabled=frames_enabled,
    )


def test_pool_server_survives_frame_garbage(tmp_path, run_async):
    async def flow():
        client = await _pool_client(tmp_path)
        try:
            assert client.frames_active
            garbage = [
                bytes([frames.MAGIC[0], 0x11]) + b"junk\n",
                b"\xc5"  # lone magic byte then a newline-terminated mess
                + b"\x00" * 7 + b"\n",
                frames.HEADER.pack(
                    frames.MAGIC, 42, 0, 0, 1, 1
                ) + b"\n",  # bad version
                frames.HEADER.pack(
                    frames.MAGIC, frames.VERSION, 0, 0,
                    frames.MAX_HEADER_BYTES + 1, 0,
                ) + b"\n",  # oversized header length
                b"plain text that is not json\n",
            ]
            for chunk in garbage:
                await client._process.write_bytes(chunk)
                # The runtime must still answer commands after every
                # injection — fail loud, keep serving.
                await client.ping(10.0)
            return True
        finally:
            await client.close()

    assert run_async(flow()) is True


def test_pool_server_torn_invoke_body_rejected_permanent(
    tmp_path, run_async
):
    async def flow():
        client = await _pool_client(tmp_path)
        try:
            assert client.frames_active
            head = json.dumps({
                "cmd": "invoke", "id": "tornop", "digest": "d" * 64,
                "_body": "args_bytes",
            }).encode()
            body = b"garbage, not zlib"
            await client._process.write_bytes(
                frames.HEADER.pack(
                    frames.MAGIC, frames.VERSION, frames.VERB_INVOKE,
                    frames.FLAG_BODY_ZLIB, len(head), len(body),
                ) + head + body
            )
            await client._wait(
                lambda c: c._error_codes.get("tornop"), 15.0
            )
            rejection = client._pop_rejection("tornop", "invoke")
            fault, label = classify_error(rejection)
            await client.ping(10.0)  # runtime alive after the refusal
            return fault, label
        finally:
            await client.close()

    fault, label = run_async(flow())
    assert fault is FaultClass.PERMANENT
    assert label == "agent_bad_frame"


def test_pool_server_mid_frame_channel_death_exits_clean(
    tmp_path, run_async
):
    async def flow():
        client = await _pool_client(tmp_path)
        assert client.frames_active
        wire = frames.encode_frame(
            frames.VERB_INVOKE,
            {"cmd": "invoke", "id": "op", "_body": "args_bytes"},
            b"y" * 4096,
        )
        await client._process.write_bytes(wire[: len(wire) // 2])
        await client.close()  # EOF with half a frame buffered remotely
        return client._process.returncode

    assert run_async(flow()) == 0


# ---------------------------------------------------------------------------
# Negotiation and fallback: every degrade path is byte-equal.
# ---------------------------------------------------------------------------


def test_json_only_runtime_degrades_to_jsonl(tmp_path, run_async, monkeypatch):
    """Binary-capable client, frames-disabled runtime: silent banner, JSONL
    fallback, identical results."""
    monkeypatch.setenv("COVALENT_TPU_AGENT_FRAMES", "0")

    async def flow():
        client = await _pool_client(tmp_path, frames_enabled=True)
        try:
            assert not client.frames_active
            assert "frames" not in client._banner
            payload, digest, path = stage_payload(tmp_path, _make_square())
            await client.register_fn(digest, path)
            await client.invoke(
                "op1", digest, path=path,
                args_bytes=cloudpickle.dumps(((7,), {})),
            )
            event = await client.wait_result("op1", timeout=30.0)
            return cloudpickle.loads(
                __import__("base64").b64decode(event["data"])
            )
        finally:
            await client.close()

    result, exception = run_async(flow())
    assert exception is None and result == 49


def test_client_kill_switch_declines_capable_runtime(tmp_path, run_async):
    async def flow():
        client = await _pool_client(tmp_path, frames_enabled=False)
        try:
            assert not client.frames_active
            # The runtime DID advertise — the client declined.
            assert client._banner.get("frames") == 1
            payload, digest, path = stage_payload(tmp_path, _make_square())
            await client.register_fn(digest, path)
            await client.invoke(
                "op1", digest, path=path,
                args_bytes=cloudpickle.dumps(((8,), {})),
            )
            event = await client.wait_result("op1", timeout=30.0)
            return event.get("data_bytes"), event.get("data")
        finally:
            await client.close()

    data_bytes, data_b64 = run_async(flow())
    assert data_bytes is None  # result rode the JSONL fallback
    result, exception = cloudpickle.loads(
        __import__("base64").b64decode(data_b64)
    )
    assert exception is None and result == 64


def test_e2e_binary_and_jsonl_results_byte_equal(tmp_path, run_async):
    """The same electron through a frames channel and a JSONL channel must
    produce byte-identical result pickles — and the binary arm must have
    actually used frames (no silent fallback can pass this)."""

    async def run_arm(tag, agent_frames):
        ex = make_rpc_executor(tmp_path / tag, agent_frames=agent_frames)
        try:
            out = await ex.run(
                _make_square(), [123], {},
                {"dispatch_id": f"fr{tag}", "node_id": 0},
            )
            assert ex.last_dispatch_mode == "rpc"
            return out
        finally:
            await ex.close()

    async def flow():
        before = counter_value(
            "covalent_tpu_agent_frames_total",
            verb="invoke", encoding="binary",
        )
        binary = await run_arm("bin", True)
        after = counter_value(
            "covalent_tpu_agent_frames_total",
            verb="invoke", encoding="binary",
        )
        jsonl = await run_arm("jsonl", False)
        return binary, jsonl, after - before

    binary, jsonl, framed_invokes = run_async(flow())
    assert binary == jsonl == 123 * 123
    assert cloudpickle.dumps(binary) == cloudpickle.dumps(jsonl)
    assert framed_invokes >= 1


def test_chaos_transport_faults_apply_to_framed_channel(tmp_path, run_async):
    """ChaosTransport's injected latency/faults gate the framed channel's
    start_process exactly like the JSONL one; results stay correct."""
    from covalent_tpu_plugin.transport import ChaosPlan

    async def flow():
        ex = make_rpc_executor(
            tmp_path, dispatch_mode="rpc", chaos=ChaosPlan(delay=0.01),
            agent_frames=True,
        )
        try:
            return await ex.run(
                _make_square(), [11], {},
                {"dispatch_id": "frchaos", "node_id": 0},
            )
        finally:
            await ex.close()

    assert run_async(flow()) == 121


# ---------------------------------------------------------------------------
# Batched invoke: same-turn invokes for one digest ship as ONE frame.
# ---------------------------------------------------------------------------


def test_concurrent_invokes_coalesce_into_multi_invoke(tmp_path, run_async):
    async def flow():
        client = await _pool_client(tmp_path)
        try:
            assert client.frames_active and client.mode == "pool"
            payload, digest, path = stage_payload(tmp_path, _make_square())
            await client.register_fn(digest, path)
            before = counter_value(
                "covalent_tpu_agent_frames_total",
                verb="multi_invoke", encoding="binary",
            )
            ids = [f"batch{i}" for i in range(4)]
            await asyncio.gather(*(
                client.invoke(
                    tid, digest, path=path,
                    args_bytes=cloudpickle.dumps(((i,), {})),
                )
                for i, tid in enumerate(ids)
            ))
            results = {}
            for tid in ids:
                event = await client.wait_result(tid, timeout=30.0)
                value, exception = cloudpickle.loads(event["data_bytes"])
                assert exception is None
                results[tid] = value
            after = counter_value(
                "covalent_tpu_agent_frames_total",
                verb="multi_invoke", encoding="binary",
            )
            return results, after - before
        finally:
            await client.close()

    results, multi_frames = run_async(flow())
    assert results == {f"batch{i}": i * i for i in range(4)}
    # All four invokes left in the same event-loop turn: one frame.
    assert multi_frames >= 1


def test_full_batch_flushes_without_waiting_out_the_window(
    tmp_path, run_async, monkeypatch
):
    """Hitting COVALENT_TPU_RPC_BATCH_MAX must ship the batch NOW — a
    wide window bounds how long a lone invoke may wait, never how fast a
    full batch goes out."""
    import time as time_mod

    from covalent_tpu_plugin import agent as agent_mod

    monkeypatch.setattr(agent_mod, "_BATCH_WINDOW_S", 0.8)
    monkeypatch.setattr(agent_mod, "_BATCH_MAX_OPS", 2)

    async def flow():
        client = await _pool_client(tmp_path)
        try:
            payload, digest, path = stage_payload(tmp_path, _make_square())
            await client.register_fn(digest, path)
            t0 = time_mod.perf_counter()
            await asyncio.gather(*(
                client.invoke(
                    f"full{i}", digest, path=path,
                    args_bytes=cloudpickle.dumps(((i,), {})),
                )
                for i in range(4)
            ))
            results = []
            for i in range(4):
                event = await client.wait_result(f"full{i}", timeout=30.0)
                value, exception = cloudpickle.loads(event["data_bytes"])
                assert exception is None
                results.append(value)
            return results, time_mod.perf_counter() - t0
        finally:
            await client.close()

    results, elapsed = run_async(flow())
    assert results == [0, 1, 4, 9]
    assert elapsed < 0.6, (
        f"full batch waited out the {0.8}s window ({elapsed:.2f}s)"
    )


def test_sequential_invokes_do_not_batch_or_stall(tmp_path, run_async):
    async def flow():
        client = await _pool_client(tmp_path)
        try:
            payload, digest, path = stage_payload(tmp_path, _make_square())
            await client.register_fn(digest, path)
            out = []
            for i in range(3):
                await client.invoke(
                    f"seq{i}", digest, path=path,
                    args_bytes=cloudpickle.dumps(((i,), {})),
                )
                event = await client.wait_result(f"seq{i}", timeout=30.0)
                value, exception = cloudpickle.loads(event["data_bytes"])
                assert exception is None
                out.append(value)
            return out
        finally:
            await client.close()

    assert run_async(flow()) == [0, 1, 4]


# ---------------------------------------------------------------------------
# Token coalescing: serve streams ride batch frames, byte-identically.
# ---------------------------------------------------------------------------


def _serve_factory(chunk=2, default_cap=8, slots=2):
    def factory():
        class Engine:
            def __init__(self):
                self.slots = slots
                self.lanes = {}

            def admit(self, rid, prompt, params):
                cap = int((params or {}).get("max_new_tokens", default_cap))
                base = int(prompt[-1])
                self.lanes[rid] = [base + i + 1 for i in range(cap)]

            def step(self):
                events = []
                for rid in list(self.lanes):
                    taken = self.lanes[rid][:chunk]
                    self.lanes[rid] = self.lanes[rid][chunk:]
                    done = not self.lanes[rid]
                    if done:
                        del self.lanes[rid]
                    events.append(
                        {"rid": rid, "tokens": taken, "done": done}
                    )
                return events

            def cancel(self, rid):
                self.lanes.pop(rid, None)

        return Engine()

    return factory


async def _stream_requests(client, tmp_path, sid, n_requests):
    payload, digest, path = stage_payload(tmp_path, _serve_factory())
    records: list = []
    done_rids: set = set()
    got_all = asyncio.Event()

    def sink(_sid, data):
        records.append(data)
        if data.get("type") == "serve.token" and data.get("done"):
            done_rids.add(data.get("rid"))
            if len(done_rids) >= n_requests:
                got_all.set()

    client.watch_serve(sid, sink)
    await client.serve_open(sid, digest, path, timeout=60.0)
    for i in range(n_requests):
        await client.serve_request(sid, f"r{i}", [i * 10])
    await asyncio.wait_for(got_all.wait(), 60.0)
    await client.serve_close(sid)
    streams: dict = {}
    for record in records:
        if record.get("type") != "serve.token":
            continue
        rid = record["rid"]
        stream = streams.setdefault(rid, [])
        # idx is the cumulative count BEFORE the chunk: exactly-once
        # splice ordering must hold inside and across batch frames.
        assert record["idx"] == len(stream)
        stream.extend(record.get("tokens") or [])
    return streams


def test_serve_tokens_coalesce_and_match_jsonl_streams(
    tmp_path, run_async
):
    async def flow():
        before = counter_value(
            "covalent_tpu_agent_frames_total",
            verb="telemetry_batch", encoding="binary",
        )
        framed_client = await _pool_client(tmp_path / "framed")
        try:
            assert framed_client.frames_active
            framed = await _stream_requests(
                framed_client, tmp_path / "framed", "sid-framed", 3
            )
        finally:
            await framed_client.close()
        batches = counter_value(
            "covalent_tpu_agent_frames_total",
            verb="telemetry_batch", encoding="binary",
        ) - before
        plain_client = await _pool_client(
            tmp_path / "plain", frames_enabled=False
        )
        try:
            plain = await _stream_requests(
                plain_client, tmp_path / "plain", "sid-plain", 3
            )
        finally:
            await plain_client.close()
        return framed, plain, batches

    framed, plain, batches = run_async(flow())
    expected = {
        f"r{i}": [i * 10 + j + 1 for j in range(8)] for i in range(3)
    }
    assert framed == expected
    assert plain == expected
    assert batches >= 1  # coalescing actually engaged on the framed arm
