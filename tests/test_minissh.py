"""Protocol-level unit tests for the vendored SSH2 stack.

The functional tier (``tests/functional/test_real_ssh.py``) proves the
stack end to end; these tests pin the wire-level invariants an interop
partner would rely on: RFC 4251 mpint encoding, binary packet framing
with and without encryption, MAC tamper rejection, and the auth/hostkey
failure modes.
"""

from __future__ import annotations

import asyncio

import pytest

# The vendored stack is built on `cryptography` (its only dependency —
# minissh.py module docstring); images without it can't exercise any of
# these wire-level tests, and the functional SSH tier skips there too.
pytest.importorskip(
    "cryptography",
    reason="minissh needs the `cryptography` package (absent in this image)",
)

from covalent_tpu_plugin.transport import minissh
from covalent_tpu_plugin.transport.minissh import (
    MiniSSHError,
    _mpint,
    _PacketStream,
    _Reader,
    _string,
    _u32,
)


def run(coro):
    return asyncio.run(coro)


def test_mpint_rfc4251_vectors():
    # RFC 4251 §5 worked examples.
    assert _mpint(0) == bytes.fromhex("00000000")
    assert _mpint(0x9A378F9B2E332A7) == bytes.fromhex(
        "0000000809a378f9b2e332a7"
    )
    assert _mpint(0x80) == bytes.fromhex("000000020080")


def test_reader_roundtrip():
    payload = _u32(7) + _string(b"abc") + bytes([1])
    r = _Reader(payload)
    assert r.u32() == 7
    assert r.string() == b"abc"
    assert r.boolean() is True


class _FeedReader:
    """Minimal StreamReader stand-in backed by a byte buffer."""

    def __init__(self, data: bytes) -> None:
        self.data = data
        self.off = 0

    async def readexactly(self, n: int) -> bytes:
        if self.off + n > len(self.data):
            raise asyncio.IncompleteReadError(b"", n)
        self.off += n
        return self.data[self.off - n:self.off]


def test_packet_roundtrip_plaintext():
    out = _PacketStream()
    inp = _PacketStream()
    wire = out.wrap(b"\x14hello-kexinit")
    # multiple of 8, length field sane, payload recovered
    assert len(wire) % 8 == 0
    got = run(inp.read_packet(_FeedReader(wire)))
    assert got == b"\x14hello-kexinit"
    assert out.seq == 1 and inp.seq == 1


def test_packet_roundtrip_encrypted_and_mac_tamper():
    key, iv, mac = b"k" * 16, b"i" * 16, b"m" * 32
    out = _PacketStream()
    out.arm(key, iv, mac, encrypt=True)
    inp = _PacketStream()
    inp.arm(key, iv, mac, encrypt=False)
    wire1 = out.wrap(b"payload-one")
    wire2 = out.wrap(b"payload-two!")
    assert b"payload-one" not in wire1  # actually encrypted
    got1 = run(inp.read_packet(_FeedReader(wire1)))
    got2 = run(inp.read_packet(_FeedReader(wire2)))  # CTR state carries over
    assert (got1, got2) == (b"payload-one", b"payload-two!")

    # One flipped ciphertext bit must fail the MAC, not decode garbage.
    out2 = _PacketStream()
    out2.arm(key, iv, mac, encrypt=True)
    inp2 = _PacketStream()
    inp2.arm(key, iv, mac, encrypt=False)
    tampered = bytearray(out2.wrap(b"payload-one"))
    tampered[17] ^= 0x01  # inside ciphertext body, outside the length word
    with pytest.raises(MiniSSHError, match="MAC"):
        run(inp2.read_packet(_FeedReader(bytes(tampered))))


def test_wrong_mac_key_rejected():
    key, iv = b"k" * 16, b"i" * 16
    out = _PacketStream()
    out.arm(key, iv, b"m" * 32, encrypt=True)
    inp = _PacketStream()
    inp.arm(key, iv, b"X" * 32, encrypt=False)
    with pytest.raises(MiniSSHError, match="MAC"):
        run(inp.read_packet(_FeedReader(out.wrap(b"data"))))


def test_invalid_encrypted_packet_length_clean_error():
    """A garbled/hostile length that is below the cipher-block minimum or
    not block-aligned must raise a clean protocol error BEFORE readexactly
    (a negative count ValueError) or a CTR keystream desync."""
    key, iv, mac = b"k" * 16, b"i" * 16, b"m" * 32
    out = _PacketStream()
    out.arm(key, iv, mac, encrypt=True)
    inp = _PacketStream()
    inp.arm(key, iv, mac, encrypt=False)

    def forged_head(length: int) -> bytes:
        # Encrypt a head block whose decrypted length field is `length`
        # using the receiver's own keystream position (fresh streams, so
        # the first block's keystream matches).
        head_plain = _u32(length) + b"\x04" + b"\x00" * 11
        return out._cipher.update(head_plain)

    # length < block - 4: readexactly count would go negative.
    with pytest.raises(MiniSSHError, match="invalid packet length"):
        run(inp.read_packet(_FeedReader(forged_head(7) + b"\x00" * 64)))
    # misaligned length: (4 + length) not a multiple of the block size.
    out2 = _PacketStream()
    out2.arm(key, iv, mac, encrypt=True)
    inp2 = _PacketStream()
    inp2.arm(key, iv, mac, encrypt=False)
    head_plain = _u32(21) + b"\x04" + b"\x00" * 11
    forged = out2._cipher.update(head_plain)
    with pytest.raises(MiniSSHError, match="invalid packet length"):
        run(inp2.read_packet(_FeedReader(forged + b"\x00" * 64)))


def test_kexinit_guess_flag_parsed_and_mismatch_discarded():
    """RFC 4253 §7 first_kex_packet_follows: a wrongly guessed first kex
    packet is reported for discard; a right guess (or no guess) is not."""
    from covalent_tpu_plugin.transport.minissh import (
        _check_kexinit,
        _kexinit_payload,
    )

    # Our own KEXINIT: no guess, right algorithms.
    assert _check_kexinit(_kexinit_payload()) is False

    def kexinit(first_lists: dict, follows: bool) -> bytes:
        lists = [
            first_lists.get("kex", minissh._KEX_ALG),
            first_lists.get("hostkey", minissh._HOSTKEY_ALG),
            minissh._CIPHER_ALG, minissh._CIPHER_ALG,
            minissh._MAC_ALG, minissh._MAC_ALG,
            minissh._COMP_ALG, minissh._COMP_ALG,
            b"", b"",
        ]
        out = bytes([minissh.MSG_KEXINIT]) + b"\x00" * 16
        for item in lists:
            out += _string(item)
        return out + bytes([1 if follows else 0]) + _u32(0)

    # Guess flag set, but the peer's first-listed algorithms match ours:
    # the guessed packet IS the right one — nothing to discard.
    assert _check_kexinit(kexinit({}, follows=True)) is False
    # Peer guessed a kex algorithm we didn't negotiate: discard one packet.
    wrong = {"kex": b"diffie-hellman-group14-sha256," + minissh._KEX_ALG}
    assert _check_kexinit(kexinit(wrong, follows=True)) is True
    # Same first-list mismatch WITHOUT the flag: nothing was sent early.
    assert _check_kexinit(kexinit(wrong, follows=False)) is False


def test_password_auth_rejects_wrong_and_unknown_users():
    async def flow():
        server = await minissh.serve(users={"u": "pw"})
        try:
            for user, pw in (("u", "wrong"), ("ghost", "pw")):
                with pytest.raises(minissh.MiniSSHAuthError):
                    await minissh.connect(
                        "127.0.0.1", server.port, user, password=pw
                    )
            conn = await minissh.connect(
                "127.0.0.1", server.port, "u", password="pw"
            )
            conn.close()
            await conn.wait_closed()
        finally:
            server.close()
            await server.wait_closed()

    run(flow())


def test_authorized_keys_bound_to_username():
    """Dict-form authorized_keys authenticate only their own user; the
    legacy list form stays global (documented test-server behavior)."""
    from cryptography.hazmat.primitives.asymmetric import ed25519

    alice_key = ed25519.Ed25519PrivateKey.generate()

    async def flow():
        server = await minissh.serve(
            authorized_keys={"alice": [alice_key.public_key()]}
        )
        try:
            conn = await minissh.connect(
                "127.0.0.1", server.port, "alice", client_key=alice_key
            )
            res = await conn.run("echo ok")
            assert res.stdout.strip() == "ok"
            conn.close()
            await conn.wait_closed()
            # Same key under a different username must be rejected.
            with pytest.raises(minissh.MiniSSHAuthError):
                await minissh.connect(
                    "127.0.0.1", server.port, "mallory",
                    client_key=alice_key,
                )
        finally:
            server.close()
            await server.wait_closed()

        # Legacy global list: any username authenticates (test fixtures).
        server = await minissh.serve(
            authorized_keys=[alice_key.public_key()]
        )
        try:
            conn = await minissh.connect(
                "127.0.0.1", server.port, "anyone", client_key=alice_key
            )
            conn.close()
            await conn.wait_closed()
        finally:
            server.close()
            await server.wait_closed()

    run(flow())


def test_put_bundle_over_minissh_roundtrip(tmp_path):
    """The generic bundle path over a REAL encrypted channel: one cat
    upload + one unpack exec, members digest-verified on the far side."""
    import hashlib
    import os
    import sys

    from covalent_tpu_plugin.transport import SSHTransport
    from covalent_tpu_plugin.transport import codec as codec_mod

    os.makedirs(tmp_path / "cas", exist_ok=True)
    items = []
    body = '{"spec": "payload", "idx": %d}\n' * 64
    for i in range(3):
        local = tmp_path / f"art{i}.json"
        local.write_text(body % i)
        digest = hashlib.sha256(local.read_bytes()).hexdigest()
        items.append((str(local), str(tmp_path / "cas" / f"art{i}"), digest))

    async def flow():
        server = await minissh.serve(users={"u": "pw"})
        try:
            transport = SSHTransport(
                "127.0.0.1", username="u", port=server.port,
                strict_host_keys=False, backend="minissh", password="pw",
            )
            await transport._open()
            stats = await transport.put_bundle(
                items, str(tmp_path / "cas" / "bundle.tar"),
                python_path=sys.executable,
                codec=codec_mod.get_codec("zlib"),
            )
            assert stats["codec"] == "zlib" and stats["members"] == 3
            for local, remote, digest in items:
                assert hashlib.sha256(
                    open(remote, "rb").read()
                ).hexdigest() == digest
            await transport.close()
        finally:
            server.close()
            await server.wait_closed()

    run(flow())


def test_exec_exit_status_and_streams():
    async def flow():
        server = await minissh.serve(users={"u": "pw"})
        try:
            conn = await minissh.connect(
                "127.0.0.1", server.port, "u", password="pw"
            )
            res = await conn.run(
                "printf a-out; printf a-err >&2; exit 41"
            )
            assert (res.exit_status, res.stdout, res.stderr) == (
                41, "a-out", "a-err"
            )
            conn.close()
            await conn.wait_closed()
        finally:
            server.close()
            await server.wait_closed()

    run(flow())


def test_large_transfer_crosses_window_boundary():
    """> initial-window payloads force WINDOW_ADJUST traffic both ways."""

    async def flow():
        server = await minissh.serve(users={"u": "pw"})
        try:
            conn = await minissh.connect(
                "127.0.0.1", server.port, "u", password="pw"
            )
            n = (1 << 21) + 12345  # one byte past the 2 MiB window
            res = await conn.run(f"head -c {n} /dev/zero | wc -c")
            assert res.stdout.strip() == str(n)
            # and upstream: stdin bigger than the server's window
            res = await conn.run("wc -c", stdin=b"z" * n)
            assert res.stdout.strip() == str(n)
            conn.close()
            await conn.wait_closed()
        finally:
            server.close()
            await server.wait_closed()

    run(flow())


def test_concurrent_channels_one_connection():
    async def flow():
        server = await minissh.serve(users={"u": "pw"})
        try:
            conn = await minissh.connect(
                "127.0.0.1", server.port, "u", password="pw"
            )
            results = await asyncio.gather(*[
                conn.run(f"echo ch{i}") for i in range(8)
            ])
            assert [r.stdout for r in results] == [
                f"ch{i}\n" for i in range(8)
            ]
            conn.close()
            await conn.wait_closed()
        finally:
            server.close()
            await server.wait_closed()

    run(flow())


def test_unknown_channel_type_refused():
    async def flow():
        server = await minissh.serve(users={"u": "pw"})
        try:
            conn = await minissh.connect(
                "127.0.0.1", server.port, "u", password="pw"
            )
            ch = conn.new_channel()
            await conn.send(
                bytes([minissh.MSG_CHANNEL_OPEN]) + _string(b"x11")
                + _u32(ch.local_id) + _u32(1 << 20) + _u32(1 << 15)
            )
            with pytest.raises(MiniSSHError, match="channel open failed"):
                await asyncio.wait_for(ch.opened, 10)
            conn.close()
            await conn.wait_closed()
        finally:
            server.close()
            await server.wait_closed()

    run(flow())


def test_auth_and_hostkey_errors_not_retryable():
    """Deterministic verdicts must bypass the transport retry classifier
    (which retries ConnectionError/OSError)."""
    from covalent_tpu_plugin.transport.minissh import (
        MiniSSHAuthError,
        MiniSSHHostKeyError,
    )

    assert not issubclass(MiniSSHAuthError, OSError)
    assert not issubclass(MiniSSHHostKeyError, OSError)
    assert issubclass(MiniSSHError, ConnectionError)  # transport errors ARE


def test_non_ed25519_client_key_clear_error(tmp_path):
    from cryptography.hazmat.primitives import serialization
    from cryptography.hazmat.primitives.asymmetric import rsa

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    path = tmp_path / "id_rsa"
    path.write_bytes(key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.OpenSSH,
        serialization.NoEncryption(),
    ))

    async def flow():
        server = await minissh.serve(users={"u": "pw"})
        try:
            with pytest.raises(ValueError, match="only ed25519"):
                await minissh.connect(
                    "127.0.0.1", server.port, "u", client_key=str(path)
                )
        finally:
            server.close()
            await server.wait_closed()

    run(flow())


def test_server_kills_command_on_channel_close(tmp_path):
    """TransportProcess.close(kill=True) semantics: closing the exec
    channel must terminate the remote command, like the other backends."""
    import os
    import time

    pidfile = tmp_path / "pid"

    async def flow():
        server = await minissh.serve(users={"u": "pw"})
        try:
            conn = await minissh.connect(
                "127.0.0.1", server.port, "u", password="pw"
            )
            proc = await conn.open_exec(
                f"echo $$ > {pidfile}; exec sleep 600"
            )
            for _ in range(100):
                if pidfile.exists() and pidfile.read_text().strip():
                    break
                await asyncio.sleep(0.05)
            pid = int(pidfile.read_text())
            proc.terminate()
            for _ in range(100):
                try:
                    os.kill(pid, 0)
                except ProcessLookupError:
                    break
                await asyncio.sleep(0.05)
                time.sleep(0)
            else:
                raise AssertionError(f"remote pid {pid} survived close")
            conn.close()
            await conn.wait_closed()
        finally:
            server.close()
            await server.wait_closed()

    run(flow())
