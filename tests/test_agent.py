"""Resident worker agent: compile the real C++ binary and drive it.

This is the native analog of the reference's transport tests — but where
the reference mocks its connection (`ssh_test.py:120-132`), the agent tests
exercise the genuine artifact: `native/agent.cc` is compiled by the same
`ensure_agent_binary` path the executor uses, then spoken to over a real
local process channel.
"""

import asyncio
import shutil

import pytest

from covalent_tpu_plugin.agent import (
    AgentClient,
    AgentError,
    agent_source_hash,
    ensure_agent_binary,
)
from covalent_tpu_plugin.transport import LocalTransport

pytestmark = pytest.mark.skipif(
    all(shutil.which(cc) is None for cc in ("g++", "c++", "clang++")),
    reason="no C++ compiler",
)


@pytest.fixture(scope="module")
def agent_binary(tmp_path_factory):
    """Compile once per test session (content-hash cached like production)."""
    cache = tmp_path_factory.mktemp("agent-cache")

    async def build():
        conn = LocalTransport()
        return await ensure_agent_binary(conn, str(cache))

    return asyncio.run(build())


def test_ensure_agent_is_idempotent(agent_binary, run_async):
    async def second():
        conn = LocalTransport()
        return await ensure_agent_binary(conn, agent_binary.rsplit("/", 1)[0])

    assert run_async(second()) == agent_binary
    assert agent_source_hash() in agent_binary


def test_agent_runs_task_and_pushes_exit(agent_binary, tmp_path, run_async):
    async def flow():
        conn = LocalTransport()
        client = await AgentClient.start(conn, agent_binary)
        out = tmp_path / "out.txt"
        pid = await client.run_task(
            "t1",
            ["/bin/sh", "-c", f"echo from-agent > {out}; exit 7"],
            log=str(tmp_path / "t1.log"),
        )
        assert pid > 0
        code, signal = await client.wait_exit("t1", timeout=10.0)
        await client.close()
        return out.read_text().strip(), code, signal

    text, code, signal = run_async(flow())
    assert text == "from-agent"
    assert code == 7
    assert signal == 0


def test_agent_multiplexes_concurrent_tasks(agent_binary, run_async):
    async def flow():
        conn = LocalTransport()
        client = await AgentClient.start(conn, agent_binary)
        # Launch out of order; the slower task must not block the faster one.
        await client.run_task("slow", ["/bin/sh", "-c", "sleep 0.5; exit 1"])
        await client.run_task("fast", ["/bin/sh", "-c", "exit 0"])
        fast = await client.wait_exit("fast", timeout=10.0)
        slow = await client.wait_exit("slow", timeout=10.0)
        await client.close()
        return fast, slow

    fast, slow = run_async(flow())
    assert fast == (0, 0)
    assert slow == (1, 0)


def test_agent_applies_cwd_and_env(agent_binary, tmp_path, run_async):
    async def flow():
        conn = LocalTransport()
        client = await AgentClient.start(conn, agent_binary)
        out = tmp_path / "envdump"
        await client.run_task(
            "t-env",
            ["/bin/sh", "-c", f"pwd > {out}; echo $AGENT_TEST_VAR >> {out}"],
            cwd=str(tmp_path),
            env={"AGENT_TEST_VAR": "tpu-native"},
        )
        await client.wait_exit("t-env", timeout=10.0)
        await client.close()
        return out.read_text().splitlines()

    lines = run_async(flow())
    assert lines[0] == str(tmp_path)
    assert lines[1] == "tpu-native"


def test_agent_kill_terminates_task(agent_binary, run_async):
    async def flow():
        conn = LocalTransport()
        client = await AgentClient.start(conn, agent_binary)
        await client.run_task("victim", ["/bin/sh", "-c", "exec sleep 30"])
        await client.kill("victim")
        code, signal = await client.wait_exit("victim", timeout=10.0)
        await client.close()
        return code, signal

    code, signal = run_async(flow())
    assert signal == 15 or code != 0


def test_agent_survivor_task_outlives_agent(agent_binary, tmp_path, run_async):
    """Children run in their own sessions: agent death must not kill them
    (the executor falls back to pid polling, like a dropped nohup channel)."""

    async def flow():
        conn = LocalTransport()
        client = await AgentClient.start(conn, agent_binary)
        marker = tmp_path / "survived"
        pid = await client.run_task(
            "orphan", ["/bin/sh", "-c", f"sleep 0.6; echo yes > {marker}"]
        )
        await client.close(    )  # shutdown before the task finishes
        for _ in range(60):
            if marker.exists():
                break
            await asyncio.sleep(0.1)
        return marker.exists(), pid

    survived, pid = run_async(flow())
    assert survived
    assert pid > 0


def test_agent_rejects_malformed_run(agent_binary, run_async):
    async def flow():
        conn = LocalTransport()
        client = await AgentClient.start(conn, agent_binary)
        with pytest.raises(AgentError):
            await client.run_task("bad", [], timeout=5.0)
        await client.close()

    run_async(flow())


def test_agent_channel_death_surfaces_as_error(agent_binary, run_async):
    async def flow():
        conn = LocalTransport()
        client = await AgentClient.start(conn, agent_binary)
        await client.run_task("t", ["/bin/sh", "-c", "sleep 5"])
        # Kill the agent process out from under the client.
        client._process._proc.kill()
        with pytest.raises(AgentError):
            await client.wait_exit("t", timeout=5.0)
        assert not client.alive
        await client.close()

    run_async(flow())


# ---------------------------------------------------------------------------
# RPC execute-by-digest verbs (PR 8): the native agent's register_fn/invoke
# protocol surface, exercised against the real compiled binary.  The
# dispatcher's fast path prefers the Python pool runtime, so these verbs
# are the native agent's protocol-uniformity guarantee — tested here so
# they cannot bit-rot invisibly.
# ---------------------------------------------------------------------------


def test_agent_register_fn_verifies_digest_in_process(
    agent_binary, tmp_path, run_async
):
    """The C++ agent sha256s the CAS artifact itself: a wrong digest is
    refused (never stored) and classifies PERMANENT; the right digest
    registers and lands in the client's registered set."""
    import hashlib

    from covalent_tpu_plugin.resilience import FaultClass, classify_error

    async def flow():
        conn = LocalTransport()
        client = await AgentClient.start(conn, agent_binary)
        try:
            artifact = tmp_path / "fn.bin"
            artifact.write_bytes(b"function payload bytes")
            good = hashlib.sha256(b"function payload bytes").hexdigest()
            bad = hashlib.sha256(b"different bytes").hexdigest()
            with pytest.raises(AgentError) as excinfo:
                await client.register_fn(bad, str(artifact), timeout=10.0)
            await client.register_fn(good, str(artifact), timeout=10.0)
            registered = client.registered_digests
        finally:
            await client.close()
        return excinfo.value, good, registered

    error, good, registered = run_async(flow())
    fault, label = classify_error(error)
    assert fault is FaultClass.PERMANENT
    assert label == "rpc_digest_mismatch"
    assert good in registered


def test_agent_native_invoke_roundtrip_via_rpc_child(
    agent_binary, tmp_path, run_async
):
    """register_fn with a runner argv, invoke with inline args: the agent
    forks the harness --rpc-child runner, pipes the command to stdin, and
    streams the started/result events back over the channel."""
    import base64
    import hashlib
    import pickle
    import sys

    import cloudpickle

    from covalent_tpu_plugin import harness as harness_mod

    def _make_mul():
        def mul(a, b):
            return a * b

        return mul

    async def flow():
        conn = LocalTransport()
        client = await AgentClient.start(conn, agent_binary)
        try:
            payload = cloudpickle.dumps(_make_mul())
            digest = hashlib.sha256(payload).hexdigest()
            artifact = tmp_path / f"{digest}.pkl"
            artifact.write_bytes(payload)
            runner = [sys.executable, harness_mod.__file__, "--rpc-child"]
            await client.register_fn(
                digest, str(artifact), runner=runner, timeout=30.0
            )
            # Unregistered digest: rejected cleanly, channel stays alive.
            with pytest.raises(AgentError):
                await client.invoke(
                    "nat-bad", "0" * 64, path=str(artifact), timeout=10.0
                )
            args_b64 = base64.b64encode(
                cloudpickle.dumps(((6, 7), {}))
            ).decode("ascii")
            pid = await client.invoke(
                "nat-1", digest, spec={"operation_id": "nat-1"},
                path=str(artifact), args_b64=args_b64, timeout=30.0,
            )
            event = await client.wait_result("nat-1", timeout=30.0)
        finally:
            await client.close()
        return pid, event

    pid, event = run_async(flow())
    assert isinstance(pid, int) and pid > 0
    assert event.get("ok") is True
    # The channel negotiates binary frames by default, so the runner's
    # result arrives as raw pickle bytes; a JSONL fallback would carry it
    # base64-inline instead — both decode to the same pair.
    raw = event.get("data_bytes")
    if raw is None:
        raw = base64.b64decode(str(event.get("data")))
    result, exception = pickle.loads(raw)
    assert exception is None
    assert result == 42


# ---------------------------------------------------------------------------
# Serving sessions: the native agent's line-switching analog of the pool
# server's session verbs (tests/test_serving.py).  The C++ agent forks the
# harness --serve-child runner with its stdin pipe HELD OPEN, forwards every
# serve_request/serve_close line verbatim, and pumps the child's stdout back
# over the channel — so the protocol observed here must be bit-identical to
# the pool server's.
# ---------------------------------------------------------------------------


def _native_serve_factory():
    """A stub engine factory, cloudpickled BY VALUE (closure-local class:
    the forked --serve-child runner cannot import the tests package)."""

    def factory():
        class Engine:
            def __init__(self):
                self.slots = 2
                self.lanes = {}

            def admit(self, rid, prompt, params):
                cap = int((params or {}).get("max_new_tokens", 4))
                base = int(prompt[-1])
                self.lanes[rid] = [base + i + 1 for i in range(cap)]

            def step(self):
                events = []
                for rid in list(self.lanes):
                    taken = self.lanes[rid][:2]
                    self.lanes[rid] = self.lanes[rid][2:]
                    done = not self.lanes[rid]
                    if done:
                        del self.lanes[rid]
                    events.append(
                        {"rid": rid, "tokens": taken, "done": done}
                    )
                return events

            def cancel(self, rid):
                self.lanes.pop(rid, None)

        return Engine()

    return factory


async def _drain_until(records, predicate, timeout=30.0):
    import time as time_mod

    deadline = time_mod.monotonic() + timeout
    while time_mod.monotonic() < deadline:
        for record in records:
            if predicate(record):
                return record
        await asyncio.sleep(0.02)
    raise AssertionError(f"no matching record in {records}")


def test_agent_native_serve_open_request_close_roundtrip(
    agent_binary, tmp_path, run_async
):
    """serve_open forks the --serve-child runner (stdin held open), a
    serve_request streams cumulative-idx token chunks back over the
    channel, and serve_close drains and acks with the served count."""
    import hashlib
    import sys

    import cloudpickle

    from covalent_tpu_plugin import harness as harness_mod

    async def flow():
        conn = LocalTransport()
        client = await AgentClient.start(conn, agent_binary)
        records: list = []
        try:
            payload = cloudpickle.dumps(_native_serve_factory())
            digest = hashlib.sha256(payload).hexdigest()
            artifact = tmp_path / f"{digest}.pkl"
            artifact.write_bytes(payload)
            runner = [sys.executable, harness_mod.__file__, "--serve-child"]
            client.watch_serve(
                "nsrv", lambda sid, data: records.append(data)
            )
            opened = await client.serve_open(
                "nsrv", digest, str(artifact), runner=runner, timeout=60.0,
            )
            await client.serve_request(
                "nsrv", "r1", [5], params={"max_new_tokens": 4}
            )
            final = await _drain_until(
                records,
                lambda r: r.get("type") == "serve.token" and r.get("done"),
            )
            closed = await client.serve_close("nsrv", timeout=30.0)
        finally:
            await client.close()
        return opened, records, final, closed

    opened, records, final, closed = run_async(flow())
    assert opened["slots"] == 2 and opened["pid"] > 0
    chunks = [r for r in records if r.get("type") == "serve.token"]
    streamed: list = []
    for chunk in chunks:
        assert chunk["rid"] == "r1"
        assert chunk["idx"] == len(streamed)  # cumulative-before-chunk
        streamed.extend(chunk["tokens"])
    assert streamed == [6, 7, 8, 9]
    assert final["done"] is True
    assert closed["served"] == 1


def test_agent_native_serve_unknown_session_rejected(
    agent_binary, run_async
):
    """A request against a sid that was never opened fails fast as a
    streamed serve.reject; closing it is a clean serve_error — the agent
    synthesizes both itself (no runner involved), channel stays alive."""
    from covalent_tpu_plugin.resilience import FaultClass, classify_error

    async def flow():
        conn = LocalTransport()
        client = await AgentClient.start(conn, agent_binary)
        records: list = []
        try:
            client.watch_serve(
                "ghost", lambda sid, data: records.append(data)
            )
            await client.serve_request("ghost", "r0", [1])
            reject = await _drain_until(
                records, lambda r: r.get("type") == "serve.reject"
            )
            with pytest.raises(AgentError, match="unknown_session") as ghost:
                await client.serve_close("ghost", timeout=10.0)
            # The channel survived both refusals: a ping still pongs.
            await client.ping(timeout=10.0)
        finally:
            await client.close()
        return reject, ghost.value

    reject, ghost_error = run_async(flow())
    assert reject["code"] == "unknown_session"
    assert reject["rid"] == "r0"
    fault, _ = classify_error(ghost_error)
    assert fault is FaultClass.PERMANENT


def test_agent_native_serve_open_failure_fails_fast(
    agent_binary, tmp_path, run_async
):
    """A runner that cannot exec must fail the open as a streamed
    serve_error within seconds (reaper announces the dead child) — not
    stall the caller for the whole open timeout."""
    import time

    async def flow():
        conn = LocalTransport()
        client = await AgentClient.start(conn, agent_binary)
        try:
            artifact = tmp_path / "factory.pkl"
            artifact.write_bytes(b"never unpickled")
            t0 = time.monotonic()
            with pytest.raises(AgentError, match="serve_open") as excinfo:
                await client.serve_open(
                    "doomed", "0" * 64, str(artifact),
                    runner=["/nonexistent-serve-runner"], timeout=30.0,
                )
            elapsed = time.monotonic() - t0
            # The channel survived the dead runner: a ping still pongs.
            await client.ping(timeout=10.0)
        finally:
            await client.close()
        return excinfo.value, elapsed

    error, elapsed = run_async(flow())
    assert "runner_exited" in str(error) or "spawn_failed" in str(error)
    assert elapsed < 10.0, f"open took {elapsed:.1f}s — waited out the timeout"


# ---------------------------------------------------------------------------
# Binary frame protocol on the native agent: negotiation, framed invoke
# round-trip through the runner child, and parser fuzz — malformed frames
# must fail loud as clean errors and never hang or kill the agent.
# ---------------------------------------------------------------------------


def test_native_agent_negotiates_frames(agent_binary, run_async):
    async def flow():
        conn = LocalTransport()
        client = await AgentClient.start(conn, agent_binary)
        try:
            active = client.frames_active
            banner = dict(client._banner)
            await client.ping(timeout=10.0)
        finally:
            await client.close()
        return active, banner

    active, banner = run_async(flow())
    assert active is True
    assert banner.get("frames") == 1
    # No codecs advertised: the native agent never inflates bodies itself.
    assert not banner.get("codecs")


def test_native_agent_framed_invoke_roundtrip(agent_binary, tmp_path, run_async):
    """args as a raw frame body into the forked --rpc-child runner, the
    framed result passed back verbatim through the stream pump."""
    import hashlib
    import pickle
    import sys

    import cloudpickle

    from covalent_tpu_plugin import harness as harness_mod

    def _make_add():
        def add(a, b):
            return a + b

        return add

    async def flow():
        conn = LocalTransport()
        client = await AgentClient.start(conn, agent_binary)
        try:
            assert client.frames_active
            payload = cloudpickle.dumps(_make_add())
            digest = hashlib.sha256(payload).hexdigest()
            artifact = tmp_path / f"{digest}.pkl"
            artifact.write_bytes(payload)
            runner = [sys.executable, harness_mod.__file__, "--rpc-child"]
            await client.register_fn(
                digest, str(artifact), runner=runner, timeout=30.0
            )
            await client.invoke(
                "natframe", digest, path=str(artifact),
                args_bytes=cloudpickle.dumps(((19, 23), {})), timeout=30.0,
            )
            event = await client.wait_result("natframe", timeout=30.0)
        finally:
            await client.close()
        return event

    event = run_async(flow())
    assert event.get("ok") is True
    assert event.get("data_bytes") is not None, (
        "runner result did not ride a binary frame"
    )
    import pickle

    result, exception = pickle.loads(event["data_bytes"])
    assert exception is None
    assert result == 42


def test_native_agent_survives_frame_garbage(agent_binary, run_async):
    from covalent_tpu_plugin.transport import frames

    async def flow():
        conn = LocalTransport()
        client = await AgentClient.start(conn, agent_binary)
        try:
            assert client.frames_active
            garbage = [
                bytes([frames.MAGIC[0], 0x13]) + b"not a frame\n",
                frames.HEADER.pack(frames.MAGIC, 9, 0, 0, 1, 1) + b"\n",
                frames.HEADER.pack(
                    frames.MAGIC, frames.VERSION, 0, 0,
                    frames.MAX_HEADER_BYTES + 7, 0,
                ) + b"\n",
                # well-framed but non-JSON header: consumed in sync
                frames.HEADER.pack(
                    frames.MAGIC, frames.VERSION, 0, 0, 4, 0
                ) + b"{bad",
                b"line noise without any structure\n",
            ]
            for chunk in garbage:
                await client._process.write_bytes(chunk)
                # The agent must keep answering after every injection.
                await client.ping(timeout=10.0)
            return True
        finally:
            await client.close()

    assert run_async(flow()) is True


def test_native_agent_multi_invoke_refused_per_op(agent_binary, run_async):
    """The native agent cannot batch (one runner fork per invocation); a
    multi_invoke frame is refused cleanly per op id, channel alive."""
    from covalent_tpu_plugin.transport import frames

    async def flow():
        conn = LocalTransport()
        client = await AgentClient.start(conn, agent_binary)
        try:
            await client._send_frame(
                frames.VERB_MULTI_INVOKE,
                {"cmd": "multi_invoke", "digest": "d" * 64,
                 "ops": [{"id": "mop1"}, {"id": "mop2"}],
                 "args_lens": [1, 1], "_body": "args_bytes"},
                b"xy",
            )
            await client._wait(
                lambda c: "mop1" in c._errors and "mop2" in c._errors, 15.0
            )
            errors = dict(client._errors)
            await client.ping(timeout=10.0)
        finally:
            await client.close()
        return errors

    errors = run_async(flow())
    assert "pool runtime" in errors["mop1"]
    assert "pool runtime" in errors["mop2"]
