"""Resident worker agent: compile the real C++ binary and drive it.

This is the native analog of the reference's transport tests — but where
the reference mocks its connection (`ssh_test.py:120-132`), the agent tests
exercise the genuine artifact: `native/agent.cc` is compiled by the same
`ensure_agent_binary` path the executor uses, then spoken to over a real
local process channel.
"""

import asyncio
import shutil

import pytest

from covalent_tpu_plugin.agent import (
    AgentClient,
    AgentError,
    agent_source_hash,
    ensure_agent_binary,
)
from covalent_tpu_plugin.transport import LocalTransport

pytestmark = pytest.mark.skipif(
    all(shutil.which(cc) is None for cc in ("g++", "c++", "clang++")),
    reason="no C++ compiler",
)


@pytest.fixture(scope="module")
def agent_binary(tmp_path_factory):
    """Compile once per test session (content-hash cached like production)."""
    cache = tmp_path_factory.mktemp("agent-cache")

    async def build():
        conn = LocalTransport()
        return await ensure_agent_binary(conn, str(cache))

    return asyncio.run(build())


def test_ensure_agent_is_idempotent(agent_binary, run_async):
    async def second():
        conn = LocalTransport()
        return await ensure_agent_binary(conn, agent_binary.rsplit("/", 1)[0])

    assert run_async(second()) == agent_binary
    assert agent_source_hash() in agent_binary


def test_agent_runs_task_and_pushes_exit(agent_binary, tmp_path, run_async):
    async def flow():
        conn = LocalTransport()
        client = await AgentClient.start(conn, agent_binary)
        out = tmp_path / "out.txt"
        pid = await client.run_task(
            "t1",
            ["/bin/sh", "-c", f"echo from-agent > {out}; exit 7"],
            log=str(tmp_path / "t1.log"),
        )
        assert pid > 0
        code, signal = await client.wait_exit("t1", timeout=10.0)
        await client.close()
        return out.read_text().strip(), code, signal

    text, code, signal = run_async(flow())
    assert text == "from-agent"
    assert code == 7
    assert signal == 0


def test_agent_multiplexes_concurrent_tasks(agent_binary, run_async):
    async def flow():
        conn = LocalTransport()
        client = await AgentClient.start(conn, agent_binary)
        # Launch out of order; the slower task must not block the faster one.
        await client.run_task("slow", ["/bin/sh", "-c", "sleep 0.5; exit 1"])
        await client.run_task("fast", ["/bin/sh", "-c", "exit 0"])
        fast = await client.wait_exit("fast", timeout=10.0)
        slow = await client.wait_exit("slow", timeout=10.0)
        await client.close()
        return fast, slow

    fast, slow = run_async(flow())
    assert fast == (0, 0)
    assert slow == (1, 0)


def test_agent_applies_cwd_and_env(agent_binary, tmp_path, run_async):
    async def flow():
        conn = LocalTransport()
        client = await AgentClient.start(conn, agent_binary)
        out = tmp_path / "envdump"
        await client.run_task(
            "t-env",
            ["/bin/sh", "-c", f"pwd > {out}; echo $AGENT_TEST_VAR >> {out}"],
            cwd=str(tmp_path),
            env={"AGENT_TEST_VAR": "tpu-native"},
        )
        await client.wait_exit("t-env", timeout=10.0)
        await client.close()
        return out.read_text().splitlines()

    lines = run_async(flow())
    assert lines[0] == str(tmp_path)
    assert lines[1] == "tpu-native"


def test_agent_kill_terminates_task(agent_binary, run_async):
    async def flow():
        conn = LocalTransport()
        client = await AgentClient.start(conn, agent_binary)
        await client.run_task("victim", ["/bin/sh", "-c", "exec sleep 30"])
        await client.kill("victim")
        code, signal = await client.wait_exit("victim", timeout=10.0)
        await client.close()
        return code, signal

    code, signal = run_async(flow())
    assert signal == 15 or code != 0


def test_agent_survivor_task_outlives_agent(agent_binary, tmp_path, run_async):
    """Children run in their own sessions: agent death must not kill them
    (the executor falls back to pid polling, like a dropped nohup channel)."""

    async def flow():
        conn = LocalTransport()
        client = await AgentClient.start(conn, agent_binary)
        marker = tmp_path / "survived"
        pid = await client.run_task(
            "orphan", ["/bin/sh", "-c", f"sleep 0.6; echo yes > {marker}"]
        )
        await client.close(    )  # shutdown before the task finishes
        for _ in range(60):
            if marker.exists():
                break
            await asyncio.sleep(0.1)
        return marker.exists(), pid

    survived, pid = run_async(flow())
    assert survived
    assert pid > 0


def test_agent_rejects_malformed_run(agent_binary, run_async):
    async def flow():
        conn = LocalTransport()
        client = await AgentClient.start(conn, agent_binary)
        with pytest.raises(AgentError):
            await client.run_task("bad", [], timeout=5.0)
        await client.close()

    run_async(flow())


def test_agent_channel_death_surfaces_as_error(agent_binary, run_async):
    async def flow():
        conn = LocalTransport()
        client = await AgentClient.start(conn, agent_binary)
        await client.run_task("t", ["/bin/sh", "-c", "sleep 5"])
        # Kill the agent process out from under the client.
        client._process._proc.kill()
        with pytest.raises(AgentError):
            await client.wait_exit("t", timeout=5.0)
        assert not client.alive
        await client.close()

    run_async(flow())
