"""Serving-side parallelism + persistence.

Decode under tensor parallelism: sharding the params over a mesh must not
change the generated tokens (the logical-axis annotations on every dense
let pjit insert the collectives).  And the serving trees (int8 quant,
LoRA adapters) must survive a checkpoint round-trip bit-exactly.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from covalent_tpu_plugin.models import (
    TransformerConfig,
    TransformerLM,
    generate,
    quantize_lm,
    quantize_then_lora,
)
from covalent_tpu_plugin.parallel import MeshPlan, make_mesh

BASE = TransformerConfig(
    vocab_size=64,
    d_model=32,
    n_layers=2,
    n_heads=4,
    d_ff=64,
    max_seq=32,
    dtype=jnp.float32,
    attention="reference",
    scan_layers=False,
)


@pytest.fixture(scope="module")
def lm():
    model = TransformerLM(BASE)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (4, 5), 0, BASE.vocab_size)
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]
    return model, params, prompt


def test_sharded_decode_matches_unsharded(lm):
    """Tensor-parallel generation: params sharded over tensor=2, batch
    over data=2 — tokens must equal the single-device run exactly."""
    from covalent_tpu_plugin.parallel.sharding import param_shardings, unbox

    model, params, prompt = lm
    want = np.asarray(generate(model, params, prompt, 6))

    mesh = make_mesh(MeshPlan(data=2, tensor=2))
    shardings = param_shardings(params, mesh)
    sharded_params = jax.device_put(unbox(params), shardings)
    with mesh:
        got = np.asarray(generate(model, sharded_params, prompt, 6))
    np.testing.assert_array_equal(got, want)


def test_quant_and_lora_trees_roundtrip_checkpoint(tmp_path, lm):
    from covalent_tpu_plugin.utils.checkpoint import (
        restore_checkpoint,
        save_checkpoint,
    )

    model, params, prompt = lm
    _, qparams = quantize_lm(model, params)
    _, qlparams = quantize_then_lora(model, params, rank=4)

    save_checkpoint({"quant": qparams, "qlora": qlparams}, 1, tmp_path)
    restored = restore_checkpoint(1, tmp_path)

    for name, original in (("quant", qparams), ("qlora", qlparams)):
        flat_orig = jax.tree_util.tree_flatten_with_path(original)[0]
        flat_rest = dict(jax.tree_util.tree_flatten_with_path(restored[name])[0])
        for path, leaf in flat_orig:
            got = flat_rest[path]
            assert got.dtype == leaf.dtype, (name, path)  # int8 stays int8
            np.testing.assert_array_equal(np.asarray(got), np.asarray(leaf))


def test_sharded_continuous_batching_matches_unsharded(lm):
    """Continuous batching over tensor-sharded params: the donated
    admission-wave and decode-scan executables must produce the same
    tokens as the single-device loop (pjit inserts the collectives; the
    fixed-slot host loop never looks at placement)."""
    from covalent_tpu_plugin.models import continuous_generate
    from covalent_tpu_plugin.parallel.sharding import param_shardings, unbox

    model, params, _ = lm
    prompts = [
        np.asarray(
            jax.random.randint(
                jax.random.PRNGKey(10 + i), (3 + i % 3,), 0,
                BASE.vocab_size,
            ),
            np.int32,
        )
        for i in range(5)
    ]
    caps = [4, 9, 2, 6, 5]
    want = continuous_generate(
        model, params, prompts, caps, max_batch=2, sync_steps=4
    )

    mesh = make_mesh(MeshPlan(data=2, tensor=2))
    shardings = param_shardings(params, mesh)
    sharded_params = jax.device_put(unbox(params), shardings)
    with mesh:
        got = continuous_generate(
            model, sharded_params, prompts, caps, max_batch=2,
            sync_steps=4,
        )
    for w, g in zip(want, got):
        np.testing.assert_array_equal(g, w)
