"""Fused vocab-chunked cross-entropy (ops/xent.py) vs the dense path.

Exactness contract: at f32 inputs the fused loss and BOTH gradients match
a dense logits + stable log-softmax reference to float tolerance (the
chunked online logsumexp is the same math, reassociated); through the
model at bf16 the comparison is against the standard `lm_loss` path
within bf16-matmul tolerance (the fused path intentionally runs the
lm_head matmul with bf16 inputs on the MXU-native path, where the
logits_dtype=f32 default upcasts first).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import pytest

from covalent_tpu_plugin.ops.xent import fused_cross_entropy


def _ref(x, w, labels):
    logits = jax.lax.dot_general(
        x, w.astype(x.dtype),
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    lab = jnp.take_along_axis(logits, labels[:, None], 1)[:, 0]
    return jnp.mean(lse - lab)


@pytest.mark.parametrize("chunk", [32, 64, 256])
def test_fused_xent_matches_dense(chunk):
    T, d, V = 48, 32, 256
    x = jax.random.normal(jax.random.PRNGKey(0), (T, d), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(1), (d, V)) * 0.1
    labels = jax.random.randint(jax.random.PRNGKey(2), (T,), 0, V)
    lf = fused_cross_entropy(x, w, labels, chunk)
    lr = _ref(x, w, labels)
    assert abs(float(lf) - float(lr)) < 1e-5


def test_fused_xent_grads_match_dense():
    T, d, V, chunk = 48, 32, 256, 64
    x = jax.random.normal(jax.random.PRNGKey(0), (T, d), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(1), (d, V)) * 0.1
    labels = jax.random.randint(jax.random.PRNGKey(2), (T,), 0, V)
    dxf, dwf = jax.grad(
        lambda x, w: fused_cross_entropy(x, w, labels, chunk), argnums=(0, 1)
    )(x, w)
    dxr, dwr = jax.grad(
        lambda x, w: _ref(x, w, labels), argnums=(0, 1)
    )(x, w)
    assert float(jnp.abs(dxf - dxr).max()) < 1e-6
    assert float(jnp.abs(dwf - dwr).max()) < 1e-6


def test_fused_xent_rejects_ragged_vocab():
    x = jnp.zeros((4, 8))
    w = jnp.zeros((8, 100))
    labels = jnp.zeros((4,), jnp.int32)
    with pytest.raises(ValueError, match="divisible"):
        fused_cross_entropy(x, w, labels, 64)


def test_lm_loss_fused_path_matches_standard():
    from covalent_tpu_plugin.models import TransformerConfig, TransformerLM
    from covalent_tpu_plugin.models.train import lm_loss

    cfg = TransformerConfig(
        vocab_size=128, d_model=64, n_layers=2, n_heads=2, d_ff=128,
        max_seq=32, scan_layers=False,
    )
    model = TransformerLM(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(3), (4, 17), 0, 128)
    params = model.init(jax.random.PRNGKey(4), tokens[:, :-1])["params"]
    batch = {"tokens": tokens}
    l_std = float(lm_loss(params, model.apply, batch))
    l_fused = float(lm_loss(params, model.apply, batch, vocab_chunk=32))
    assert abs(l_std - l_fused) < 2e-3
    g_std = jax.grad(lambda p: lm_loss(p, model.apply, batch))(params)
    g_fused = jax.grad(
        lambda p: lm_loss(p, model.apply, batch, vocab_chunk=32)
    )(params)
    rel = jax.tree_util.tree_map(
        lambda a, b: float(
            jnp.abs(a - b).max() / (jnp.abs(a).max() + 1e-9)
        ),
        g_std, g_fused,
    )
    assert max(jax.tree_util.tree_leaves(rel)) < 0.05


def test_fused_xent_trains():
    """A few adamw steps through the fused path actually reduce loss."""
    import optax

    from covalent_tpu_plugin.models import TransformerConfig, TransformerLM
    from covalent_tpu_plugin.models.data import synthetic_lm_batch
    from covalent_tpu_plugin.models.train import TrainState, lm_loss

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=2, d_ff=64,
        max_seq=33, scan_layers=False,
    )
    model = TransformerLM(cfg)
    tokens0 = jnp.asarray(
        synthetic_lm_batch(8, 33, 64, seed=0)["tokens"]
    )
    params = model.init(jax.random.PRNGKey(0), tokens0[:, :-1])["params"]
    state = TrainState.create(
        apply_fn=model.apply, params=params, tx=optax.adamw(1e-2)
    )

    @jax.jit
    def step(state, tokens):
        loss, grads = jax.value_and_grad(
            lambda p: lm_loss(
                p, state.apply_fn, {"tokens": tokens}, vocab_chunk=32
            )
        )(state.params)
        return state.apply_gradients(grads=grads), loss

    losses = []
    for i in range(30):
        tokens = jnp.asarray(
            synthetic_lm_batch(8, 33, 64, seed=1 + i)["tokens"]
        )
        state, loss = step(state, tokens)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8, losses


def test_fused_xent_under_sharded_train_step():
    """The fused loss composes with the mesh story: a dp x tp sharded
    train step (lm_head vocab-sharded over tensor) produces the same
    loss and gradient norm as the standard logits path."""
    import optax

    from covalent_tpu_plugin.models import TransformerConfig, TransformerLM
    from covalent_tpu_plugin.models.data import synthetic_lm_batch
    from covalent_tpu_plugin.models.train import (
        lm_loss,
        make_sharded_train_state,
        make_train_step,
    )
    from covalent_tpu_plugin.parallel import MeshPlan, make_mesh

    mesh = make_mesh(MeshPlan(data=2, tensor=4))
    cfg = TransformerConfig(
        vocab_size=128, d_model=64, n_layers=2, n_heads=4, d_ff=128,
        max_seq=33, scan_layers=False,
    )
    model = TransformerLM(cfg)
    tokens = jnp.asarray(synthetic_lm_batch(8, 33, 128, seed=0)["tokens"])
    state, shardings = make_sharded_train_state(
        model, optax.adamw(1e-2), jax.random.PRNGKey(0), tokens[:, :-1],
        mesh,
    )

    def loss_fused(params, apply_fn, batch):
        return lm_loss(params, apply_fn, batch, vocab_chunk=32)

    step_std = make_train_step(lm_loss, mesh, shardings, donate_state=False)
    step_fused = make_train_step(
        loss_fused, mesh, shardings, donate_state=False
    )
    # No `with mesh:` around the jitted steps: an ambient mesh makes flax
    # apply the params' *logical* axis names as sharding constraints during
    # tracing (Partitioned.unbox), which physical meshes reject; the steps
    # carry explicit in/out shardings and need no ambient mesh.
    _, m_std = step_std(state, {"tokens": tokens})
    _, m_fused = step_fused(state, {"tokens": tokens})
    assert abs(float(m_std["loss"]) - float(m_fused["loss"])) < 5e-3
    gs, gf = float(m_std["grad_norm"]), float(m_fused["grad_norm"])
    assert abs(gs - gf) / gs < 0.02
