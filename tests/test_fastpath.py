"""Dispatch fast-path tests: wire codecs, bundled staging, pipelined
dispatch, and DAG-driven connection prewarm (ISSUE 5).

The codec/bundle layer is exercised three ways: against the LocalTransport
override (direct-fs fast path), against the *generic* base-class
implementation (via a no-fault ChaosTransport wrapper, whose put/run ride
the real local shell — the same code path SSH/minissh use), and against a
truncating chaos wrapper to prove a torn bundle is a clean PERMANENT
integrity error, not a retry storm.
"""

import asyncio
import os
import sys

import pytest

from covalent_tpu_plugin.cache import CASIndex, file_digest
from covalent_tpu_plugin.obs.metrics import REGISTRY
from covalent_tpu_plugin.resilience import FaultClass, classify_error
from covalent_tpu_plugin.transport import (
    ChaosPlan,
    ChaosTransport,
    CodecIntegrityError,
    LocalTransport,
)
from covalent_tpu_plugin.transport import codec as codec_mod

from .helpers import (
    FakeTransport,
    make_local_executor,
    scripted_ok_responses,
)

#: ~8 KiB of structured, highly-compressible text (a realistic spec/manifest
#: payload shape) — comfortably above MIN_COMPRESS_BYTES.
COMPRESSIBLE = (
    '{"worker": 0, "env": {"JAX_PLATFORMS": "tpu"}, "path": '
    '"/workdir/covalent-tpu/artifacts/"}\n'
) * 80


def counter_value(counter, **labels) -> float:
    child = counter.labels(**labels) if labels else counter
    return child.value


def write(tmp_path, name: str, content: str) -> str:
    path = tmp_path / name
    path.write_text(content)
    return str(path)


# --------------------------------------------------------------------- #
# Codec primitives + negotiation
# --------------------------------------------------------------------- #


def test_codec_zlib_roundtrip():
    codec = codec_mod.get_codec("zlib")
    data = COMPRESSIBLE.encode()
    packed = codec.compress(data)
    assert len(packed) < len(data)
    assert codec.decompress(packed) == data


def test_pick_codec_intersects_with_raw_fallback():
    assert codec_mod.pick_codec(["zlib"]).name == "zlib"
    assert codec_mod.pick_codec([]) is None
    assert codec_mod.pick_codec(["lz4"]) is None  # unknown remote offer
    assert "zlib" in codec_mod.available_codecs()


def test_probe_clause_parse_and_garbled_fallback():
    clause = codec_mod.probe_clause(sys.executable)
    assert codec_mod.PROBE_PREFIX in clause
    assert clause.endswith("true)")  # can never fail the pre-flight chain
    assert codec_mod.probe_clause(sys.executable, compress="off") is None
    stdout = f"{codec_mod.PROBE_PREFIX}zlib\n3\n"
    assert codec_mod.parse_probe(stdout) == ["zlib"]
    # Garbled/absent probe output degrades to raw, never an error.
    assert codec_mod.parse_probe("3\n") == []
    assert codec_mod.parse_probe("") == []


def test_executor_negotiates_codec_from_preflight(tmp_path, run_async):
    """The pre-flight compound carries the probe; its output decides the
    per-connection codec, with raw as the fallback for silent workers."""
    from covalent_tpu_plugin.tpu import TPUExecutor

    ex = TPUExecutor(
        transport="local", cache_dir=str(tmp_path / "c"),
        remote_cache=str(tmp_path / "r"), use_agent=False,
    )
    from covalent_tpu_plugin.transport.base import CommandResult

    assert codec_mod.PROBE_PREFIX in ex._preflight_command()
    advertising = FakeTransport({
        "mkdir -p": CommandResult(
            0, f"{codec_mod.PROBE_PREFIX}zlib\n3\n", ""
        ),
    })
    silent = FakeTransport(scripted_ok_responses(), address="mute")
    run_async(ex._preflight(advertising, key="fake:w1"))
    run_async(ex._preflight(silent, key="fake:w2"))
    assert ex._codec_for("fake:w1", advertising).name == "zlib"
    assert ex._codec_for("fake:w2", silent) is None  # raw fallback
    # Zero-wire transports always ship raw, whatever was advertised.
    assert ex._codec_for("fake:w1", LocalTransport()) is None


# --------------------------------------------------------------------- #
# put_file: compressed single-artifact publish
# --------------------------------------------------------------------- #


def test_put_file_compressed_publish_verifies_decompressed_digest(
    tmp_path, run_async
):
    src = write(tmp_path, "artifact.json", COMPRESSIBLE)
    dst = str(tmp_path / "cas" / "artifact.json")
    os.makedirs(tmp_path / "cas")
    digest = file_digest(src)

    stats = run_async(codec_mod.put_file(
        LocalTransport(), src, dst,
        codec=codec_mod.get_codec("zlib"), python_path=sys.executable,
        digest=digest,
    ))
    # The digest the remote side verified is of the DECOMPRESSED bytes.
    assert open(dst).read() == COMPRESSIBLE
    assert file_digest(dst) == digest
    assert stats["codec"] == "zlib"
    assert stats["wire_bytes"] < os.path.getsize(src)


def test_put_file_skips_compression_when_unprofitable(tmp_path, run_async):
    incompressible = tmp_path / "noise.bin"
    incompressible.write_bytes(os.urandom(4096))
    small = write(tmp_path, "tiny.txt", "x")
    for src in (str(incompressible), small):
        dst = f"{src}.shipped"
        stats = run_async(codec_mod.put_file(
            LocalTransport(), src, dst,
            codec=codec_mod.get_codec("zlib"), python_path=sys.executable,
        ))
        assert stats["codec"] == "raw"
        assert open(dst, "rb").read() == open(src, "rb").read()


def test_put_file_digest_mismatch_is_permanent_integrity_error(
    tmp_path, run_async
):
    src = write(tmp_path, "artifact.json", COMPRESSIBLE)
    dst = str(tmp_path / "published")
    with pytest.raises(CodecIntegrityError, match="digest"):
        run_async(codec_mod.put_file(
            LocalTransport(), src, dst,
            codec=codec_mod.get_codec("zlib"), python_path=sys.executable,
            digest="0" * 64,
        ))
    fault, label = classify_error(CodecIntegrityError("x"))
    assert fault is FaultClass.PERMANENT
    assert not os.path.exists(dst)  # nothing published on failure


def test_get_file_compressed_roundtrip_and_raw_small(tmp_path, run_async):
    big = write(tmp_path, "result.pkl", COMPRESSIBLE)
    fetched = str(tmp_path / "fetched.pkl")
    stats = run_async(codec_mod.get_file(
        LocalTransport(), big, fetched,
        codec=codec_mod.get_codec("zlib"), python_path=sys.executable,
    ))
    assert open(fetched).read() == COMPRESSIBLE
    assert stats["codec"] == "zlib"
    assert stats["wire_bytes"] < os.path.getsize(big)
    small = write(tmp_path, "small.pkl", "tiny")
    stats = run_async(codec_mod.get_file(
        LocalTransport(), small, str(tmp_path / "small.out"),
        codec=codec_mod.get_codec("zlib"), python_path=sys.executable,
    ))
    assert stats["codec"] == "raw"  # remote side declined: too small


# --------------------------------------------------------------------- #
# put_bundle: one put + one exec for N artifacts
# --------------------------------------------------------------------- #


def bundle_items(tmp_path, n=3):
    # The executor's pre-flight mkdir -p creates the remote cas dir; these
    # transport-level tests stand in for it here.
    os.makedirs(tmp_path / "cas", exist_ok=True)
    items = []
    for i in range(n):
        local = write(tmp_path, f"art{i}.json", f"{COMPRESSIBLE}#{i}")
        remote = str(tmp_path / "cas" / f"art{i}.json")
        items.append((local, remote, file_digest(local)))
    return items


def test_put_bundle_generic_path_roundtrip(tmp_path, run_async):
    """The base-class tar+unpack path (what SSH/minissh ride), driven
    through a no-fault chaos wrapper over the real local shell."""
    conn = ChaosTransport(LocalTransport(), ChaosPlan())
    items = bundle_items(tmp_path)
    stats = run_async(conn.put_bundle(
        items, str(tmp_path / "cas" / "bundle.tar"),
        python_path=sys.executable, codec=codec_mod.get_codec("zlib"),
    ))
    for local, remote, digest in items:
        assert file_digest(remote) == digest
        assert open(remote).read() == open(local).read()
    assert stats["members"] == 3 and stats["ops"] == 2
    assert stats["codec"] == "zlib"
    raw_total = sum(os.path.getsize(l) for l, _, _ in items)
    assert stats["wire_bytes"] < raw_total  # compressed tar beat raw files
    # The bundle temp file was consumed by the unpack exec.
    assert not os.path.exists(tmp_path / "cas" / "bundle.tar")


def test_put_bundle_local_override_is_direct_copy(tmp_path, run_async):
    items = bundle_items(tmp_path)
    stats = run_async(LocalTransport().put_bundle(
        items, str(tmp_path / "cas" / "bundle.tar"),
        python_path=sys.executable, codec=codec_mod.get_codec("zlib"),
    ))
    for local, remote, digest in items:
        assert file_digest(remote) == digest
    assert stats["ops"] == 1 and stats["codec"] == "raw"  # zero wire


def test_truncated_bundle_is_permanent_integrity_error(tmp_path, run_async):
    """A bundle torn in flight fails the unpack's digest/decompress check
    loudly — classified PERMANENT so the retry driver never re-ships the
    same corrupt bytes (no retry storm)."""
    plan = ChaosPlan(truncate_uploads=1, max_faults=1)
    conn = ChaosTransport(LocalTransport(), plan)
    items = bundle_items(tmp_path)
    with pytest.raises(CodecIntegrityError, match="digest|decompress"):
        run_async(conn.put_bundle(
            items, str(tmp_path / "cas" / "bundle.tar"),
            python_path=sys.executable, codec=codec_mod.get_codec("zlib"),
        ))
    assert plan.faults_injected == 1
    for _, remote, _ in items:
        assert not os.path.exists(remote)  # nothing half-published
    fault, _ = classify_error(CodecIntegrityError("torn"))
    assert fault is FaultClass.PERMANENT


# --------------------------------------------------------------------- #
# CAS integration: ensure_bundle hits/misses/single-flight
# --------------------------------------------------------------------- #


def test_ensure_bundle_ships_once_then_hits(tmp_path, run_async):
    from covalent_tpu_plugin.cache import CAS_UPLOADS_TOTAL

    fake = FakeTransport()
    index = CASIndex()
    items = bundle_items(tmp_path)
    hits0 = counter_value(CAS_UPLOADS_TOTAL, result="hit")
    misses0 = counter_value(CAS_UPLOADS_TOTAL, result="miss")

    async def flow():
        await index.ensure_bundle("k", fake, items)
        await index.ensure_bundle("k", fake, items)

    run_async(flow())
    assert len(fake.puts) == 1  # one bundle, second call all-hit
    assert "/bundle-" in fake.puts[0][1]
    assert counter_value(CAS_UPLOADS_TOTAL, result="miss") - misses0 == 3
    assert counter_value(CAS_UPLOADS_TOTAL, result="hit") - hits0 == 3


def test_ensure_bundle_single_missing_degrades_to_per_file(
    tmp_path, run_async
):
    fake = FakeTransport()
    index = CASIndex()
    items = bundle_items(tmp_path)

    async def flow():
        # Pre-warm two of three digests; the bundle path must not pay tar
        # overhead to ship one file.
        index._present["k"] = {items[0][2], items[1][2]}
        await index.ensure_bundle("k", fake, items)

    run_async(flow())
    assert len(fake.puts) == 1
    assert ".tmp-" in fake.puts[0][1]  # per-file temp+rename, not a bundle


def test_ensure_bundle_dedupes_identical_payloads(tmp_path, run_async):
    """Two artifacts with the same digest (a map fan-out sharing one
    function pickle) bundle once."""
    fake = FakeTransport()
    index = CASIndex()
    local = write(tmp_path, "shared.pkl", COMPRESSIBLE)
    digest = file_digest(local)
    items = [
        (local, str(tmp_path / "cas" / "a.pkl"), digest),
        (local, str(tmp_path / "cas" / "b.pkl"), digest),
        (write(tmp_path, "other.pkl", "other"),
         str(tmp_path / "cas" / "c.pkl"), "d" * 64),
    ]
    run_async(index.ensure_bundle("k", fake, items))
    assert len(fake.puts) == 1  # one bundle: {shared, other}, not 3 members


# --------------------------------------------------------------------- #
# Executor end-to-end: bundled + compressed dispatch over a "wire"
# --------------------------------------------------------------------- #


def test_run_bundled_compressed_dispatch_end_to_end(tmp_path, run_async):
    """A full electron through the fast path: chaos wrapper (simulated
    wire) forces real codec negotiation + the generic bundle, the harness
    verifies the CAS digest of the decompressed function pickle, and the
    wire/staging metrics record the savings."""
    wire0 = counter_value(
        codec_mod.WIRE_BYTES_TOTAL, direction="up", codec="zlib"
    )
    from covalent_tpu_plugin.cache import STAGING_OPS_TOTAL

    bundled0 = counter_value(STAGING_OPS_TOTAL, mode="bundled")
    ex = make_local_executor(
        tmp_path, chaos=ChaosPlan(), poll_freq=0.05,
    )
    payload = COMPRESSIBLE

    def electron(text):
        return len(text)

    async def flow():
        try:
            return await ex.run(
                electron, [payload], {},
                {"dispatch_id": "fastpath", "node_id": 0},
            )
        finally:
            await ex.close()

    assert run_async(flow()) == len(payload)
    assert counter_value(
        codec_mod.WIRE_BYTES_TOTAL, direction="up", codec="zlib"
    ) > wire0  # compressed bytes actually crossed the simulated wire
    assert counter_value(STAGING_OPS_TOTAL, mode="bundled") - bundled0 == 2
    assert "wall_overhead" in ex.last_timings


def test_run_pinned_codec_compresses_result_download(tmp_path, run_async):
    """compress="zlib" (pinned) engages the compressed result fetch, keyed
    by the worker's POOL key (the configured address — regression: keying
    by conn.address broke for user@host workers)."""
    down0 = counter_value(
        codec_mod.WIRE_BYTES_TOTAL, direction="down", codec="zlib"
    )
    ex = make_local_executor(
        tmp_path, chaos=ChaosPlan(), compress="zlib", poll_freq=0.05,
    )
    payload = COMPRESSIBLE * 4  # result pickle big enough to pack

    def electron(text):
        return text  # echo: the RESULT is the large compressible payload

    async def flow():
        try:
            return await ex.run(
                electron, [payload], {},
                {"dispatch_id": "pinned", "node_id": 0},
            )
        finally:
            await ex.close()

    assert run_async(flow()) == payload
    assert counter_value(
        codec_mod.WIRE_BYTES_TOTAL, direction="down", codec="zlib"
    ) > down0  # the fetch actually rode the wire compressed


def test_run_unpicklable_electron_still_fails_cleanly(tmp_path, run_async):
    """The pipelined stage leg (serialization on a thread, overlapping the
    dial) must surface staging errors exactly like the sequential path."""
    ex = make_local_executor(tmp_path)

    def gen():
        yield 1

    async def flow():
        try:
            return await ex.run(
                lambda g: next(g), [gen()], {},
                {"dispatch_id": "nopickle", "node_id": 0},
            )
        finally:
            await ex.close()

    with pytest.raises(TypeError, match="pickle|generator"):
        run_async(flow())


# --------------------------------------------------------------------- #
# DAG-driven prewarm
# --------------------------------------------------------------------- #


def test_prewarm_dials_pool_once_and_skips_when_warm(tmp_path, run_async):
    ex = make_local_executor(tmp_path)

    async def flow():
        first = await ex.prewarm()
        second = await ex.prewarm()
        warmed = ex._pool.has(ex._pool_key("localhost"))
        preflighted = ex._pool_key("localhost") in ex._preflighted
        await ex.close()
        return first, second, warmed, preflighted

    first, second, warmed, preflighted = run_async(flow())
    assert first is True and second is False  # idempotent fast path
    assert warmed and preflighted


def test_prewarm_disabled_under_chaos_and_by_config(tmp_path, run_async):
    chaotic = make_local_executor(tmp_path, chaos=ChaosPlan(drop_after=100))
    disabled = make_local_executor(tmp_path, prewarm=False)

    async def flow():
        a = await chaotic.prewarm()
        b = await disabled.prewarm()
        await chaotic.close()
        await disabled.close()
        return a, b

    assert run_async(flow()) == (False, False)
    assert chaotic._chaos.faults_injected == 0  # no budget spent on warmup


def test_workflow_runner_prewarms_blocked_node(tmp_path):
    """A node blocked on an upstream dependency gets its executor's
    control plane dialed WHILE the upstream runs, so its own connect
    stage lands on a warm pool."""
    import covalent_tpu_plugin.workflow as ct

    warmed = counter_value(
        REGISTRY.counter("covalent_tpu_prewarm_total", "", ("result",)),
        result="warmed",
    )
    ex = make_local_executor(tmp_path)

    @ct.electron
    def upstream():
        import time

        time.sleep(0.3)  # window for the prewarm to land
        return 2

    @ct.electron(executor=ex)
    def downstream(x):
        return x * 21

    @ct.lattice
    def flow():
        return downstream(upstream())

    result = ct.dispatch_sync(flow)()
    assert result.status.value == "COMPLETED", result.error
    assert result.result == 42
    assert counter_value(
        REGISTRY.counter("covalent_tpu_prewarm_total", "", ("result",)),
        result="warmed",
    ) == warmed + 1
