"""int8 KV cache: near-exactness vs the float cache, self-consistency
across every decode path (generate/beam/speculative share one cache
machinery), and composition with rolling+sinks.

The quantized cache is deliberately lossy (~1e-2 relative); the decisive
properties are logit cosine > 0.999 against the float cache and BIT
self-consistency between paths that use the same quantized cache.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from covalent_tpu_plugin.models import (
    TransformerConfig,
    TransformerLM,
    beam_search,
    generate,
    speculative_generate,
)
from covalent_tpu_plugin.models.decode import _decode_model, init_cache

BASE = TransformerConfig(
    vocab_size=64,
    d_model=32,
    n_layers=2,
    n_heads=4,
    d_ff=64,
    max_seq=48,
    dtype=jnp.float32,
    attention="reference",
)
QKV = dataclasses.replace(BASE, quantized_kv_cache=True)


def build(cfg=BASE, batch=2, plen=5, seed=1):
    model = TransformerLM(cfg)
    prompt = jax.random.randint(
        jax.random.PRNGKey(seed), (batch, plen), 0, cfg.vocab_size
    )
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]
    return model, params, prompt


def test_cache_leaves_are_int8_with_scales():
    model = TransformerLM(QKV)
    cache = init_cache(model, 2)
    leaves = jax.tree_util.tree_flatten_with_path(cache)[0]
    kinds = {}
    for path, leaf in leaves:
        name = next(
            (getattr(e, "key", None) for e in reversed(path)
             if getattr(e, "key", None)), None,
        )
        kinds[name] = leaf.dtype
    assert kinds["cached_k"] == jnp.int8
    assert kinds["cached_v"] == jnp.int8
    assert kinds["k_scale"] == jnp.float32
    assert kinds["v_scale"] == jnp.float32


def test_prefill_logits_cosine_vs_float_cache():
    model, params, prompt = build()
    qmodel = TransformerLM(QKV)
    float_logits, _ = _decode_model(model).apply(
        {"params": params, "cache": init_cache(model, 2)}, prompt,
        mutable=["cache"],
    )
    quant_logits, _ = _decode_model(qmodel).apply(
        {"params": params, "cache": init_cache(qmodel, 2)}, prompt,
        mutable=["cache"],
    )
    a = np.asarray(float_logits, np.float64).reshape(-1)
    b = np.asarray(quant_logits, np.float64).reshape(-1)
    cos = (a @ b) / (np.linalg.norm(a) * np.linalg.norm(b))
    assert cos > 0.999, cos
    # And it is genuinely lossy (otherwise the test proves nothing).
    assert not np.array_equal(a, b)


def test_generation_stays_close_to_float_cache():
    """Greedy tokens may diverge once a near-tie flips, but the FIRST
    decode steps (small accumulated error) must agree."""
    model, params, prompt = build()
    qmodel = TransformerLM(QKV)
    want = np.asarray(generate(model, params, prompt, 4))
    got = np.asarray(generate(qmodel, params, prompt, 4))
    np.testing.assert_array_equal(got[:, :7], want[:, :7])


def test_beam_and_speculative_self_consistency():
    """beam_width=1 and the speculative path must reproduce the SAME
    quantized model's greedy generate() bit-for-bit: all three flows
    drive one cache implementation (including the scale-leaf gathers)."""
    qmodel, params, prompt = build(QKV)
    want = np.asarray(generate(qmodel, params, prompt, 10))
    tokens, _ = beam_search(qmodel, params, prompt, 10, beam_width=1)
    np.testing.assert_array_equal(np.asarray(tokens[:, 0]), want)

    draft_cfg = dataclasses.replace(
        QKV, d_model=16, n_layers=1, n_heads=2, d_ff=32
    )
    draft = TransformerLM(draft_cfg)
    dparams = draft.init(jax.random.PRNGKey(7), prompt)["params"]
    got = np.asarray(
        speculative_generate(
            qmodel, params, draft, dparams, prompt, 10, draft_len=3
        )
    )
    np.testing.assert_array_equal(got, want)


def test_composes_with_rolling_and_sinks():
    cfg = dataclasses.replace(
        QKV, sliding_window=6, attention_sinks=2, rolling_cache=True,
        max_seq=32,
    )
    model, params, prompt = build(cfg, batch=1)
    n_new = cfg.max_seq + 8
    out = generate(model, params, prompt, n_new)
    arr = np.asarray(out)
    assert arr.shape == (1, 5 + n_new)
    assert (arr >= 0).all() and (arr < cfg.vocab_size).all()
    cache = init_cache(model, 1)
    k_leaves = [
        leaf for path, leaf in jax.tree_util.tree_flatten_with_path(cache)[0]
        if any(getattr(e, "key", None) == "cached_k" for e in path)
    ]
    assert all(leaf.dtype == jnp.int8 for leaf in k_leaves)
    assert all(leaf.shape[-3] == 8 for leaf in k_leaves)  # window + sinks


def test_memory_halves_vs_bf16():
    """The point of the feature: cache bytes per slot drop ~2x vs bf16
    (int8 payload + one f32 scale per D-vector) at a realistic head_dim
    — the toy D=8 configs above would let the scale overhead dominate."""
    bf16 = dataclasses.replace(
        BASE, dtype=jnp.bfloat16, d_model=256, n_heads=4
    )
    model = TransformerLM(bf16)
    qmodel = TransformerLM(
        dataclasses.replace(bf16, quantized_kv_cache=True)
    )

    def cache_bytes(m):
        return sum(
            leaf.size * leaf.dtype.itemsize
            for path, leaf in jax.tree_util.tree_flatten_with_path(
                init_cache(m, 4)
            )[0]
            if any(
                getattr(e, "key", None) in
                ("cached_k", "cached_v", "k_scale", "v_scale")
                for e in path
            )
        )

    ratio = cache_bytes(model) / cache_bytes(qmodel)
    assert ratio > 1.7, ratio  # 2x payload less the f32 scale overhead
