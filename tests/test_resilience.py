"""Unit tests for the resilience layer (resilience.py, transport/chaos.py).

State machines and policies are tested with injected clocks/seeds so every
assertion is deterministic; the end-to-end recovery behavior (real
subprocess gangs under injected faults) lives in tests/test_chaos.py.
"""

from __future__ import annotations

import asyncio

import pytest

from covalent_tpu_plugin.cache import CASIndex
from covalent_tpu_plugin.resilience import (
    CircuitBreaker,
    CircuitBreakerRegistry,
    CircuitOpenError,
    CircuitState,
    Deadline,
    FaultClass,
    RetryPolicy,
    classify_error,
)
from covalent_tpu_plugin.transport import TransportPool
from covalent_tpu_plugin.transport.base import CommandResult, TransportError
from covalent_tpu_plugin.transport.chaos import (
    ChaosPlan,
    ChaosTransport,
    plan_from_spec,
)

from .helpers import FakeTransport


class Clock:
    """Manually-advanced monotonic clock for breaker/deadline tests."""

    def __init__(self) -> None:
        self.now = 100.0

    def __call__(self) -> float:
        return self.now


# --------------------------------------------------------------------- #
# Fault classification
# --------------------------------------------------------------------- #


def test_classify_transport_errors_transient():
    from covalent_tpu_plugin.agent import AgentError

    assert classify_error(TransportError("channel died")) == (
        FaultClass.TRANSIENT, "transport",
    )
    # AgentError (RPC loss) subclasses TransportError: same class.
    assert classify_error(AgentError("rpc lost"))[0] is FaultClass.TRANSIENT
    assert classify_error(ConnectionRefusedError())[0] is FaultClass.TRANSIENT
    assert classify_error(OSError("broken pipe"))[0] is FaultClass.TRANSIENT


def test_classify_circuit_open_is_transient_with_own_reason():
    assert classify_error(CircuitOpenError("open")) == (
        FaultClass.TRANSIENT, "circuit_open",
    )


def test_classify_user_and_cancel_permanent():
    assert classify_error(ValueError("bad topology"))[0] is FaultClass.PERMANENT
    assert classify_error(ZeroDivisionError())[0] is FaultClass.PERMANENT
    assert classify_error(asyncio.CancelledError()) == (
        FaultClass.PERMANENT, "cancelled",
    )


# --------------------------------------------------------------------- #
# RetryPolicy
# --------------------------------------------------------------------- #


def test_retry_policy_budget_and_fault_gating():
    policy = RetryPolicy(max_retries=2)
    unbounded = Deadline(0.0)
    assert policy.should_retry(0, FaultClass.TRANSIENT, unbounded)
    assert policy.should_retry(1, FaultClass.TRANSIENT, unbounded)
    assert not policy.should_retry(2, FaultClass.TRANSIENT, unbounded)
    assert not policy.should_retry(0, FaultClass.PERMANENT, unbounded)


def test_retry_policy_respects_wall_deadline():
    clock = Clock()
    policy = RetryPolicy(max_retries=5)
    deadline = Deadline(10.0, clock=clock)
    assert policy.should_retry(0, FaultClass.TRANSIENT, deadline)
    clock.now += 11.0
    assert not policy.should_retry(0, FaultClass.TRANSIENT, deadline)


def test_retry_delay_full_jitter_bounds_and_determinism():
    a = RetryPolicy(max_retries=8, base_delay=0.5, max_delay=4.0, seed=7)
    b = RetryPolicy(max_retries=8, base_delay=0.5, max_delay=4.0, seed=7)
    delays_a = [a.delay(i) for i in range(8)]
    delays_b = [b.delay(i) for i in range(8)]
    assert delays_a == delays_b  # seeded => reproducible
    for attempt, delay in enumerate(delays_a):
        assert 0.0 <= delay <= min(4.0, 0.5 * 2 ** attempt)


# --------------------------------------------------------------------- #
# Deadline
# --------------------------------------------------------------------- #


def test_deadline_unbounded():
    d = Deadline(0.0)
    assert not d.bounded
    assert d.remaining() is None
    assert not d.expired


def test_deadline_counts_down_and_expires():
    clock = Clock()
    d = Deadline(5.0, clock=clock)
    clock.now += 2.0
    assert d.remaining() == pytest.approx(3.0)
    clock.now += 4.0
    assert d.expired
    assert d.remaining() == 0.0


# --------------------------------------------------------------------- #
# CircuitBreaker state machine
# --------------------------------------------------------------------- #


def make_breaker(clock, threshold=3, cooldown=30.0):
    return CircuitBreaker(
        "w0", failure_threshold=threshold, cooldown=cooldown, clock=clock
    )


def test_circuit_opens_after_consecutive_failures():
    breaker = make_breaker(Clock(), threshold=3)
    breaker.record_failure()
    breaker.record_failure()
    breaker.check()  # still closed below threshold
    breaker.record_failure()
    assert breaker.state is CircuitState.OPEN
    with pytest.raises(CircuitOpenError, match="circuit open for w0"):
        breaker.check()


def test_circuit_success_resets_consecutive_count():
    breaker = make_breaker(Clock(), threshold=2)
    breaker.record_failure()
    breaker.record_success()
    breaker.record_failure()
    assert breaker.state is CircuitState.CLOSED  # 1 < threshold after reset


def test_circuit_half_opens_after_cooldown_then_closes_on_success():
    clock = Clock()
    breaker = make_breaker(clock, threshold=1, cooldown=30.0)
    breaker.record_failure()
    assert breaker.state is CircuitState.OPEN
    clock.now += 31.0
    assert breaker.state is CircuitState.HALF_OPEN
    breaker.check()  # the probe gets through
    # ...but a concurrent second caller during the probe fails fast
    with pytest.raises(CircuitOpenError, match="probe in flight"):
        breaker.check()
    breaker.record_success()
    assert breaker.state is CircuitState.CLOSED
    breaker.check()  # back to normal


def test_circuit_failed_probe_reopens_with_fresh_cooldown():
    clock = Clock()
    breaker = make_breaker(clock, threshold=1, cooldown=30.0)
    breaker.record_failure()
    clock.now += 31.0
    breaker.check()  # half-open probe
    breaker.record_failure()
    assert breaker.state is CircuitState.OPEN
    clock.now += 29.0  # fresh cooldown: not elapsed yet
    assert breaker.state is CircuitState.OPEN
    clock.now += 2.0
    assert breaker.state is CircuitState.HALF_OPEN


def test_registry_one_breaker_per_address():
    registry = CircuitBreakerRegistry(failure_threshold=2, cooldown=5.0)
    assert registry.get("a") is registry.get("a")
    assert registry.get("a") is not registry.get("b")
    registry.get("a").record_failure()
    registry.get("a").record_failure()
    assert registry.states() == {"a": "open", "b": "closed"}


# --------------------------------------------------------------------- #
# Pool gating
# --------------------------------------------------------------------- #


def test_pool_acquire_gated_by_breaker(run_async):
    """The pool fails fast on a quarantined key and records dial outcomes."""
    clock = Clock()
    breaker = make_breaker(clock, threshold=2, cooldown=10.0)
    dials = []

    async def failing_factory():
        dials.append("dial")
        raise TransportError("refused")

    async def flow():
        pool = TransportPool()
        for _ in range(2):
            with pytest.raises(TransportError):
                await pool.acquire("k", failing_factory, gate=breaker)
        # Threshold reached: next acquire must NOT dial.
        with pytest.raises(CircuitOpenError):
            await pool.acquire("k", failing_factory, gate=breaker)
        assert len(dials) == 2
        # Cooldown elapses -> half-open probe dials again and can heal.
        clock.now += 11.0
        fake = FakeTransport()

        async def ok_factory():
            dials.append("dial")
            return fake

        got = await pool.acquire("k", ok_factory, gate=breaker)
        assert got is fake
        assert breaker.state is CircuitState.CLOSED
        await pool.close_all()

    run_async(flow())


# --------------------------------------------------------------------- #
# Chaos spec parsing
# --------------------------------------------------------------------- #


def test_plan_from_spec_roundtrip():
    plan = plan_from_spec(
        "seed=9,delay=0.01,drop_after=5,max_faults=2,drop_match=if test -f"
    )
    assert plan.seed == 9
    assert plan.delay == pytest.approx(0.01)
    assert plan.drop_after == 5
    assert plan.max_faults == 2
    assert plan.drop_match == "if test -f"
    assert plan.active


def test_plan_from_spec_empty_and_invalid():
    assert plan_from_spec("") is None
    assert plan_from_spec("   ") is None
    with pytest.raises(ValueError, match="unknown chaos spec key"):
        plan_from_spec("tpyo=1")
    with pytest.raises(ValueError, match="key=value"):
        plan_from_spec("justakey")


def test_plan_fault_budget():
    plan = ChaosPlan(run_errors=10, max_faults=2)
    assert plan.take_fault("run")
    assert plan.take_fault("run")
    assert not plan.take_fault("run")
    assert plan.faults_injected == 2


# --------------------------------------------------------------------- #
# ChaosTransport
# --------------------------------------------------------------------- #


def test_chaos_drop_after_kills_channel_permanently(run_async):
    inner = FakeTransport()
    chaos = ChaosTransport(inner, ChaosPlan(drop_after=2))

    async def flow():
        await chaos.run("one")
        await chaos.run("two")
        with pytest.raises(TransportError, match="dropped after"):
            await chaos.run("three")
        # Dead is dead: every later op fails without new fault budget.
        with pytest.raises(TransportError, match="dead"):
            await chaos.run("four")
        with pytest.raises(TransportError, match="dead"):
            await chaos.put("/a", "/b")

    run_async(flow())
    assert inner.commands == ["one", "two"]
    assert chaos.plan.faults_injected == 1


def test_chaos_drop_match_targets_specific_command(run_async):
    inner = FakeTransport()
    chaos = ChaosTransport(
        inner, ChaosPlan(drop_match="if test -f", drop_match_skip=1)
    )

    async def flow():
        await chaos.run("if test -f /r.pkl; then echo READY; fi")  # skipped
        await chaos.run("mkdir -p cache")
        with pytest.raises(TransportError, match="dropped on command"):
            await chaos.run("if test -f /r.pkl; then echo READY; fi")

    run_async(flow())
    assert len(inner.commands) == 2


def test_chaos_connect_errors_consume_budget(run_async):
    inner = FakeTransport()
    chaos = ChaosTransport(inner, ChaosPlan(connect_errors=1))

    async def flow():
        with pytest.raises(ConnectionRefusedError):
            await chaos._open()
        await chaos._open()  # budget spent: connects fine now
        await chaos.run("ok")

    run_async(flow())
    assert inner.commands == ["ok"]


def test_chaos_run_errors_do_not_kill_channel(run_async):
    inner = FakeTransport()
    chaos = ChaosTransport(inner, ChaosPlan(run_errors=1))

    async def flow():
        with pytest.raises(TransportError, match="run failed"):
            await chaos.run("first")
        await chaos.run("second")

    run_async(flow())
    assert inner.commands == ["second"]


def test_chaos_truncate_upload_corrupts_payload(tmp_path, run_async):
    from covalent_tpu_plugin.transport.local import LocalTransport

    src = tmp_path / "artifact.bin"
    dst = tmp_path / "uploaded.bin"
    src.write_bytes(b"0123456789abcdef")
    chaos = ChaosTransport(LocalTransport(), ChaosPlan(truncate_uploads=1))

    async def flow():
        await chaos.put(str(src), str(dst))

    run_async(flow())
    assert dst.read_bytes() == b"01234567"  # half the payload shipped
    # Budget spent: the next upload is intact.
    run_async(chaos.put(str(src), str(tmp_path / "clean.bin")))
    assert (tmp_path / "clean.bin").read_bytes() == src.read_bytes()


def test_chaos_seeded_probabilistic_faults_reproducible(run_async):
    async def sequence(seed):
        inner = FakeTransport()
        chaos = ChaosTransport(
            inner, ChaosPlan(seed=seed, p_run_error=0.5)
        )
        outcomes = []
        for i in range(12):
            try:
                await chaos.run(f"cmd{i}")
                outcomes.append("ok")
            except TransportError:
                outcomes.append("err")
        return outcomes

    async def flow():
        first = await sequence(3)
        second = await sequence(3)
        other = await sequence(4)
        return first, second, other

    first, second, other = run_async(flow())
    assert first == second
    assert "err" in first and "ok" in first
    assert first != other  # different seed, different fault pattern


# --------------------------------------------------------------------- #
# CAS probe fallback (satellite: exists_batch failure must not fail
# preflight)
# --------------------------------------------------------------------- #


class _BrokenBatchTransport(FakeTransport):
    async def exists_batch(self, paths):
        raise TransportError("SFTP subsystem refused")


def test_cas_probe_falls_back_to_per_artifact(run_async):
    conn = _BrokenBatchTransport(
        responses={
            "test -e": lambda cmd: CommandResult(
                0 if "have.pkl" in cmd else 1, "", ""
            ),
        }
    )
    index = CASIndex()

    async def flow():
        await index.ensure_probed(
            "k", conn, [("d1", "/cas/have.pkl"), ("d2", "/cas/missing.pkl")]
        )

    run_async(flow())
    assert index.known("k", "d1")          # found by the per-path probe
    assert not index.known("k", "d2")
    # One `test -e` round-trip per artifact was issued.
    assert sum("test -e" in c for c in conn.commands) == 2


class _TotallyBrokenTransport(FakeTransport):
    async def exists_batch(self, paths):
        raise TransportError("channel dead")

    async def run(self, command, timeout=None):
        raise TransportError("channel dead")


def test_cas_probe_degrades_to_all_missing(run_async):
    """Both probe tiers failing reads as nothing-present (spurious
    re-upload at worst), never a failed preflight."""
    index = CASIndex()

    async def flow():
        await index.ensure_probed(
            "k", _TotallyBrokenTransport(), [("d1", "/cas/a.pkl")]
        )

    run_async(flow())
    assert not index.known("k", "d1")
