"""Fleet observability plane (ISSUE 6): distributed trace propagation,
heartbeat liveness + stall detection, the telemetry backhaul side-band, the
ops status endpoint, and the event-stream bounds (rotation, swallow-and-
count worker emission)."""

from __future__ import annotations

import asyncio
import json
import os
import shutil
import signal
import sys
import time
import urllib.request

import pytest

from covalent_tpu_plugin import harness
from covalent_tpu_plugin.obs import events as obs_events
from covalent_tpu_plugin.obs.heartbeat import HeartbeatMonitor, MONITOR
from covalent_tpu_plugin.obs.metrics import REGISTRY
from covalent_tpu_plugin.obs.opsserver import (
    OpsServer,
    register_status_provider,
    unregister_status_provider,
)
from covalent_tpu_plugin.resilience import (
    FaultClass,
    WorkerStalledError,
    classify_error,
)

from .helpers import make_local_executor


@pytest.fixture()
def events_file(tmp_path):
    path = tmp_path / "events.jsonl"
    obs_events.configure(str(path))
    yield path
    obs_events.reset()


def read_events(path) -> list[dict]:
    if not path.exists():
        return []
    return [json.loads(line) for line in path.read_text().splitlines()]


# --------------------------------------------------------------------- #
# Heartbeat monitor: cadence, dedup, stall detection (fake clock)
# --------------------------------------------------------------------- #


def test_monitor_records_and_ages_heartbeats():
    now = [100.0]
    monitor = HeartbeatMonitor(clock=lambda: now[0])
    monitor.watch("op", stall_after=3.0)
    assert monitor.record("op", "w0", {"seq": 1, "step": 5})
    now[0] += 1.0
    view = monitor.last("op")
    assert view["w0"]["age_s"] == pytest.approx(1.0)
    assert view["w0"]["step"] == 5
    # Same seq re-delivered (snapshot re-read): not fresh, clock untouched.
    assert not monitor.record("op", "w0", {"seq": 1, "step": 5})
    assert monitor.last("op")["w0"]["age_s"] == pytest.approx(1.0)


def test_monitor_stall_detection_fake_clock():
    now = [0.0]
    monitor = HeartbeatMonitor(clock=lambda: now[0])
    monitor.watch("op", stall_after=2.0)
    monitor.record("op", "w0", {"seq": 1})
    monitor.record("op", "w1", {"seq": 1})
    now[0] = 1.5
    monitor.record("op", "w1", {"seq": 2})  # w1 keeps beating
    assert monitor.stalled("op") == []
    now[0] = 2.5  # w0 silent for 2.5s, w1 for 1.0s
    stalled = monitor.stalled("op")
    assert [w for w, _ in stalled] == ["w0"]
    assert stalled[0][1] == pytest.approx(2.5)
    # A worker that never beat can never stall; forget clears everything.
    monitor.forget("op")
    assert monitor.stalled("op") == []
    assert monitor.last("op") == {}


def test_monitor_nobeat_worker_stalls_after_launch_slack():
    """A worker wedged BEFORE its first beat (e.g. frozen mid-write) must
    still stall once the launch slack (stall_after + one interval) runs
    out — silence-from-birth is not blindness."""
    now = [0.0]
    monitor = HeartbeatMonitor(clock=lambda: now[0])
    monitor.watch("op", stall_after=2.0, workers=("w0", "w1"),
                  interval=0.5, launch_slack=0.0)
    monitor.record("op", "w1", {"seq": 1})
    now[0] = 2.4  # inside the no-beat deadline (2.5): not yet
    assert [w for w, _ in monitor.stalled("op")] == ["w1"]  # w1 silent 2.4
    monitor.record("op", "w1", {"seq": 2})  # w1 recovers
    now[0] = 2.6  # w0 never beat and the slack is spent
    assert [w for w, _ in monitor.stalled("op")] == ["w0"]
    monitor.forget("op")


def test_monitor_disabled_threshold_never_stalls():
    now = [0.0]
    monitor = HeartbeatMonitor(clock=lambda: now[0])
    monitor.watch("op", stall_after=0.0)
    monitor.record("op", "w0", {"seq": 1})
    now[0] = 1e6
    assert monitor.stalled("op") == []


def test_monitor_jitter_adaptive_stall_threshold():
    """A worker whose beats arrive erratically widens its own stall
    deadline (3 x observed mean gap + K x std, floored at the configured
    stall_after) instead of tripping a false stall; a steady beater keeps
    the configured floor; and a genuinely wedged erratic worker still
    trips once its silence outgrows the learned statistics."""
    now = [0.0]
    monitor = HeartbeatMonitor(clock=lambda: now[0])
    monitor.watch("op", stall_after=2.0)
    # Fewer than ADAPTIVE_MIN_BEATS gaps: the configured floor rules.
    monitor.record("op", "steady", {"seq": 1})
    assert monitor.effective_stall_after("op", "steady") == 2.0
    for seq, gap in enumerate([0.5] * 6, start=2):
        now[0] += gap
        monitor.record("op", "steady", {"seq": seq})
    # Steady cadence (0.5s gaps, ~zero std): 3 x 0.5 < 2.0 -> floor.
    assert monitor.effective_stall_after("op", "steady") == 2.0
    # An erratic-but-alive worker: gaps oscillating around 1.5s with
    # ~1.4s swings learn a deadline well past the configured 2s.
    monitor.record("op", "erratic", {"seq": 1})
    for seq, gap in enumerate([0.1, 2.9, 0.2, 2.8, 0.1, 2.9], start=2):
        now[0] += gap
        monitor.record("op", "erratic", {"seq": seq})
    widened = monitor.effective_stall_after("op", "erratic")
    assert widened > 2.0
    # Silence past the FLOOR but inside the widened deadline: no stall —
    # this exact pattern used to false-positive under fixed thresholds.
    now[0] += 2.5
    assert "erratic" not in [w for w, _ in monitor.stalled("op")]
    # Silence past the widened deadline: the detector still fires — the
    # learned statistics freeze while the silence keeps growing.
    now[0] += widened
    assert "erratic" in [w for w, _ in monitor.stalled("op")]
    monitor.forget("op")


def test_worker_stalled_error_classification():
    fault, reason = classify_error(WorkerStalledError("silent"))
    assert fault is FaultClass.TRANSIENT
    assert reason == "worker_stalled"


# --------------------------------------------------------------------- #
# Event stream bounds: rotation + worker-side swallow-and-count
# --------------------------------------------------------------------- #


def test_event_sink_size_rotation(tmp_path):
    path = tmp_path / "rot.jsonl"
    sink = obs_events.EventSink(str(path), max_bytes=512, backups=2)
    for i in range(64):
        sink.emit("spam", i=i, pad="x" * 64)
    sink.close()
    assert path.exists()
    assert (tmp_path / "rot.jsonl.1").exists()
    assert (tmp_path / "rot.jsonl.2").exists()
    assert not (tmp_path / "rot.jsonl.3").exists()  # bounded generations
    # Live file stays under the cap (+ one line of slack at rotation).
    assert path.stat().st_size < 1024
    # Rotated generations hold valid JSONL.
    for line in (tmp_path / "rot.jsonl.1").read_text().splitlines():
        json.loads(line)


def test_event_sink_rotation_disabled(tmp_path):
    path = tmp_path / "flat.jsonl"
    sink = obs_events.EventSink(str(path), max_bytes=0, backups=2)
    for i in range(32):
        sink.emit("spam", i=i, pad="y" * 64)
    sink.close()
    assert not (tmp_path / "flat.jsonl.1").exists()
    assert len(path.read_text().splitlines()) == 32


def test_worker_event_unwritable_path_never_raises(capsys, monkeypatch):
    """Satellite: `_emit_worker_event` swallows ENOSPC-class failures,
    counts them, and notes the first on stderr."""
    monkeypatch.setattr(harness, "_worker_event_failures", 0)
    spec = {
        "operation_id": "op",
        "events_file": "/nonexistent-dir-xyz/events.jsonl",
    }
    harness._emit_worker_event(spec, "worker.task_started", process_id=0)
    harness._emit_worker_event(spec, "worker.task_finished", process_id=0)
    assert harness._worker_event_failures == 2
    err = capsys.readouterr().err
    assert err.count("worker events unwritable") == 1  # one-line, once


def test_worker_event_carries_trace_and_seq(tmp_path):
    path = tmp_path / "worker.jsonl"
    spec = {
        "operation_id": "op",
        "events_file": str(path),
        "trace": {"trace_id": "t" * 32, "span_id": "s" * 16, "attempt": 2},
    }
    harness._emit_worker_event(spec, "worker.task_started", process_id=0)
    (event,) = [json.loads(line) for line in path.read_text().splitlines()]
    assert event["trace_id"] == "t" * 32
    assert event["parent_id"] == "s" * 16
    assert event["attempt"] == 2
    assert isinstance(event["seq"], int)


# --------------------------------------------------------------------- #
# Ops status endpoint
# --------------------------------------------------------------------- #


def http_get(port: int, path: str) -> tuple[int, bytes]:
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=5
    ) as response:
        return response.status, response.read()


def test_ops_server_routes_and_status_shape():
    server = OpsServer(port=0)
    try:
        REGISTRY.counter("fleetobs_probe_total", "probe").inc(3)
        register_status_provider(
            "test-exec",
            lambda: {"in_flight": {"op_1": {"stage": "executing"}}},
        )
        MONITOR.watch("op_1", stall_after=60.0)
        MONITOR.record("op_1", "w0", {"seq": 9, "step": 7})

        code, body = http_get(server.port, "/metrics")
        assert code == 200
        assert b"fleetobs_probe_total 3" in body

        code, body = http_get(server.port, "/status")
        status = json.loads(body)
        assert status["pid"] == os.getpid()
        assert status["in_flight"]["op_1"]["stage"] == "executing"
        assert status["heartbeats"]["op_1"]["w0"]["step"] == 7
        assert status["providers"]["test-exec"]

        obs_events.emit  # stream may be disabled; feed the ring directly
        server._tail.append({"ts": 1.0, "type": "probe.event"})
        code, body = http_get(server.port, "/events?n=1")
        assert code == 200
        assert json.loads(body.splitlines()[-1])["type"] == "probe.event"

        code, _ = http_get(server.port, "/healthz")
        assert code == 200
    finally:
        unregister_status_provider("test-exec")
        MONITOR.forget("op_1")
        server.close()


def test_ops_server_prunes_dead_providers():
    server = OpsServer(port=0)
    try:
        register_status_provider("gone", lambda: None)
        status = server.status()
        assert "gone" not in status.get("providers", {})
        # Pruned on first read, not just skipped.
        from covalent_tpu_plugin.obs import opsserver as ops_mod

        assert "gone" not in ops_mod._providers
    finally:
        server.close()


def test_executor_registers_status_provider(tmp_path):
    from covalent_tpu_plugin.obs import opsserver as ops_mod

    ex = make_local_executor(tmp_path)
    assert ex._ops_provider_name in ops_mod._providers
    view = ops_mod._providers[ex._ops_provider_name]()
    assert view["transport"] == "local"
    assert "circuit_breakers" in view and "in_flight" in view


# --------------------------------------------------------------------- #
# End-to-end: trace across a retry, live heartbeats, stall recovery
# --------------------------------------------------------------------- #


def test_trace_id_survives_retry_with_attempt_attrs(
    tmp_path, run_async, events_file
):
    """Satellite: worker events carry the dispatcher's trace id across a
    gang retry — fresh attempt, same trace, attempt attr preserved."""
    from covalent_tpu_plugin.transport.chaos import ChaosPlan

    ex = make_local_executor(
        tmp_path,
        max_task_retries=2,
        retry_base_delay=0.01,
        heartbeat_interval=0.1,
        # Kill exactly one status-probe channel mid-poll: attempt 0 dies
        # transiently, attempt 1 completes.
        chaos=ChaosPlan(drop_match="if test -f", max_faults=1),
    )
    out = run_async(ex.run(lambda x: x + 1, [1], {},
                           {"dispatch_id": "ftrace", "node_id": 0}))
    assert out == 2
    assert ex.last_attempts == 2
    events = read_events(events_file)
    worker = [e for e in events if e["type"].startswith("worker.")
              and e.get("operation_id", "").startswith("ftrace_0")]
    assert worker, "no worker events reached the stream"
    attempts = {e.get("attempt") for e in worker}
    assert attempts == {0, 1}, attempts  # both attempts left records
    # ONE trace follows the electron across the retry...
    assert len({e["trace_id"] for e in worker}) == 1
    # ...and it is the dispatcher's own dispatch trace.
    (task_span,) = [e for e in events if e["type"] == "span"
                    and e["name"] == "executor.task"]
    assert {e["trace_id"] for e in worker} == {task_span["trace_id"]}
    run_spans = [e for e in events if e["type"] == "span"
                 and e["name"] == "executor.run"]
    assert len(run_spans) == 2
    assert {s["trace_id"] for s in run_spans} == {task_span["trace_id"]}
    assert sorted(s["attributes"]["attempt"] for s in run_spans) == [0, 1]


def test_heartbeats_reach_monitor_and_stream(tmp_path, run_async, events_file):
    ex = make_local_executor(tmp_path, heartbeat_interval=0.1)

    def slow(x):
        import time as _time

        _time.sleep(0.6)
        return x * 2

    out = run_async(ex.run(slow, [4], {},
                           {"dispatch_id": "fhb", "node_id": 0}))
    assert out == 8
    beats = [e for e in read_events(events_file)
             if e["type"] == "worker.heartbeat"]
    assert beats, "no heartbeats re-emitted on the dispatcher stream"
    assert all(e["worker"] == "localhost" for e in beats)
    assert all(e["trace_id"] for e in beats)
    assert all("rss_bytes" in e for e in beats)
    # Fresh beats moved the per-worker counter.
    total = REGISTRY.counter(
        "covalent_tpu_worker_heartbeats_total", "", ("worker",)
    ).labels(worker="localhost").value
    assert total >= len(beats)


def test_stalled_worker_classified_and_retried(tmp_path, run_async, events_file):
    """Acceptance: a silenced worker (alive but frozen) is classified
    `worker_stalled` and the gang retried before any hard deadline."""
    flag = tmp_path / "stalled_once"

    def freeze_once(flag_path):
        import os as _os
        import signal as _signal

        if not _os.path.exists(flag_path):
            with open(flag_path, "w") as f:
                f.write("1")
            # Freeze THIS harness process: heartbeat thread stops with it,
            # while kill -0 still reports the pid alive.
            _os.kill(_os.getpid(), _signal.SIGSTOP)
        return "recovered"

    retries = REGISTRY.counter(
        "covalent_tpu_task_retries_total", "", ("reason",)
    )
    before = retries.labels(reason="worker_stalled").value
    ex = make_local_executor(
        tmp_path,
        max_task_retries=1,
        retry_base_delay=0.01,
        heartbeat_interval=0.1,
        stall_threshold=0.8,
        task_timeout=60.0,  # the stall detector must win, not this
    )
    t0 = time.monotonic()
    out = run_async(ex.run(freeze_once, [str(flag)], {},
                           {"dispatch_id": "fstall", "node_id": 0}))
    elapsed = time.monotonic() - t0
    assert out == "recovered"
    assert ex.last_attempts == 2
    assert elapsed < 30.0, "stall detection did not beat the hard timeout"
    assert retries.labels(reason="worker_stalled").value == before + 1
    events = read_events(events_file)
    assert any(e["type"] == "task.stall_escalated" for e in events)
    (failed,) = [e for e in events if e["type"] == "task.failed"]
    assert failed["status"] == "STALLED"
    retry_events = [e for e in events if e["type"] == "task.retry"]
    assert retry_events and retry_events[0]["reason"] == "worker_stalled"


# --------------------------------------------------------------------- #
# Telemetry backhaul over the pool-server channel
# --------------------------------------------------------------------- #


def test_pool_server_watch_flushes_and_survives_channel_death(
    tmp_path, run_async
):
    """Satellite: events buffered on the worker while no channel is
    attached are flushed on the next (re-)watch and deduped by seq."""
    from covalent_tpu_plugin.agent import start_pool_server
    from covalent_tpu_plugin.transport import LocalTransport

    telemetry = tmp_path / "telemetry.jsonl"

    def write_lines(*seqs):
        with open(telemetry, "a", encoding="utf-8") as f:
            for seq in seqs:
                f.write(json.dumps(
                    {"seq": seq, "type": "worker.heartbeat", "step": seq}
                ) + "\n")

    async def flow():
        seen: list[dict] = []
        conn = LocalTransport()
        client = await start_pool_server(
            conn, str(tmp_path / "cache"), sys.executable
        )
        client.on_telemetry = lambda task_id, data: seen.append(data)
        write_lines(1, 2)  # buffered BEFORE any watch: backlog
        await client.watch("t1", str(telemetry))
        for _ in range(100):
            if len(seen) >= 2:
                break
            await asyncio.sleep(0.05)
        assert [d["seq"] for d in seen] == [1, 2]

        write_lines(3)  # live tail
        for _ in range(100):
            if len(seen) >= 3:
                break
            await asyncio.sleep(0.05)
        assert [d["seq"] for d in seen] == [1, 2, 3]

        # Channel death: the file (the buffer) survives the client.
        await client.close()
        write_lines(4)

        # Reconnect: a fresh server re-watches from offset 0 — the full
        # backlog replays and the client-side seq dedup drops 1..3.
        client2 = await start_pool_server(
            conn, str(tmp_path / "cache"), sys.executable
        )
        client2._telemetry_seq["t1"] = max(d["seq"] for d in seen)
        client2.on_telemetry = lambda task_id, data: seen.append(data)
        await client2.watch("t1", str(telemetry))
        for _ in range(100):
            if len(seen) >= 4:
                break
            await asyncio.sleep(0.05)
        await client2.close()
        return seen

    seen = run_async(flow())
    assert [d["seq"] for d in seen] == [1, 2, 3, 4]


def test_agent_launched_run_backhauls_heartbeats(tmp_path, run_async,
                                                 events_file):
    """Full executor path in pool-agent mode: heartbeats ride the channel
    side-band into the monitor and the dispatcher stream."""
    ex = make_local_executor(
        tmp_path, use_agent="pool", heartbeat_interval=0.1, poll_freq=0.1
    )

    def slow(x):
        import time as _time

        _time.sleep(0.5)
        return x + 10

    async def flow():
        try:
            return await ex.run(slow, [5], {},
                                {"dispatch_id": "fbackhaul", "node_id": 0})
        finally:
            await ex.close()  # same loop: pool-server channel lives here

    out = run_async(flow())
    assert out == 15
    beats = [e for e in read_events(events_file)
             if e["type"] == "worker.heartbeat"
             and e.get("operation_id") == "fbackhaul_0"]
    assert beats, "no backhauled heartbeats"
    # Channel-pushed AND probe-read copies dedup to one stream record per
    # worker-side seq.
    seqs = [e["seq"] for e in beats]
    assert len(seqs) == len(set(seqs))


def test_pool_server_auto_unwatches_on_task_exit(tmp_path, run_async):
    """A finished task's watcher is pruned (after a final flush): a
    long-lived server must not stat() dead tasks' files forever."""
    from covalent_tpu_plugin.agent import start_pool_server
    from covalent_tpu_plugin.transport import LocalTransport

    telemetry = tmp_path / "t.jsonl"
    spec = tmp_path / "spec.json"
    result = tmp_path / "r.pkl"
    spec.write_text(json.dumps({
        "operation_id": "t1",
        "function_file": str(tmp_path / "missing.pkl"),  # exits fast (rc 1)
        "result_file": str(result),
    }))

    async def flow():
        seen: list[dict] = []
        conn = LocalTransport()
        client = await start_pool_server(
            conn, str(tmp_path / "cache"), sys.executable
        )
        client.on_telemetry = lambda task_id, data: seen.append(data)
        await client.watch("t1", str(telemetry))
        with open(telemetry, "w") as f:
            f.write(json.dumps({"seq": 1, "type": "worker.x"}) + "\n")
        await client.run_task("t1", spec=str(spec),
                              log=str(tmp_path / "log.txt"))
        await client.wait_exit("t1", timeout=20.0)
        # The pre-exit line was flushed by the final pump at reap time.
        for _ in range(100):
            if seen:
                break
            await asyncio.sleep(0.05)
        assert [d["seq"] for d in seen] == [1]
        # Post-exit lines must NOT be forwarded: the watcher is gone.
        with open(telemetry, "a") as f:
            f.write(json.dumps({"seq": 2, "type": "worker.x"}) + "\n")
        await asyncio.sleep(0.8)  # > the 250ms watcher tick
        await client.close()
        return seen

    seen = run_async(flow())
    assert [d["seq"] for d in seen] == [1]


def test_agent_stall_suspicion_confirmed_against_hb_file(
    tmp_path, run_async, monkeypatch
):
    """A broken telemetry side-band must NOT kill a healthy gang: on
    stall suspicion the agent wait re-reads the .hb snapshot directly and
    a beating worker survives."""
    from covalent_tpu_plugin import agent as agent_mod

    # No side-band at all: every watch fails (the worst case the review
    # flagged — agent mode with zero streaming feed into the monitor).
    async def broken_watch(self, task_id, path):
        raise agent_mod.AgentError("watch unsupported")

    monkeypatch.setattr(agent_mod.AgentClient, "watch", broken_watch)
    # Tighten the never-beat launch slack so the suspicion actually fires
    # within the electron's runtime.
    monkeypatch.setattr(HeartbeatMonitor, "LAUNCH_SLACK_S", 1.0)
    # 16 missed beats before suspicion: 0.4s flaked under full-suite load
    # (a transiently starved beat thread read as a stall), and 0.8s still
    # did on loaded machines — the .hb staleness tolerance must exceed the
    # worst beat-thread starvation the suite inflicts, not the cadence.
    ex = make_local_executor(
        tmp_path, use_agent="pool", heartbeat_interval=0.1,
        stall_threshold=1.6, max_task_retries=1, poll_freq=0.1,
    )

    def slow(x):
        import time as _time

        _time.sleep(3.0)
        return x * 3

    async def flow():
        try:
            return await ex.run(slow, [7], {},
                                {"dispatch_id": "fconfirm", "node_id": 0})
        finally:
            await ex.close()

    assert run_async(flow()) == 21
    assert ex.last_attempts == 1, "healthy gang was stall-killed"


NATIVE_AGENT_SKIP = pytest.mark.skipif(
    all(shutil.which(cc) is None for cc in ("g++", "c++", "clang++")),
    reason="no C++ compiler",
)


@NATIVE_AGENT_SKIP
def test_native_agent_watch_side_band(tmp_path, run_async):
    from covalent_tpu_plugin.agent import AgentClient, ensure_agent_binary
    from covalent_tpu_plugin.transport import LocalTransport

    telemetry = tmp_path / "native_telemetry.jsonl"
    telemetry.write_text(
        json.dumps({"seq": 1, "type": "worker.heartbeat"}) + "\n"
        + "not json\n"
        + json.dumps({"seq": 2, "type": "worker.task_finished"}) + "\n"
    )

    async def flow():
        seen: list[dict] = []
        conn = LocalTransport()
        binary = await ensure_agent_binary(conn, str(tmp_path / "cache"))
        client = await AgentClient.start(conn, binary)
        client.on_telemetry = lambda task_id, data: seen.append(data)
        await client.watch("t1", str(telemetry))
        for _ in range(100):
            if len(seen) >= 2:
                break
            await asyncio.sleep(0.05)
        await client.unwatch("t1")
        await client.close()
        return seen

    seen = run_async(flow())
    # Valid lines forwarded in order; the malformed line was dropped.
    assert [d["seq"] for d in seen] == [1, 2]
