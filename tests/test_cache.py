"""Two-level dispatch cache tests (cache.py + executor/transport wiring).

Level 1: content-addressed staging — digest helpers, the per-connection
CAS index (probe seeding, single-flight puts, eviction), and the
executor-level guarantee the PR exists for: the harness pickle is put at
most once per connection across a multi-electron run.

Level 2: electron result memoization — disk LRU bounds, the opt-in
switches, and a full run() short-circuit that never touches the transport.
"""

import asyncio
import json
import os
import sys
import time

import pytest

from covalent_tpu_plugin.cache import (
    CAS_UPLOADS_TOTAL,
    RESULT_CACHE_TOTAL,
    CASIndex,
    ResultCache,
    bytes_digest,
    cas_path,
    file_digest,
    harness_digest,
)
from covalent_tpu_plugin.obs.metrics import REGISTRY
from covalent_tpu_plugin.transport.base import CommandResult
from covalent_tpu_plugin.transport.local import LocalTransport

from .helpers import FakeTransport, scripted_ok_responses
from .test_tpu_executor import METADATA, make_executor


def counter_value(counter, **labels) -> float:
    return counter.labels(**labels).value


# --------------------------------------------------------------------- #
# Digest helpers
# --------------------------------------------------------------------- #


def test_file_digest_matches_bytes_digest(tmp_path):
    path = tmp_path / "payload.bin"
    path.write_bytes(b"covalent" * 1000)
    assert file_digest(str(path)) == bytes_digest(b"covalent" * 1000)


def test_harness_digest_is_stable_and_matches_file():
    from covalent_tpu_plugin import harness

    assert harness_digest() == file_digest(harness.__file__)
    assert harness_digest() == harness_digest()  # memoized


def test_cas_path_layout():
    assert cas_path("/rc", "abc123", ".pkl") == "/rc/cas/abc123.pkl"


# --------------------------------------------------------------------- #
# CASIndex
# --------------------------------------------------------------------- #


def test_cas_ensure_uploads_once_per_key(tmp_path, run_async):
    fake = FakeTransport()
    index = CASIndex()
    local = tmp_path / "artifact"
    local.write_bytes(b"payload")
    digest = file_digest(str(local))
    hits0 = counter_value(CAS_UPLOADS_TOTAL, result="hit")
    misses0 = counter_value(CAS_UPLOADS_TOTAL, result="miss")

    async def flow():
        await index.ensure("k", fake, digest, str(local), "/rc/cas/x")
        await index.ensure("k", fake, digest, str(local), "/rc/cas/x")
        # A different connection key has its own present set.
        await index.ensure("k2", fake, digest, str(local), "/rc/cas/x")

    run_async(flow())
    assert len(fake.puts) == 2  # once per key, not per call
    assert counter_value(CAS_UPLOADS_TOTAL, result="hit") - hits0 == 1
    assert counter_value(CAS_UPLOADS_TOTAL, result="miss") - misses0 == 2


def test_cas_concurrent_ensures_single_flight(tmp_path, run_async):
    """Concurrent electrons sharing one digest trigger exactly one put."""

    class SlowPutTransport(FakeTransport):
        async def put(self, local_path, remote_path):
            await asyncio.sleep(0.02)
            await super().put(local_path, remote_path)

    fake = SlowPutTransport()
    index = CASIndex()
    local = tmp_path / "artifact"
    local.write_bytes(b"shared")
    digest = file_digest(str(local))

    async def flow():
        await asyncio.gather(
            *(
                index.ensure("k", fake, digest, str(local), "/rc/cas/x")
                for _ in range(5)
            )
        )

    run_async(flow())
    assert len(fake.puts) == 1


def test_cas_probe_seeds_present_set(tmp_path, run_async):
    """Artifacts the worker already holds are never re-uploaded: the ONE
    batched existence probe seeds the present set."""
    fake = FakeTransport({"test -e": CommandResult(0, "1\n1\n", "")})
    index = CASIndex()
    a = tmp_path / "a"
    a.write_bytes(b"a")
    b = tmp_path / "b"
    b.write_bytes(b"b")
    da, db = file_digest(str(a)), file_digest(str(b))

    async def flow():
        await index.ensure_probed(
            "k", fake, [(da, "/rc/cas/a"), (db, "/rc/cas/b")]
        )
        await index.ensure("k", fake, da, str(a), "/rc/cas/a")
        await index.ensure("k", fake, db, str(b), "/rc/cas/b")
        # Probe ran once; re-asking is a no-op round-trip-wise.
        await index.ensure_probed("k", fake, [(da, "/rc/cas/a")])

    run_async(flow())
    assert len(fake.puts) == 0
    assert len([c for c in fake.commands if "test -e" in c]) == 1


def test_cas_forget_evicts_key(tmp_path, run_async):
    fake = FakeTransport()
    index = CASIndex()
    local = tmp_path / "artifact"
    local.write_bytes(b"payload")
    digest = file_digest(str(local))

    async def flow():
        await index.ensure("k", fake, digest, str(local), "/rc/cas/x")
        index.forget("k")
        await index.ensure("k", fake, digest, str(local), "/rc/cas/x")

    run_async(flow())
    assert len(fake.puts) == 2  # re-uploaded after eviction


def test_exists_batch_shell_default_and_local_override(tmp_path, run_async):
    present = tmp_path / "present"
    present.write_text("x")
    absent = str(tmp_path / "absent")

    conn = LocalTransport()
    assert run_async(conn.exists_batch([str(present), absent])) == [True, False]

    # The ABC default: one compound shell round-trip through run().
    from covalent_tpu_plugin.transport.base import Transport

    shell = LocalTransport()
    flags = run_async(Transport.exists_batch(shell, [str(present), absent]))
    assert flags == [True, False]
    assert run_async(Transport.exists_batch(shell, [])) == []


# --------------------------------------------------------------------- #
# Executor-level CAS (the acceptance-criteria test)
# --------------------------------------------------------------------- #


def test_harness_put_at_most_once_per_connection_two_electrons(
    tmp_path, run_async
):
    """Across a 2-electron run on one pooled connection, the harness (and
    the identical function pickle) upload once; the second electron ships
    only its spec.  CAS hit counter >= 1 and the per-put span count drops
    on the second electron."""
    fake = FakeTransport(scripted_ok_responses())
    fake.result_payload = (1, None)
    ex = make_executor(tmp_path, fake)
    fn = lambda: 1  # noqa: E731 - identical pickle across both electrons
    hits0 = counter_value(CAS_UPLOADS_TOTAL, result="hit")

    def put_span_count() -> int:
        hist = REGISTRY.get("covalent_tpu_span_duration_seconds")
        if hist is None:
            return 0
        for labels, child in hist._series():
            if labels.get("span") == "executor.cas_put":
                return child.count
        return 0

    spans0 = put_span_count()
    state = {}

    async def flow():
        # One dispatcher loop for both electrons, like the workflow runner:
        # a fresh loop per run() would (correctly) abandon the CAS index
        # with the pooled transports it describes.
        await ex.run(fn, [], {}, {"dispatch_id": "d", "node_id": 0})
        state["first_puts"] = list(fake.puts)
        state["spans_first"] = put_span_count() - spans0
        await ex.run(fn, [], {}, {"dispatch_id": "d", "node_id": 1})

    run_async(flow())
    first_puts = state["first_puts"]
    spans_first = state["spans_first"]
    second_puts = fake.puts[len(first_puts):]
    spans_second = put_span_count() - spans0 - spans_first

    # The cold first electron ships its 3 missing artifacts (function +
    # harness + spec) as ONE bundle put; the warm second electron misses
    # only its spec, so the bundle path degrades to a single per-file put
    # under a temp name, atomically renamed into the digest path.
    assert len(first_puts) == 1 and "/bundle-" in first_puts[0][1]
    assert len(second_puts) == 1  # only the new spec (fn + harness hit)
    assert ".json.tmp-" in second_puts[0][1]
    assert counter_value(CAS_UPLOADS_TOTAL, result="hit") - hits0 >= 2
    # The second electron never pays a bundle span: its upload traffic is
    # one per-file put for the new spec.
    assert spans_first == 0 and spans_second == 1


def test_discarded_connection_reprobes_and_reuploads(tmp_path, run_async):
    """_discard_workers evicts CAS knowledge: a recreated worker gets the
    artifacts again instead of a dangling 'already present' assumption."""
    fake = FakeTransport(scripted_ok_responses(), address="localhost")
    fake.result_payload = (1, None)
    ex = make_executor(tmp_path, fake)
    fn = lambda: 1  # noqa: E731

    async def flow():
        await ex.run(fn, [], {}, {"dispatch_id": "d", "node_id": 0})
        await ex._discard_workers()
        await ex.run(fn, [], {}, {"dispatch_id": "d", "node_id": 1})

    run_async(flow())
    # Each cold electron ships one bundle (fn + harness + spec); the
    # discard between them evicts the present set, so the SECOND electron
    # re-bundles everything instead of trusting stale CAS knowledge.
    bundle_puts = [p for _, p in fake.puts if "/bundle-" in p]
    assert len(bundle_puts) == 2  # re-uploaded after discard


# --------------------------------------------------------------------- #
# ResultCache (level 2)
# --------------------------------------------------------------------- #


def test_result_cache_roundtrip_and_miss(tmp_path):
    cache = ResultCache(str(tmp_path / "rc"))
    key = ResultCache.make_key("fn", "args", "env")
    hit, value = cache.get(key)
    assert (hit, value) == (False, None)
    assert cache.put(key, {"loss": 0.25})
    hit, value = cache.get(key)
    assert hit and value == {"loss": 0.25}


def test_result_cache_entry_bound_evicts_oldest(tmp_path):
    import os
    import time

    cache = ResultCache(str(tmp_path / "rc"), max_entries=2)
    evicted0 = counter_value(RESULT_CACHE_TOTAL, result="evict")
    keys = [ResultCache.make_key("fn", str(i), "env") for i in range(3)]
    for i, key in enumerate(keys):
        cache.put(key, i)
        # mtime is the LRU clock; backdate each entry (oldest first) so
        # the ordering is deterministic under sub-second mtime resolution.
        path = cache._path(key)
        if os.path.exists(path):
            stamp = time.time() - 10 + i
            os.utime(path, (stamp, stamp))
    assert len(cache) == 2
    assert cache.get(keys[0])[0] is False  # oldest gone
    assert cache.get(keys[2]) == (True, 2)
    assert counter_value(RESULT_CACHE_TOTAL, result="evict") - evicted0 >= 1


def test_result_cache_byte_bound(tmp_path):
    cache = ResultCache(str(tmp_path / "rc"), max_entries=100, max_bytes=64)
    key = ResultCache.make_key("fn", "big", "env")
    assert cache.put(key, "x" * 10_000) is False  # oversize, never stored
    assert cache.get(key)[0] is False


def test_result_cache_unpicklable_value_is_counted_not_fatal(tmp_path):
    cache = ResultCache(str(tmp_path / "rc"))
    before = counter_value(RESULT_CACHE_TOTAL, result="unpicklable")
    assert cache.put("k", lambda: (yield)) in (True, False)  # never raises
    # generator-function results pickle via cloudpickle; use a socket to
    # guarantee failure
    import socket

    sock = socket.socket()
    try:
        assert cache.put("k2", sock) is False
    finally:
        sock.close()
    assert counter_value(RESULT_CACHE_TOTAL, result="unpicklable") > before


def test_result_cache_corrupt_entry_is_a_miss(tmp_path):
    cache = ResultCache(str(tmp_path / "rc"))
    key = ResultCache.make_key("fn", "args", "env")
    cache.put(key, 42)
    with open(cache._path(key), "wb") as f:
        f.write(b"\x80garbage")
    hit, value = cache.get(key)
    assert (hit, value) == (False, None)


# --------------------------------------------------------------------- #
# Executor-level memoization
# --------------------------------------------------------------------- #


def test_run_result_cache_hit_skips_transport(tmp_path, run_async):
    fake = FakeTransport(scripted_ok_responses())
    fake.result_payload = ({"acc": 0.9}, None)
    ex = make_executor(tmp_path, fake, cache_results=True)
    fn = lambda: {"acc": 0.9}  # noqa: E731
    hits0 = counter_value(RESULT_CACHE_TOTAL, result="hit")

    out1 = run_async(ex.run(fn, [], {}, {"dispatch_id": "d", "node_id": 0}))
    commands_after_first = len(fake.commands)
    puts_after_first = len(fake.puts)
    out2 = run_async(ex.run(fn, [], {}, {"dispatch_id": "d2", "node_id": 0}))

    assert out1 == out2 == {"acc": 0.9}
    # The hit returned before connect: zero new control-plane traffic.
    assert len(fake.commands) == commands_after_first
    assert len(fake.puts) == puts_after_first
    assert counter_value(RESULT_CACHE_TOTAL, result="hit") - hits0 == 1
    assert ex.last_timings["overhead"] >= 0.0


def test_run_result_cache_distinguishes_args(tmp_path, run_async):
    fake = FakeTransport(scripted_ok_responses())
    fake.result_payload = (1, None)
    ex = make_executor(tmp_path, fake, cache_results=True)
    fn = lambda x: x  # noqa: E731

    run_async(ex.run(fn, [1], {}, {"dispatch_id": "d", "node_id": 0}))
    commands_after_first = len(fake.commands)
    run_async(ex.run(fn, [2], {}, {"dispatch_id": "d", "node_id": 1}))
    # Different args -> different key -> full dispatch again.
    assert len(fake.commands) > commands_after_first


def test_run_remote_exception_not_memoized(tmp_path, run_async):
    fake = FakeTransport(scripted_ok_responses())
    fake.result_payload = (None, KeyError("boom"))
    ex = make_executor(tmp_path, fake, cache_results=True)
    fn = lambda: 1  # noqa: E731

    with pytest.raises(KeyError):
        run_async(ex.run(fn, [], {}, {"dispatch_id": "d", "node_id": 0}))
    commands_after_first = len(fake.commands)
    with pytest.raises(KeyError):
        run_async(ex.run(fn, [], {}, {"dispatch_id": "d", "node_id": 1}))
    assert len(fake.commands) > commands_after_first  # re-ran, no hit


def test_cache_results_env_var_opt_in(tmp_path, monkeypatch):
    monkeypatch.setenv("COVALENT_TPU_RESULT_CACHE", "1")
    ex = make_executor(tmp_path)
    assert ex.cache_results is True
    assert ex._result_cache is not None
    monkeypatch.setenv("COVALENT_TPU_RESULT_CACHE", "0")
    ex = make_executor(tmp_path)
    assert ex.cache_results is False
    assert ex._result_cache is None


def test_cache_results_default_off(tmp_path):
    ex = make_executor(tmp_path)
    assert ex.cache_results is False
    assert ex._result_cache is None


def test_result_cache_key_covers_env_fingerprint(tmp_path):
    ex1 = make_executor(tmp_path, cache_results=True)
    ex2 = make_executor(
        tmp_path, cache_results=True, task_env={"LIBTPU_INIT_ARGS": "x"}
    )
    fn = lambda: 1  # noqa: E731
    k1 = ex1._result_cache_key(fn, (), {}, dict(METADATA))
    k2 = ex2._result_cache_key(fn, (), {}, dict(METADATA))
    k1_again = ex1._result_cache_key(fn, (), {}, dict(METADATA))
    assert k1 == k1_again
    assert k1 != k2  # different task_env must not share results
    with_pip = ex1._result_cache_key(
        fn, (), {}, {**METADATA, "pip_deps": ["scikit-learn"]}
    )
    assert with_pip != k1


def test_result_cache_shared_across_executor_instances(tmp_path, run_async):
    """Alias executors are rebuilt per workflow dispatch; the disk store
    under cache_dir is what lets repeated dispatches of the same lattice
    hit the cache."""
    fake1 = FakeTransport(scripted_ok_responses())
    fake1.result_payload = (7, None)
    ex1 = make_executor(tmp_path, fake1, cache_results=True)
    fn = lambda: 7  # noqa: E731
    assert run_async(ex1.run(fn, [], {}, dict(METADATA))) == 7

    fake2 = FakeTransport(scripted_ok_responses())
    ex2 = make_executor(tmp_path, fake2, cache_results=True)
    assert run_async(ex2.run(fn, [], {}, dict(METADATA))) == 7
    assert fake2.commands == []  # pure cache hit, no transport traffic


# --------------------------------------------------------------------- #
# Harness-side CAS integrity
# --------------------------------------------------------------------- #


def test_harness_rejects_digest_mismatch(tmp_path):
    """A torn/stale CAS artifact fails loud before unpickling."""
    import cloudpickle

    from covalent_tpu_plugin import harness

    fn_file = tmp_path / "fn.pkl"
    with open(fn_file, "wb") as f:
        cloudpickle.dump((lambda: 1, (), {}), f)
    result_file = tmp_path / "result.pkl"
    spec = {
        "operation_id": "op",
        "function_file": str(fn_file),
        "function_digest": "0" * 64,  # wrong on purpose
        "result_file": str(result_file),
    }
    rc = harness.run_task(spec)
    assert rc == 1
    import pickle

    with open(result_file, "rb") as f:
        result, error = pickle.load(f)
    assert result is None
    assert "digest" in str(error)


def test_harness_accepts_matching_digest(tmp_path):
    import cloudpickle

    from covalent_tpu_plugin import harness

    fn_file = tmp_path / "fn.pkl"
    with open(fn_file, "wb") as f:
        cloudpickle.dump((lambda: 41 + 1, (), {}), f)
    result_file = tmp_path / "result.pkl"
    spec = {
        "operation_id": "op",
        "function_file": str(fn_file),
        "function_digest": file_digest(str(fn_file)),
        "result_file": str(result_file),
    }
    assert harness.run_task(spec) == 0
    import pickle

    with open(result_file, "rb") as f:
        result, error = pickle.load(f)
    assert (result, error) == (42, None)


# --------------------------------------------------------------------- #
# Pre-flight keying (satellite: id(conn) reuse bug)
# --------------------------------------------------------------------- #


def test_preflight_keyed_by_pool_key_not_id(tmp_path, run_async):
    fake = FakeTransport(
        {"mkdir -p": CommandResult(0, "3\n", "")}, address="localhost"
    )
    ex = make_executor(tmp_path)
    run_async(ex._preflight(fake, key=ex._pool_key("localhost")))
    assert ex._preflighted == {ex._pool_key("localhost")}
    assert not any(isinstance(k, int) for k in ex._preflighted)


def test_discard_workers_evicts_preflight_entry(tmp_path, run_async):
    fake = FakeTransport(
        {"mkdir -p": CommandResult(0, "3\n", "")}, address="localhost"
    )
    ex = make_executor(tmp_path)

    async def flow():
        await ex._preflight(fake, key=ex._pool_key("localhost"))
        assert ex._pool_key("localhost") in ex._preflighted
        await ex._discard_workers()

    run_async(flow())
    assert ex._pool_key("localhost") not in ex._preflighted
    # A fresh connection must re-run pre-flight.
    fresh = FakeTransport(
        {"mkdir -p": CommandResult(0, "3\n", "")}, address="localhost"
    )
    run_async(ex._preflight(fresh, key=ex._pool_key("localhost")))
    assert len(fresh.commands) == 1


def test_spec_content_distinguishes_workers(tmp_path):
    """Per-worker specs carry distinct process ids, so their digests (and
    CAS paths) never collide across workers of one electron."""
    ex = make_executor(tmp_path, workers=["w0", "w1"])
    staged = ex._write_function_files("op", lambda: 1, (), {}, "/wd")
    assert len(set(staged.spec_digests)) == 2
    assert staged.remote_spec_file(0) != staged.remote_spec_file(1)
    for process_id in (0, 1):
        spec = json.load(open(staged.local_spec_files[process_id]))
        assert spec["function_digest"] == staged.function_digest


# --------------------------------------------------------------------- #
# Review hardening: atomic publish, TTL prune, spec cleanup
# --------------------------------------------------------------------- #


def test_cas_put_is_atomic_publish(tmp_path, run_async):
    """Per-file uploads land under a temp name and are renamed into the
    digest path, so a concurrent probe can never see a half-written
    artifact (bundle=False pins the per-file path; the bundled path's
    atomicity is the unpack program's per-member tmp+replace, covered in
    test_fastpath)."""
    fake = FakeTransport(scripted_ok_responses())
    fake.result_payload = (1, None)
    ex = make_executor(tmp_path, fake, bundle=False)
    run_async(ex.run(lambda: 1, [], {}, dict(METADATA)))
    # No put targets a bare digest path directly...
    assert all(".tmp-" in remote for _, remote in fake.puts)
    # ...and each tmp upload is published by an atomic mv to the CAS path.
    renames = [c for c in fake.commands if c.startswith("mv -f")]
    assert len(renames) == len(fake.puts)
    assert all("/cas/" in c for c in renames)


def test_local_transport_rename_is_atomic_replace(tmp_path, run_async):
    conn = LocalTransport()
    src = tmp_path / "a.tmp"
    src.write_text("payload")
    dst = tmp_path / "a"
    run_async(conn.rename(str(src), str(dst)))
    assert dst.read_text() == "payload"
    assert not src.exists()
    from covalent_tpu_plugin.transport import TransportError

    with pytest.raises(TransportError):
        run_async(conn.rename(str(tmp_path / "missing"), str(dst)))


def test_preflight_command_prunes_cas_by_ttl(tmp_path):
    ex = make_executor(tmp_path, cas_ttl_hours=2)
    cmd = ex._preflight_command()
    assert "find" in cmd and "-mmin +120" in cmd and "/cas" in cmd
    # The prune can never fail pre-flight, and the python check stays last.
    assert "|| true" in cmd
    assert cmd.rstrip().endswith("sys.version_info[0])'")
    no_prune = make_executor(tmp_path, cas_ttl_hours=0)
    assert "find" not in no_prune._preflight_command()


def test_cleanup_removes_spec_keeps_dedupable_artifacts(tmp_path, run_async):
    """Per-operation specs (never dedupable) are cleaned and evicted from
    the CAS index; the function pickle and harness stay cached."""
    fake = FakeTransport(scripted_ok_responses())
    fake.result_payload = (1, None)
    ex = make_executor(tmp_path, fake)
    state = {}
    original_stage = ex._write_function_files

    def spy(*args, **kwargs):
        state["staged"] = original_stage(*args, **kwargs)
        return state["staged"]

    ex._write_function_files = spy
    run_async(ex.run(lambda: 1, [], {}, {"dispatch_id": "d", "node_id": 0}))
    staged = state["staged"]
    rm_commands = [c for c in fake.commands if c.startswith("rm -f")]
    assert rm_commands, "cleanup issued no removals"
    removed = " ".join(rm_commands)
    # The spec CAS file is removed; fn pickle and harness stay cached.
    assert staged.remote_spec_file(0) in removed
    assert f"{staged.harness_digest}.py" not in removed
    assert f"{staged.function_digest}.pkl" not in removed
    # run() keys the CAS by the configured worker address, not the fake's.
    key = ex._pool_key("localhost")
    assert ex._cas.known(key, staged.harness_digest)
    assert ex._cas.known(key, staged.function_digest)
    assert not ex._cas.known(key, staged.spec_digests[0])  # evicted


def test_forget_digest_evicts_across_keys(tmp_path, run_async):
    fake = FakeTransport()
    index = CASIndex()
    local = tmp_path / "spec.json"
    local.write_bytes(b"{}")
    digest = file_digest(str(local))

    async def flow():
        await index.ensure("k1", fake, digest, str(local), "/rc/cas/s.json")
        await index.ensure("k2", fake, digest, str(local), "/rc/cas/s.json")

    run_async(flow())
    assert index.known("k1", digest) and index.known("k2", digest)
    index.forget_digest(digest)
    assert not index.known("k1", digest)
    assert not index.known("k2", digest)


def test_result_cache_key_includes_function_code(tmp_path):
    """By-reference pickled functions keep the same payload bytes when
    their body changes; the code digest must still split the keys."""
    ex = make_executor(tmp_path, cache_results=True)

    def f1():
        return 1

    def f2():
        return 2

    same_payload = b"identical-bytes"
    k1 = ex._result_cache_key(f1, (), {}, {}, payload=same_payload)
    k2 = ex._result_cache_key(f2, (), {}, {}, payload=same_payload)
    assert k1 != k2
    # Stable for the same function.
    assert k1 == ex._result_cache_key(f1, (), {}, {}, payload=same_payload)
    # Callables without __code__ still produce a key (no code component).
    import functools

    part = functools.partial(f1)
    assert ex._result_cache_key(part, (), {}, {}, payload=b"x") is not None


def test_cleanup_touches_hot_artifacts_and_prunes(tmp_path, run_async):
    """Cleanup refreshes fn+harness mtimes (so sibling executors' TTL
    prunes treat them as hot) and re-runs the age prune per electron."""
    fake = FakeTransport(scripted_ok_responses())
    fake.result_payload = (1, None)
    ex = make_executor(tmp_path, fake, cas_ttl_hours=1)
    state = {}
    original_stage = ex._write_function_files

    def spy(*args, **kwargs):
        state["staged"] = original_stage(*args, **kwargs)
        return state["staged"]

    ex._write_function_files = spy
    run_async(ex.run(lambda: 1, [], {}, dict(METADATA)))
    staged = state["staged"]
    maintenance = [c for c in fake.commands if c.startswith("touch -c")]
    assert len(maintenance) == 1
    assert staged.remote_function_file in maintenance[0]
    assert staged.remote_harness_file in maintenance[0]
    assert "-mmin +60" in maintenance[0]  # prune rides the same round-trip
    assert maintenance[0].rstrip().endswith("true")  # can never fail cleanup


def test_cleanup_maintenance_skips_prune_when_disabled(tmp_path):
    ex = make_executor(tmp_path, cas_ttl_hours=0)
    staged = ex._write_function_files("op", lambda: 1, (), {}, "/wd")
    cmd = ex._cas_maintenance_command(staged)
    assert "touch -c" in cmd and "find" not in cmd


def test_prune_cas_dir_byte_budget_lru(tmp_path):
    """Oldest-mtime-first eviction until the dir fits the budget; newer
    (touched-hot) artifacts survive; 0 disables."""
    from covalent_tpu_plugin.cache import prune_cas_dir

    root = tmp_path / "cas"
    root.mkdir()
    now = time.time()
    for i in range(5):
        path = root / f"a{i}.pkl"
        path.write_bytes(b"x" * 100)
        os.utime(path, (now - 500 + i * 100, now - 500 + i * 100))
    assert prune_cas_dir(str(root), 0) == 0
    assert prune_cas_dir(str(root), 250) == 3  # two newest fit (200B)
    left = sorted(p.name for p in root.iterdir())
    assert left == ["a3.pkl", "a4.pkl"]
    assert prune_cas_dir(str(root), 250) == 0  # already under budget


def test_remote_cas_bytes_prune_command(tmp_path):
    """The worker-side mirror evicts the same way and announces the
    count the dispatcher's counter consumes."""
    import subprocess

    from covalent_tpu_plugin.cache import cas_bytes_prune_command

    root = tmp_path / "cas"
    root.mkdir()
    now = time.time()
    for i in range(4):
        path = root / f"b{i}.kv"
        path.write_bytes(b"y" * 1000)
        os.utime(path, (now - 400 + i * 100, now - 400 + i * 100))
    command = cas_bytes_prune_command(sys.executable, str(root), 2500)
    out = subprocess.run(
        ["sh", "-c", command], capture_output=True, text=True, check=True
    )
    assert "CAS_EVICTED=2" in out.stdout
    assert sorted(p.name for p in root.iterdir()) == ["b2.kv", "b3.kv"]


def test_cleanup_maintenance_includes_byte_prune(tmp_path):
    """cas_max_bytes wires the LRU clause into the maintenance round
    trip (after the touch, so hot artifacts sit at the LRU tail) and
    off by default."""
    ex = make_executor(tmp_path, cas_max_bytes=12345)
    staged = ex._write_function_files("op", lambda: 1, (), {}, "/wd")
    cmd = ex._cas_maintenance_command(staged)
    assert "CAS_EVICTED" in cmd and "12345" in cmd
    assert cmd.index("touch -c") < cmd.index("CAS_EVICTED")
    off = make_executor(tmp_path, cache_dir=str(tmp_path / "c2"))
    assert "CAS_EVICTED" not in off._cas_maintenance_command(staged)
