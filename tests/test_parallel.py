"""Mesh construction, logical shardings, and collective semantics on the
8-device CPU mesh (SURVEY §4.2c test tier)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from covalent_tpu_plugin.parallel import (
    MeshPlan,
    all_gather,
    all_to_all,
    auto_mesh,
    batch_sharding,
    make_mesh,
    psum,
    reduce_scatter,
    ring_permute,
    shard_batch,
)
from covalent_tpu_plugin.parallel.distributed import coordinator_spec
from covalent_tpu_plugin.parallel.mesh import AXES


def test_mesh_plan_and_axes():
    mesh = make_mesh(MeshPlan(data=2, fsdp=2, tensor=2))
    assert mesh.axis_names == AXES
    assert mesh.shape == {"data": 2, "fsdp": 2, "tensor": 2, "seq": 1, "pipe": 1}


def test_mesh_plan_wrong_device_count():
    with pytest.raises(ValueError, match="needs 16 devices"):
        make_mesh(MeshPlan(data=16))


def test_hybrid_mesh_axis_placement():
    """2 virtual slices x 4 devices: the DCN axis must span slices (each
    data-coordinate = one whole slice) and every ICI axis must stay
    inside one slice — the property that keeps tensor/seq collectives
    off DCN."""
    from covalent_tpu_plugin.parallel.mesh import make_hybrid_mesh

    devices = jax.devices()
    mesh = make_hybrid_mesh(
        MeshPlan(data=2, tensor=2, seq=2), n_slices=2
    )
    assert mesh.shape == {"data": 2, "fsdp": 1, "tensor": 2, "seq": 2, "pipe": 1}
    arr = mesh.devices  # (2, 1, 2, 2, 1)
    slice_of = {d: i // 4 for i, d in enumerate(devices)}
    for di in range(2):
        slice_ids = {
            slice_of[d] for d in arr[di].ravel()
        }
        assert slice_ids == {di}, (di, slice_ids)


def test_hybrid_mesh_dcn_axis_choice_and_validation():
    from covalent_tpu_plugin.parallel.mesh import make_hybrid_mesh

    # fsdp over DCN: data stays an in-slice axis.
    mesh = make_hybrid_mesh(
        MeshPlan(data=4, fsdp=2), n_slices=2, dcn_axis="fsdp"
    )
    devices = jax.devices()
    slice_of = {d: i // 4 for i, d in enumerate(devices)}
    arr = mesh.devices  # (4, 2, 1, 1, 1)
    for fi in range(2):
        assert {slice_of[d] for d in arr[:, fi].ravel()} == {fi}
    # DCN-axis extent must equal the slice count.
    with pytest.raises(ValueError, match="must equal the slice count"):
        make_hybrid_mesh(MeshPlan(data=4, fsdp=2), n_slices=2)
    # Slice-less topologies require an explicit n_slices.
    with pytest.raises(ValueError, match="n_slices"):
        make_hybrid_mesh(MeshPlan(data=2, tensor=2))
    with pytest.raises(ValueError, match="not divisible"):
        make_hybrid_mesh(MeshPlan(data=3), n_slices=3)


def test_hybrid_mesh_runs_a_sharded_step():
    """A psum over the ICI axes + one over the DCN axis both execute on
    the hybrid mesh (virtual slices on the CPU tier)."""
    from covalent_tpu_plugin.parallel.mesh import make_hybrid_mesh

    mesh = make_hybrid_mesh(MeshPlan(data=2, tensor=4), n_slices=2)

    def body(x):
        intra = jax.lax.psum(x, "tensor")   # ICI collective
        inter = jax.lax.psum(intra, "data")  # DCN collective
        return inter

    x = jnp.arange(8.0)
    out = jax.shard_map(
        body, mesh=mesh,
        in_specs=P(("data", "tensor")), out_specs=P(("data", "tensor")),
    )(x)
    np.testing.assert_allclose(np.asarray(out), np.full(8, x.sum()))


def test_auto_mesh_defaults_to_data_parallel():
    mesh = auto_mesh()
    assert mesh.shape["data"] == 8


def test_auto_mesh_with_model_axes():
    mesh = auto_mesh(tensor=2, seq=2)
    assert mesh.shape == {"data": 2, "fsdp": 1, "tensor": 2, "seq": 2, "pipe": 1}
    with pytest.raises(ValueError, match="not divisible"):
        auto_mesh(tensor=3)


def test_shard_batch_places_on_data_axes():
    mesh = make_mesh(MeshPlan(data=4, fsdp=2))
    batch = {"x": np.ones((16, 8), np.float32), "y": np.ones((16,), np.int32)}
    placed = shard_batch(batch, mesh)
    sharding = placed["x"].sharding
    assert isinstance(sharding, NamedSharding)
    assert sharding.spec == P(("data", "fsdp"), None)
    # each device holds 16/8 = 2 rows
    assert placed["x"].addressable_shards[0].data.shape == (2, 8)
    assert batch_sharding(mesh).spec == P(("data", "fsdp"))


def test_shard_batch_replicates_scalar_leaves():
    mesh = make_mesh(MeshPlan(data=8))
    placed = shard_batch({"x": np.ones((8, 4), np.float32), "step": np.float32(3.0)}, mesh)
    assert placed["step"].sharding.spec == P()
    assert float(placed["step"]) == 3.0


def collective_run(mesh, fn, x, in_spec, out_spec, axis):
    return jax.shard_map(
        fn, mesh=mesh, in_specs=(in_spec,), out_specs=out_spec, check_vma=False
    )(x)


def test_psum_semantics():
    mesh = make_mesh(MeshPlan(data=8))
    x = jnp.arange(8.0)
    total = collective_run(
        mesh, lambda s: psum(s, "data"), x, P("data"), P("data"), "data"
    )
    np.testing.assert_allclose(np.asarray(total), np.full(8, 28.0))


def test_all_gather_semantics():
    mesh = make_mesh(MeshPlan(data=8))
    x = jnp.arange(8.0)
    gathered = collective_run(
        mesh, lambda s: all_gather(s, "data"), x, P("data"), P(None), "data"
    )
    np.testing.assert_allclose(np.asarray(gathered), np.arange(8.0))


def test_reduce_scatter_semantics():
    mesh = make_mesh(MeshPlan(data=4))
    # each shard holds the full row; reduce_scatter sums then splits
    x = jnp.tile(jnp.arange(4.0), (4, 1))  # (4 shards, 4)
    out = collective_run(
        mesh,
        lambda s: reduce_scatter(s[0], "data"),
        x.reshape(4, 4),
        P("data", None),
        P("data"),
        "data",
    )
    np.testing.assert_allclose(np.asarray(out), np.arange(4.0) * 4)


def test_ring_permute_rotates():
    mesh = make_mesh(MeshPlan(data=8))
    x = jnp.arange(8.0)
    rotated = collective_run(
        mesh, lambda s: ring_permute(s, "data", shift=1), x, P("data"), P("data"), "data"
    )
    np.testing.assert_allclose(np.asarray(rotated), np.roll(np.arange(8.0), 1))


def test_all_to_all_transposes_ownership():
    mesh = make_mesh(MeshPlan(data=4))
    x = jnp.arange(16.0).reshape(4, 4)  # device i owns row i
    out = collective_run(
        mesh,
        lambda s: all_to_all(s, "data", split_axis=1, concat_axis=0),
        x,
        P("data", None),
        P(None, "data"),
        "data",
    )
    np.testing.assert_allclose(np.asarray(out), np.arange(16.0).reshape(4, 4).T.reshape(4, 4).T)


def test_coordinator_spec():
    specs = coordinator_spec(["alice@w0", "w1"], port=9999)
    assert specs[0] == {
        "coordinator_address": "w0:9999",
        "num_processes": 2,
        "process_id": 0,
    }
    assert specs[1]["process_id"] == 1
