"""Speculative decoding: the output must be BIT-IDENTICAL to plain
greedy generation from the target model, for any draft — agreement only
changes the round count, never a token.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from covalent_tpu_plugin.models import (
    TransformerConfig,
    TransformerLM,
    generate,
    speculative_generate,
)

TARGET_CFG = TransformerConfig(
    vocab_size=64,
    d_model=32,
    n_layers=2,
    n_heads=4,
    d_ff=64,
    max_seq=48,
    dtype=jnp.float32,
    attention="reference",
)
DRAFT_CFG = dataclasses.replace(TARGET_CFG, d_model=16, n_layers=1, n_heads=2, d_ff=32)


def build(cfg, seed, prompt):
    model = TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(seed), prompt)["params"]
    return model, params


@pytest.mark.parametrize("draft_len", [1, 2, 4, 5])
@pytest.mark.parametrize("batch", [1, 3])
def test_speculative_matches_greedy_any_draft(draft_len, batch):
    """Random, disagreeing draft: worst case for speedup, but the tokens
    must still be exactly the target's greedy continuation."""
    prompt = jax.random.randint(jax.random.PRNGKey(1), (batch, 5), 0, 64)
    target, tparams = build(TARGET_CFG, 0, prompt)
    draft, dparams = build(DRAFT_CFG, 7, prompt)

    want = np.asarray(generate(target, tparams, prompt, 12))
    got = np.asarray(
        speculative_generate(
            target, tparams, draft, dparams, prompt, 12, draft_len=draft_len
        )
    )
    np.testing.assert_array_equal(got, want)


def test_self_draft_commits_full_windows():
    """Draft == target: every window fully accepted, so each round
    commits draft_len + 1 tokens (the bonus token) and rounds collapse
    to ceil((N-1)/(k+1)) — the mechanism's upper bound."""
    prompt = jax.random.randint(jax.random.PRNGKey(2), (1, 4), 0, 64)
    target, tparams = build(TARGET_CFG, 0, prompt)
    max_new, k = 11, 4  # ceil(10/5)=2 with the bonus; 3 without it
    out, stats = speculative_generate(
        target, tparams, target, tparams, prompt, max_new, draft_len=k,
        return_stats=True,
    )
    want = np.asarray(generate(target, tparams, prompt, max_new))
    np.testing.assert_array_equal(np.asarray(out), want)
    assert int(stats["rounds"]) == -(-(max_new - 1) // (k + 1))  # ceil


def test_speculative_is_jittable():
    prompt = jnp.zeros((2, 3), jnp.int32)
    target, tparams = build(TARGET_CFG, 0, prompt)
    draft, dparams = build(DRAFT_CFG, 5, prompt)
    fn = jax.jit(
        lambda tp, dp, t: speculative_generate(
            target, tp, draft, dp, t, 8, draft_len=3
        )
    )
    out = fn(tparams, dparams, prompt)
    assert out.shape == (2, 11)
    np.testing.assert_array_equal(
        np.asarray(out), np.asarray(fn(tparams, dparams, prompt))
    )
    np.testing.assert_array_equal(
        np.asarray(out), np.asarray(generate(target, tparams, prompt, 8))
    )


def test_speculative_sample_topk1_equals_greedy_any_draft():
    """top_k=1 collapses the filtered target to a point mass, so
    rejection sampling must reproduce greedy generate() BIT-EXACTLY for
    any draft — a deterministic end-to-end check of the acceptance,
    residual, and bonus plumbing."""
    from covalent_tpu_plugin.models import speculative_sample

    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 5), 0, 64)
    target, tparams = build(TARGET_CFG, 0, prompt)
    draft, dparams = build(DRAFT_CFG, 7, prompt)
    want = np.asarray(generate(target, tparams, prompt, 12))
    for seed in (0, 1):
        got = np.asarray(
            speculative_sample(
                target, tparams, draft, dparams, prompt, 12,
                draft_len=3, temperature=1.0, top_k=1,
                rng=jax.random.PRNGKey(seed),
            )
        )
        np.testing.assert_array_equal(got, want)


def test_speculative_sample_self_draft_full_accept():
    """Draft == target: p == q so every proposal is accepted and rounds
    hit the ceil((N-1)/(k+1)) floor, whatever the temperature."""
    from covalent_tpu_plugin.models import speculative_sample

    prompt = jax.random.randint(jax.random.PRNGKey(2), (1, 4), 0, 64)
    target, tparams = build(TARGET_CFG, 0, prompt)
    max_new, k = 11, 4
    out, stats = speculative_sample(
        target, tparams, target, tparams, prompt, max_new, draft_len=k,
        temperature=0.7, rng=jax.random.PRNGKey(3), return_stats=True,
    )
    assert out.shape == (1, 4 + max_new)
    assert int(stats["rounds"]) == -(-(max_new - 1) // (k + 1))


def test_speculative_sample_marginal_matches_target():
    """Distribution exactness, checked empirically: over many rows the
    FIRST sampled continuation's marginal must match the target's
    filtered softmax (total-variation tolerance), with a disagreeing
    draft forcing real rejections."""
    from covalent_tpu_plugin.models import speculative_sample

    rows = 512
    prompt = jnp.tile(jnp.asarray([[3, 9, 1]], jnp.int32), (rows, 1))
    target, tparams = build(TARGET_CFG, 0, prompt[:1])
    draft, dparams = build(DRAFT_CFG, 7, prompt[:1])
    out = speculative_sample(
        target, tparams, draft, dparams, prompt, 2,
        draft_len=2, temperature=1.0, rng=jax.random.PRNGKey(4),
    )
    # Column prompt_len+1 is the first token the accept/reject/residual
    # machinery produces (column prompt_len comes from plain prefill
    # sampling).  All rows share one prompt, hence one target dist.
    second = np.asarray(out)[:, prompt.shape[1] + 1]
    # Its true conditional depends on each row's first sampled token, so
    # compare against the MIXTURE: sum_t P(first=t) P(second|t) — but
    # with a shared prompt we can use the empirical pairing instead:
    # bucket rows by their first token and check each bucket's marginal.
    firsts = np.asarray(out)[:, prompt.shape[1]]
    logits = target.apply({"params": tparams}, np.asarray(out)[:, :-1])
    probs = np.asarray(
        jax.nn.softmax(logits[:, prompt.shape[1]].astype(jnp.float32), axis=-1)
    )
    for tok in np.unique(firsts):
        idx = firsts == tok
        if idx.sum() < 96:
            continue  # too few rows for a stable empirical estimate
        emp = np.bincount(second[idx], minlength=64) / idx.sum()
        tv = 0.5 * np.abs(emp - probs[idx][0]).sum()
        assert tv < 0.25, (tok, tv)


def _quantized_target(prompt, kv_quant):
    """int8-weight target (optionally + int8 KV cache) and its params."""
    from covalent_tpu_plugin.models import quantize_lm

    model, params = build(
        dataclasses.replace(TARGET_CFG, scan_layers=False), 0, prompt
    )
    qmodel, qparams = quantize_lm(model, params)
    if kv_quant:
        qmodel = TransformerLM(
            dataclasses.replace(qmodel.config, quantized_kv_cache=True)
        )
    return qmodel, qparams


@pytest.mark.parametrize("kv_quant", [False, True])
def test_speculative_composes_with_quantized_target(kv_quant):
    """The docstring's composition claim, proven: speculative_generate
    over an int8-weight (and int8-KV) target is bit-identical to that
    QUANTIZED target's own plain greedy decode — the exactness contract
    is against whatever model serves, not the float master."""
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 5), 0, 64)
    qtarget, qtparams = _quantized_target(prompt, kv_quant)
    draft, dparams = build(DRAFT_CFG, 7, prompt)

    want = np.asarray(generate(qtarget, qtparams, prompt, 12))
    got = np.asarray(
        speculative_generate(
            qtarget, qtparams, draft, dparams, prompt, 12, draft_len=3
        )
    )
    np.testing.assert_array_equal(got, want)
    # A quantized DRAFT composes too (any decode-capable pair).
    qdraft, qdparams = _quantized_target(prompt, kv_quant)
    got_qq = np.asarray(
        speculative_generate(
            qtarget, qtparams, qdraft, qdparams, prompt, 12, draft_len=3
        )
    )
    np.testing.assert_array_equal(got_qq, want)


@pytest.mark.parametrize("kv_quant", [False, True])
def test_speculative_sample_composes_with_quantized_target(kv_quant):
    """Sampling path over the quantized target: top_k=1 collapses to the
    quantized target's greedy decode (deterministic end-to-end check of
    acceptance/residual/bonus over int8 logits), and a self-draft
    full-accept run proves the rounds floor holds with int8 KV reads."""
    from covalent_tpu_plugin.models import speculative_sample

    prompt = jax.random.randint(jax.random.PRNGKey(8), (2, 5), 0, 64)
    qtarget, qtparams = _quantized_target(prompt, kv_quant)
    draft, dparams = build(DRAFT_CFG, 7, prompt)
    want = np.asarray(generate(qtarget, qtparams, prompt, 10))
    got = np.asarray(
        speculative_sample(
            qtarget, qtparams, draft, dparams, prompt, 10,
            draft_len=3, temperature=1.0, top_k=1,
            rng=jax.random.PRNGKey(0),
        )
    )
    np.testing.assert_array_equal(got, want)
    max_new, k = 11, 4
    _, stats = speculative_sample(
        qtarget, qtparams, qtarget, qtparams, prompt, max_new, draft_len=k,
        temperature=0.7, rng=jax.random.PRNGKey(3), return_stats=True,
    )
    assert int(stats["rounds"]) == -(-(max_new - 1) // (k + 1))


def test_speculative_sample_validation():
    from covalent_tpu_plugin.models import speculative_sample

    prompt = jnp.zeros((1, 4), jnp.int32)
    target, tparams = build(TARGET_CFG, 0, prompt)
    draft, dparams = build(DRAFT_CFG, 5, prompt)
    with pytest.raises(ValueError, match="temperature"):
        speculative_sample(
            target, tparams, draft, dparams, prompt, 4, temperature=0.0,
            rng=jax.random.PRNGKey(0),
        )
    with pytest.raises(ValueError, match="rng"):
        speculative_sample(
            target, tparams, draft, dparams, prompt, 4, temperature=1.0
        )


def test_speculative_edge_cases_and_validation():
    prompt = jnp.zeros((1, 4), jnp.int32)
    target, tparams = build(TARGET_CFG, 0, prompt)
    draft, dparams = build(DRAFT_CFG, 5, prompt)

    out = speculative_generate(target, tparams, draft, dparams, prompt, 0)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(prompt))

    one = speculative_generate(target, tparams, draft, dparams, prompt, 1)
    np.testing.assert_array_equal(
        np.asarray(one), np.asarray(generate(target, tparams, prompt, 1))
    )

    with pytest.raises(ValueError, match="draft_len"):
        speculative_generate(target, tparams, draft, dparams, prompt, 4,
                             draft_len=0)
    with pytest.raises(ValueError, match="max_seq"):
        speculative_generate(target, tparams, draft, dparams, prompt, 42,
                             draft_len=4)
    small_vocab = dataclasses.replace(DRAFT_CFG, vocab_size=32)
    other, oparams = build(small_vocab, 3, prompt)
    with pytest.raises(ValueError, match="vocabulary"):
        speculative_generate(target, tparams, other, oparams, prompt, 4)


def test_lane_spec_round_commits_target_greedy_and_freezes_done():
    """The continuous engine's per-lane round body, driven standalone:
    iterated rounds reproduce the target's greedy continuation exactly
    (whatever the draft proposes), each live round proposes draft_len
    tokens, and a frozen (done) lane is a strict no-op."""
    from covalent_tpu_plugin.models import init_cache
    from covalent_tpu_plugin.models.decode import _decode_model
    from covalent_tpu_plugin.models.speculative import make_lane_spec_round

    prompt = jnp.asarray([[5, 11, 3]], jnp.int32)
    target, tparams = build(TARGET_CFG, 0, prompt)
    draft, dparams = build(DRAFT_CFG, 7, prompt)
    tdec, ddec = _decode_model(target), _decode_model(draft)
    length, k, cap = 24, 3, 9
    lane_round = make_lane_spec_round(tdec, ddec, None, length, k)

    # Admission-equivalent setup: prefill both caches, commit the
    # target's first token at row[plen] with the cursor parked on it.
    cache = init_cache(target, 1)
    dcache = init_cache(draft, 1)
    tlogits, mut = tdec.apply(
        {"params": tparams, "cache": cache}, prompt, mutable=["cache"]
    )
    cache = mut["cache"]
    first = jnp.argmax(tlogits[0, -1].astype(jnp.float32)).astype(jnp.int32)
    _, dmut = ddec.apply(
        {"params": dparams, "cache": dcache}, prompt, mutable=["cache"]
    )
    dcache = dmut["cache"]

    plen = prompt.shape[1]
    row = (
        jnp.zeros((length,), jnp.int32)
        .at[:plen].set(prompt[0])
        .at[plen].set(first)
    )
    pos = jnp.asarray(plen, jnp.int32)
    n_gen = jnp.asarray(1, jnp.int32)
    done = jnp.asarray(False)
    cap_arr = jnp.asarray(cap, jnp.int32)

    rounds = 0
    while not bool(done):
        (cache, dcache, row, pos, n_gen, done, proposed, accepted) = (
            lane_round(
                tparams, dparams, cache, dcache, row, pos, cap_arr,
                n_gen, done,
            )
        )
        rounds += 1
        assert int(proposed) == k and 0 <= int(accepted) <= k
        assert rounds <= cap, "round never converged on the budget"

    want = np.asarray(generate(target, tparams, prompt, cap))[0]
    np.testing.assert_array_equal(np.asarray(row)[: plen + cap], want)
    assert int(n_gen) == cap

    # Frozen lane: zero proposals, state untouched.
    before = (int(pos), int(n_gen))
    (_c, _d, row2, pos, n_gen, done, proposed, accepted) = lane_round(
        tparams, dparams, cache, dcache, row, pos, cap_arr, n_gen, done,
    )
    assert int(proposed) == 0 and int(accepted) == 0
    assert (int(pos), int(n_gen)) == before and bool(done)
    np.testing.assert_array_equal(np.asarray(row2), np.asarray(row))
