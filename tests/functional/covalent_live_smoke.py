"""Live-Covalent smoke: one electron through a real Covalent server.

The reference CI's strongest gate starts a Covalent server and imports the
plugin through Covalent's own loader (reference .github/workflows/
tests.yml:80-84); this script goes one step further and dispatches a
1-electron lattice on ``executor="tpu"`` (resolved via the setup.py entry
point) through that server.  It is NOT a pytest test: covalent is not
installable in the sandbox (see tests/test_covalent_interop.py for the
stub tier), so CI's optional `covalent-interop` job runs it directly
after `covalent start -d`.

Exit 0 = dispatch reached COMPLETED with the right result.
"""

from __future__ import annotations

import sys


def main() -> int:
    import covalent as ct

    # The loader gate: the entry point `tpu = covalent_tpu_plugin.tpu`
    # must surface the class under covalent.executor.
    from covalent.executor import TPUExecutor  # noqa: F401

    executor = TPUExecutor(transport="local", poll_freq=0.5)

    @ct.electron(executor=executor)
    def square(x):
        return x * x

    @ct.lattice
    def flow(x):
        return square(x)

    dispatch_id = ct.dispatch(flow)(7)
    result = ct.get_result(dispatch_id, wait=True)
    print("status:", result.status, "result:", result.result)
    ok = str(result.status) == "COMPLETED" and result.result == 49
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
