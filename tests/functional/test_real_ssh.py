"""The real-SSH functional tier: every transport operation, the agent
channel, and a 2-process pod dispatch crossing a GENUINE SSH channel.

The reference validates its transport against a live host
(``tests/functional_tests/README.md:13``,
``basic_workflow_test.py:8-29``); rounds 1-4 here could not, because the
sandbox ships no SSH stack at all (no sshd/ssh/scp binaries, no asyncssh,
no paramiko — VERDICT r4 "What's missing" #1).  Round 5's vendored SSH2
implementation (``transport/minissh.py``: curve25519-sha256 kex,
ssh-ed25519 host keys, aes128-ctr + hmac-sha2-256, RFC 4254 channels)
closes that: these tests run an in-process SSH *server* and drive
``SSHTransport``'s minissh backend against it over a real TCP socket —
version exchange, key exchange, encryption, MAC verification, publickey
and password auth, window flow control, exec channels.  Where asyncssh IS
installed (CI's interop job), ``test_minissh_interop.py`` additionally
cross-validates this stack against it in both directions.
"""

from __future__ import annotations

import contextlib
import os
import pathlib
import socket
import sys

import pytest

# No cryptography -> no minissh stack -> no in-process SSH server to test
# against: skip the whole tier instead of erroring at collection.
pytest.importorskip(
    "cryptography",
    reason="minissh needs the `cryptography` package (absent in this image)",
)

from cryptography.hazmat.primitives import serialization
from cryptography.hazmat.primitives.asymmetric import ed25519

from covalent_tpu_plugin import TPUExecutor
from covalent_tpu_plugin.transport import minissh
from covalent_tpu_plugin.transport.ssh import SSHTransport, connect_with_retries

pytestmark = pytest.mark.functional_tests

REPO_ROOT = str(pathlib.Path(__file__).resolve().parents[2])


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _write_keys(tmp_path):
    """Client ed25519 keypair on disk (OpenSSH format, like ssh-keygen)."""
    key = ed25519.Ed25519PrivateKey.generate()
    key_path = tmp_path / "id_ed25519"
    key_path.write_bytes(
        key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.OpenSSH,
            serialization.NoEncryption(),
        )
    )
    os.chmod(key_path, 0o600)
    return key, str(key_path)


def _write_host_pub(tmp_path, server) -> str:
    pub_path = tmp_path / "host_key.pub"
    pub_path.write_bytes(
        server.host_key.public_key().public_bytes(
            serialization.Encoding.OpenSSH, serialization.PublicFormat.OpenSSH
        )
    )
    return str(pub_path)


@contextlib.asynccontextmanager
async def ssh_server(tmp_path, **kwargs):
    """An in-process sshd for the current event loop."""
    server = await minissh.serve(**kwargs)
    try:
        yield server
    finally:
        server.close()
        await server.wait_closed()


# --------------------------------------------------------------------- #
# Transport operations over the wire
# --------------------------------------------------------------------- #


def test_transport_ops_over_real_ssh(tmp_path, run_async):
    """put/get/run/remove/start_process through the encrypted channel,
    with strict host-key pinning on."""

    async def flow():
        client_key, key_path = _write_keys(tmp_path)
        async with ssh_server(
            tmp_path, authorized_keys=[client_key]
        ) as server:
            t = SSHTransport(
                hostname="127.0.0.1",
                username="tester",
                ssh_key_file=key_path,
                port=server.port,
                backend="minissh",
                strict_host_keys=True,
                known_host_key=server.host_key.public_key(),
            )
            await connect_with_retries(t, max_attempts=2, retry_wait_time=0.1)
            assert t.backend == "minissh"

            # run: stdout/stderr/exit separation
            res = await t.run("echo out; echo err >&2; exit 7")
            assert (res.exit_status, res.stdout, res.stderr) == (
                7, "out\n", "err\n"
            )

            # put/get: binary round trip through exec+cat
            blob = os.urandom(65536)
            (tmp_path / "local.bin").write_bytes(blob)
            await t.put(str(tmp_path / "local.bin"), str(tmp_path / "up.bin"))
            await t.get(str(tmp_path / "up.bin"), str(tmp_path / "down.bin"))
            assert (tmp_path / "down.bin").read_bytes() == blob

            # remove: the cleanup hot path
            await t.remove([str(tmp_path / "up.bin")])
            assert not (tmp_path / "up.bin").exists()

            # start_process: persistent line-oriented channel (the agent's
            # substrate)
            proc = await t.start_process(
                "while read x; do echo pong:$x; done"
            )
            await proc.write_line("1")
            assert await proc.read_line(timeout=30) == "pong:1"
            await proc.write_line("2")
            assert await proc.read_line(timeout=30) == "pong:2"
            await proc.close()
            await t.close()

    run_async(flow())


def test_password_auth_and_host_key_rejection(tmp_path, run_async):
    async def flow():
        async with ssh_server(tmp_path, users={"alice": "s3cret"}) as server:
            t = SSHTransport(
                hostname="127.0.0.1", username="alice", port=server.port,
                backend="minissh", strict_host_keys=False,
                password="s3cret",
            )
            await t._open()
            res = await t.run("printf authed")
            assert (res.exit_status, res.stdout) == (0, "authed")
            await t.close()

            # Wrong password -> auth error surfaced through the retry
            # classifier (bounded attempts, then failure).
            bad = SSHTransport(
                hostname="127.0.0.1", username="alice", port=server.port,
                backend="minissh", strict_host_keys=False, password="wrong",
            )
            with pytest.raises(Exception, match="authentication failed"):
                await bad._open()

            # Host-key mismatch under strict checking
            strict = SSHTransport(
                hostname="127.0.0.1", username="alice", port=server.port,
                backend="minissh", strict_host_keys=True, password="s3cret",
                known_host_key=minissh.generate_host_key().public_key(),
            )
            with pytest.raises(Exception, match="host key mismatch"):
                await strict._open()

    run_async(flow())


# --------------------------------------------------------------------- #
# Full electron dispatch over SSH
# --------------------------------------------------------------------- #


def _electron_body(n):
    import jax.numpy as jnp

    x = jnp.arange(n, dtype=jnp.float32)
    return float(x @ x)


def test_electron_dispatch_over_real_ssh(tmp_path, run_async):
    """The whole executor lifecycle — stage, upload, detached launch, poll,
    fetch, cleanup — over the encrypted channel, strict host keys on."""

    async def flow():
        client_key, key_path = _write_keys(tmp_path)
        async with ssh_server(
            tmp_path, authorized_keys=[client_key]
        ) as server:
            ex = TPUExecutor(
                transport="minissh",
                hostname=f"127.0.0.1:{server.port}",
                username="tester",
                ssh_key_file=key_path,
                known_host_key_file=_write_host_pub(tmp_path, server),
                strict_host_keys=True,
                cache_dir=str(tmp_path / "cache"),
                remote_cache=str(tmp_path / "remote"),
                python_path=sys.executable,
                poll_freq=0.2,
                task_timeout=300.0,
                use_agent=False,
                task_env={
                    "PYTHONPATH": REPO_ROOT + os.pathsep
                    + os.environ.get("PYTHONPATH", ""),
                    "JAX_PLATFORMS": "cpu",
                },
            )
            result = await ex.run(
                _electron_body, [1000], {},
                {"dispatch_id": "ssh-e2e", "node_id": 0},
            )
            await ex.close()
            return result

    assert run_async(flow()) == 332833152.0


def test_agent_pool_over_real_ssh(tmp_path, run_async):
    """The resident forkserver pool: upload + launch + push-events over a
    persistent SSH channel instead of nohup + poll round-trips."""

    async def flow():
        client_key, key_path = _write_keys(tmp_path)
        async with ssh_server(
            tmp_path, authorized_keys=[client_key]
        ) as server:
            ex = TPUExecutor(
                transport="minissh",
                hostname=f"127.0.0.1:{server.port}",
                username="tester",
                ssh_key_file=key_path,
                strict_host_keys=False,
                cache_dir=str(tmp_path / "cache"),
                remote_cache=str(tmp_path / "remote"),
                python_path=sys.executable,
                poll_freq=0.2,
                task_timeout=300.0,
                use_agent="pool",
                task_env={
                    "PYTHONPATH": REPO_ROOT + os.pathsep
                    + os.environ.get("PYTHONPATH", ""),
                    "JAX_PLATFORMS": "cpu",
                },
            )
            out = []
            for i in range(2):  # second electron reuses the warm pool
                out.append(await ex.run(
                    _electron_body, [100 * (i + 1)], {},
                    {"dispatch_id": f"ssh-agent{i}", "node_id": 0},
                ))
            await ex.close()
            return out

    first, second = run_async(flow())
    assert first == float(sum(i * i for i in range(100)))
    assert second == float(sum(i * i for i in range(200)))


def test_two_worker_pod_dispatch_over_real_ssh(tmp_path, run_async):
    """2-process jax.distributed psum where BOTH workers are reached over
    genuine SSH channels — the multi-worker story (fan-out staging,
    all-or-nothing launch, all-worker liveness, done-markers) on the real
    protocol end to end."""

    def distributed_psum_electron():
        import jax
        import jax.numpy as jnp

        n_local = jax.local_device_count()
        vals = jax.pmap(lambda x: jax.lax.psum(x, "i"), axis_name="i")(
            jnp.ones((n_local,))
        )
        return {
            "processes": jax.process_count(),
            "process_id": jax.process_index(),
            "global_devices": jax.device_count(),
            "psum": float(vals[0]),
        }

    async def flow():
        client_key, key_path = _write_keys(tmp_path)
        async with ssh_server(
            tmp_path, authorized_keys=[client_key]
        ) as w0, ssh_server(
            tmp_path, authorized_keys=[client_key]
        ) as w1:
            ex = TPUExecutor(
                transport="minissh",
                workers=[
                    f"tester@127.0.0.1:{w0.port}",
                    f"tester@127.0.0.1:{w1.port}",
                ],
                ssh_key_file=key_path,
                strict_host_keys=False,
                cache_dir=str(tmp_path / "cache"),
                remote_cache=str(tmp_path / "remote"),
                python_path=sys.executable,
                poll_freq=0.2,
                coordinator_port=_free_port(),
                task_timeout=600.0,
                use_agent=False,
                task_env={
                    "PYTHONPATH": REPO_ROOT + os.pathsep
                    + os.environ.get("PYTHONPATH", ""),
                    "JAX_PLATFORMS": "cpu",
                    "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
                },
            )
            result = await ex.run(
                distributed_psum_electron, [], {},
                {"dispatch_id": "ssh-pod", "node_id": 0},
            )
            await ex.close()
            return result

    result = run_async(flow())
    assert result["processes"] == 2
    assert result["process_id"] == 0
    assert result["global_devices"] == 4
    assert result["psum"] == 4.0
