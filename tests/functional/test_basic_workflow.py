"""Functional tier: full stack through the public API only, mirroring the
reference's ``tests/functional_tests/basic_workflow_test.py`` — a success
lattice and a failure lattice — but dispatched through ``TPUExecutor`` over
the local transport (BASELINE config 1's shape: hostname electron over the
loopback control plane, SURVEY §4.2b)."""

import socket

import pytest

import covalent_tpu_plugin.workflow as ct

from ..helpers import make_local_executor as make_tpu_executor

pytestmark = pytest.mark.functional_tests


def test_basic_workflow_success(tmp_path):
    """Reference: basic_workflow_test.py:8-29 — the canonical hostname
    electron (README.md:46-50) returning through the full lifecycle."""
    executor = make_tpu_executor(tmp_path)

    @ct.electron(executor=executor)
    def get_hostname():
        import socket as s

        return s.gethostname()

    @ct.electron
    def format_greeting(host):
        return f"Hello from {host}!"

    @ct.lattice
    def flow():
        return format_greeting(get_hostname())

    result = ct.dispatch_sync(flow)()
    assert result.status is ct.Status.COMPLETED, result.error
    assert result.result == f"Hello from {socket.gethostname()}!"
    # the executor recorded per-stage timings for the overhead budget
    assert executor.last_timings["overhead"] < 10.0


def test_basic_workflow_failure(tmp_path):
    """Reference: basic_workflow_test.py:32-49 — a failing electron marks
    the dispatch FAILED and surfaces the remote exception."""
    executor = make_tpu_executor(tmp_path)

    @ct.electron(executor=executor)
    def failing_task():
        raise AssertionError("induced failure in fake task")

    @ct.lattice
    def failing_flow():
        return failing_task()

    result = ct.dispatch_sync(failing_flow)()
    assert result.status is ct.Status.FAILED
    assert "induced failure in fake task" in result.error


def test_jax_workflow_mixed_executors(tmp_path):
    """Reference: svm_workflow.py — a realistic ML lattice with electrons on
    mixed executors (load/score local, train remote).  sklearn SVM becomes a
    jax ridge regression; the train electron crosses the machine boundary."""
    executor = make_tpu_executor(tmp_path)

    @ct.electron
    def load_data(n=64, d=4):
        import numpy as np

        rng = np.random.default_rng(0)
        x = rng.normal(size=(n, d)).astype("float32")
        w_true = rng.normal(size=(d,)).astype("float32")
        y = x @ w_true + 0.01 * rng.normal(size=(n,)).astype("float32")
        return x, y

    @ct.electron(executor=executor)
    def train_ridge(data, reg=1e-3):
        import jax.numpy as jnp

        x, y = data
        x = jnp.asarray(x)
        y = jnp.asarray(y)
        gram = x.T @ x + reg * jnp.eye(x.shape[1], dtype=x.dtype)
        w = jnp.linalg.solve(gram, x.T @ y)
        return w

    @ct.electron
    def score(data, w):
        import numpy as np

        x, y = data
        pred = x @ np.asarray(w)
        ss_res = float(((y - pred) ** 2).sum())
        ss_tot = float(((y - y.mean()) ** 2).sum())
        return 1.0 - ss_res / ss_tot

    @ct.lattice
    def ridge_flow():
        data = load_data()
        w = train_ridge(data)
        return score(data, w)

    result = ct.dispatch_sync(ridge_flow)()
    assert result.status is ct.Status.COMPLETED, result.error
    assert result.result > 0.95  # fit explains the data

    # the trained weights crossed the boundary as host arrays, not jax.Array
    import numpy as np

    assert isinstance(result.node_outputs[1], np.ndarray)


def test_electron_fanout_shares_connection_pool(tmp_path):
    """Many electrons on one executor instance must reuse the pooled
    transport + cached pre-flight (the <2 s overhead budget, SURVEY §3.1)."""
    executor = make_tpu_executor(tmp_path)

    @ct.electron(executor=executor)
    def work(i):
        return i * i

    @ct.lattice
    def fan_out():
        return [work(i) for i in range(5)]

    result = ct.dispatch_sync(fan_out)()
    assert result.status is ct.Status.COMPLETED, result.error
    assert result.result == [0, 1, 4, 9, 16]
    assert len(executor._pool) == 1  # one pooled channel, five electrons


def test_cancel_kills_remote_electron(tmp_path):
    """ct.cancel must TERM the worker-side harness process, not just abandon
    it — the capability the reference stubs (ssh.py:460-464)."""
    import time

    from ..helpers import make_local_executor

    executor = make_local_executor(tmp_path, task_timeout=60.0)
    started = tmp_path / "started"
    finished = tmp_path / "finished"

    @ct.electron(executor=executor)
    def slow(started_path, finished_path):
        import os
        import pathlib
        import time as _time

        pathlib.Path(started_path).write_text(str(os.getpid()))
        _time.sleep(45)
        pathlib.Path(finished_path).write_text("y")
        return "done"

    @ct.lattice
    def flow():
        return slow(str(started), str(finished))

    dispatch_id = ct.dispatch(flow)()
    deadline = time.time() + 30
    while not started.exists() and time.time() < deadline:
        time.sleep(0.05)
    assert started.exists(), "electron never started"

    harness_pid = int(started.read_text())

    t0 = time.perf_counter()
    result = ct.cancel(dispatch_id)
    assert result.status is ct.Status.CANCELLED
    assert time.perf_counter() - t0 < 15
    # The worker-side harness process must actually be DEAD (a regression
    # that merely abandons it would otherwise pass unobserved while the
    # process sleeps out its 45 s).
    import os
    import signal as _signal

    deadline = time.time() + 10
    alive = True
    while time.time() < deadline:
        try:
            os.kill(harness_pid, 0)
        except ProcessLookupError:
            alive = False
            break
        time.sleep(0.1)
    assert not alive, f"harness pid {harness_pid} still running after cancel"
    assert not finished.exists()
