"""Functional tier: realistic ML lattice with mixed executors + pip deps.

Mirrors the reference's ``tests/functional_tests/svm_workflow.py`` — data
loading and scoring on the default (local) executor, training on the remote
executor with a ``DepsPip`` attached (``svm_workflow.py:11-29``) — but the
classifier is a numpy ridge regression (no sklearn in this image) and the
pip install is redirected through ``COVALENT_TPU_PIP_CMD`` (the contract
stated in ``tests/test_deps.py``), so the install path runs end-to-end
without touching the network or a possibly PEP 668-managed interpreter.
"""

import shlex
import sys

import numpy as np
import pytest

import covalent_tpu_plugin.workflow as ct

from ..helpers import make_local_executor

pytestmark = pytest.mark.functional_tests


def test_ml_workflow_mixed_executors(tmp_path, monkeypatch):
    # Fake pip: record the requested packages and exit 0 (numpy is already
    # satisfied in the image; a real `pip install` would fail on PEP 668
    # externally-managed interpreters even for satisfied requirements).
    record = tmp_path / "pip_args.json"
    monkeypatch.setenv(
        "COVALENT_TPU_PIP_CMD",
        f"{shlex.quote(sys.executable)} -c "
        + shlex.quote(
            "import json,sys; json.dump(sys.argv[1:], open("
            + repr(str(record)) + ", 'w'))"
        ),
    )
    executor = make_local_executor(tmp_path)

    @ct.electron  # local, like svm_workflow.py:11 load_data
    def load_data(n=200, seed=0):
        rng = np.random.default_rng(seed)
        x = rng.standard_normal((n, 8))
        w_true = rng.standard_normal(8)
        y = (x @ w_true > 0).astype(np.float64)
        split = int(0.8 * n)
        return x[:split], y[:split], x[split:], y[split:]

    @ct.electron(
        executor=executor,
        deps_pip=ct.DepsPip(packages=["numpy"]),
    )  # remote, like svm_workflow.py:16-22 train_svm
    def train_model(data, reg=1e-3):
        import numpy as np

        x, y, _, _ = data
        w = np.linalg.solve(x.T @ x + reg * np.eye(x.shape[1]), x.T @ (2 * y - 1))
        return w

    @ct.electron  # local, like svm_workflow.py:25-29 score_svm
    def score_model(data, w):
        _, _, x_test, y_test = data
        pred = (x_test @ w > 0).astype(np.float64)
        return float((pred == y_test).mean())

    @ct.lattice  # svm_workflow.py:32-40 run_experiment
    def run_experiment(n=200):
        data = load_data(n)
        w = train_model(data)
        return score_model(data, w)

    result = ct.dispatch_sync(run_experiment)(200)
    assert result.status is ct.Status.COMPLETED, result.error
    assert result.result > 0.8  # linearly separable data -> high accuracy
    assert record.exists()  # the DepsPip install path actually ran
    assert "numpy" in record.read_text()
