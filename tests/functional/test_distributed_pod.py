"""Functional tier: a REAL 2-process jax.distributed cluster through the
full dispatch path.

The CPU analog of BASELINE config 5 (multi-host pod): the executor stages,
fans out, and launches two harness processes (workers "w0"/"w1" over the
local transport); each calls ``jax.distributed.initialize`` against the
loopback coordinator, the electron body runs a cross-process ``psum``, and
only process 0 writes the result.  This is the multi-host protocol end to
end — worker fan-out, all-or-nothing launch, coordinator rendezvous, done
markers, straggler reap — with no TPU pod required (SURVEY §4.2's
simulated-mesh tier, upgraded from fakes to real processes).
"""

import os
import pathlib
import socket
import sys

import pytest

from covalent_tpu_plugin import TPUExecutor

pytestmark = pytest.mark.functional_tests


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def distributed_psum_electron():
    import jax
    import jax.numpy as jnp

    assert jax.process_count() == 2, f"expected 2 processes, got {jax.process_count()}"
    n_local = jax.local_device_count()
    summed = jax.pmap(lambda v: jax.lax.psum(v, "i"), axis_name="i")(
        jnp.ones((n_local,))
    )
    return {
        "processes": jax.process_count(),
        "process_id": jax.process_index(),
        "global_devices": jax.device_count(),
        "psum": float(summed[0]),
    }


def distributed_lm_train_electron(steps: int):
    """BASELINE config 5 in miniature: data-parallel LM training across a
    REAL 2-process jax.distributed cluster — global mesh over both
    processes' devices, per-process input feeding
    (process_local_slice + shard_batch_per_process), sharded train step."""
    import jax
    import optax

    from covalent_tpu_plugin.models import (
        TransformerConfig,
        TransformerLM,
        lm_loss,
        make_sharded_train_state,
        make_train_step,
        synthetic_lm_batches,
    )
    from covalent_tpu_plugin.parallel import (
        MeshPlan,
        make_mesh,
        process_local_slice,
        shard_batch_per_process,
    )

    mesh = make_mesh(MeshPlan(data=jax.device_count()))
    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=2, d_ff=64,
        max_seq=16, attention="reference",
    )
    model = TransformerLM(cfg)
    batches = list(synthetic_lm_batches(steps, 8, 17, cfg.vocab_size, seed=1))
    sample = shard_batch_per_process(
        process_local_slice(batches[0]), mesh
    )
    state, shardings = make_sharded_train_state(
        model, optax.adamw(1e-2), jax.random.PRNGKey(0),
        sample["tokens"][:, :-1], mesh,
    )
    step = make_train_step(lm_loss, mesh, shardings)
    losses = []
    for batch in batches:
        local = process_local_slice(batch)
        state, metrics = step(state, shard_batch_per_process(local, mesh))
        losses.append(float(metrics["loss"]))
    return {
        "processes": jax.process_count(),
        "global_devices": jax.device_count(),
        "losses": losses,
    }


def test_two_process_data_parallel_lm_training(tmp_path, run_async):
    """Multi-host LM training end to end: the full dispatch path launches a
    2-process cluster; each process feeds its own batch shard."""
    repo_root = str(pathlib.Path(__file__).resolve().parents[2])
    ex = TPUExecutor(
        transport="local",
        workers=["w0", "w1"],
        cache_dir=str(tmp_path / "cache"),
        remote_cache=str(tmp_path / "remote"),
        python_path=sys.executable,
        poll_freq=0.2,
        coordinator_port=_free_port(),
        task_timeout=600.0,
        use_agent=False,
        task_env={
            "PYTHONPATH": repo_root + os.pathsep + os.environ.get("PYTHONPATH", ""),
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        },
    )

    async def flow():
        result = await ex.run(
            distributed_lm_train_electron, [8], {},
            {"dispatch_id": "pod-lm", "node_id": 0},
        )
        await ex.close()
        return result

    result = run_async(flow())
    assert result["processes"] == 2
    assert result["global_devices"] == 4
    losses = result["losses"]
    assert losses[-1] < losses[0], losses  # it actually learns


@pytest.mark.parametrize(
    "use_agent", [False, "pool"], ids=["nohup-poll", "pool-events"]
)
def test_two_process_distributed_psum(tmp_path, run_async, use_agent):
    repo_root = str(pathlib.Path(__file__).resolve().parents[2])
    ex = TPUExecutor(
        transport="local",
        workers=["w0", "w1"],
        cache_dir=str(tmp_path / "cache"),
        remote_cache=str(tmp_path / "remote"),
        python_path=sys.executable,
        poll_freq=0.2,
        coordinator_port=_free_port(),
        task_timeout=600.0,
        use_agent=use_agent,
        task_env={
            "PYTHONPATH": repo_root + os.pathsep + os.environ.get("PYTHONPATH", ""),
            "JAX_PLATFORMS": "cpu",
            # 2 virtual devices per process -> 4 global devices, so the psum
            # result distinguishes "saw the whole cluster" from "local only".
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        },
    )

    async def flow():
        result = await ex.run(
            distributed_psum_electron, [], {}, {"dispatch_id": "pod", "node_id": 0}
        )
        await ex.close()
        return result

    result = run_async(flow())
    assert result["processes"] == 2
    assert result["process_id"] == 0  # process 0 wrote the result
    assert result["global_devices"] == 4
    assert result["psum"] == 4.0  # summed across BOTH processes' devices
