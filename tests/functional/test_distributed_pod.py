"""Functional tier: a REAL 2-process jax.distributed cluster through the
full dispatch path.

The CPU analog of BASELINE config 5 (multi-host pod): the executor stages,
fans out, and launches two harness processes (workers "w0"/"w1" over the
local transport); each calls ``jax.distributed.initialize`` against the
loopback coordinator, the electron body runs a cross-process ``psum``, and
only process 0 writes the result.  This is the multi-host protocol end to
end — worker fan-out, all-or-nothing launch, coordinator rendezvous, done
markers, straggler reap — with no TPU pod required (SURVEY §4.2's
simulated-mesh tier, upgraded from fakes to real processes).
"""

import os
import pathlib
import socket
import sys

import pytest

from covalent_tpu_plugin import TPUExecutor

pytestmark = pytest.mark.functional_tests


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def distributed_psum_electron():
    import jax
    import jax.numpy as jnp

    assert jax.process_count() == 2, f"expected 2 processes, got {jax.process_count()}"
    n_local = jax.local_device_count()
    summed = jax.pmap(lambda v: jax.lax.psum(v, "i"), axis_name="i")(
        jnp.ones((n_local,))
    )
    return {
        "processes": jax.process_count(),
        "process_id": jax.process_index(),
        "global_devices": jax.device_count(),
        "psum": float(summed[0]),
    }


@pytest.mark.parametrize(
    "use_agent", [False, "pool"], ids=["nohup-poll", "pool-events"]
)
def test_two_process_distributed_psum(tmp_path, run_async, use_agent):
    repo_root = str(pathlib.Path(__file__).resolve().parents[2])
    ex = TPUExecutor(
        transport="local",
        workers=["w0", "w1"],
        cache_dir=str(tmp_path / "cache"),
        remote_cache=str(tmp_path / "remote"),
        python_path=sys.executable,
        poll_freq=0.2,
        coordinator_port=_free_port(),
        task_timeout=180.0,
        use_agent=use_agent,
        task_env={
            "PYTHONPATH": repo_root + os.pathsep + os.environ.get("PYTHONPATH", ""),
            "JAX_PLATFORMS": "cpu",
            # 2 virtual devices per process -> 4 global devices, so the psum
            # result distinguishes "saw the whole cluster" from "local only".
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        },
    )

    async def flow():
        result = await ex.run(
            distributed_psum_electron, [], {}, {"dispatch_id": "pod", "node_id": 0}
        )
        await ex.close()
        return result

    result = run_async(flow())
    assert result["processes"] == 2
    assert result["process_id"] == 0  # process 0 wrote the result
    assert result["global_devices"] == 4
    assert result["psum"] == 4.0  # summed across BOTH processes' devices
