"""Workflow-layer unit tests: DAG tracing, dependency edges, concurrent
scheduling, alias resolution.  (The upstream layers the reference leans on —
SURVEY §1 layers 1-2 — here exercised against the built-in engine.)"""

import time

import pytest

import covalent_tpu_plugin.workflow as ct


def test_electron_direct_call_runs_inline():
    @ct.electron
    def add(a, b):
        return a + b

    assert add(2, 3) == 5


def test_lattice_direct_call_runs_eagerly():
    @ct.electron
    def add(a, b):
        return a + b

    @ct.lattice
    def flow(x):
        return add(x, 1)

    assert flow(4) == 5


def test_trace_records_nodes_and_edges():
    @ct.electron
    def add(a, b):
        return a + b

    @ct.electron
    def mul(a, b):
        return a * b

    @ct.lattice
    def flow(x):
        s = add(x, 1)
        return mul(s, 2)

    graph = flow.build_graph(3)
    assert len(graph.nodes) == 2
    assert graph.nodes[0].name == "add"
    assert graph.nodes[1].dependencies() == {0}
    assert isinstance(graph.output, ct.Node)


def test_dependencies_found_in_containers():
    @ct.electron
    def make(x):
        return x

    @ct.electron
    def consume(items, mapping):
        return sum(items) + mapping["k"]

    @ct.lattice
    def flow():
        a = make(1)
        b = make(2)
        return consume([a, b], {"k": a})

    graph = flow.build_graph()
    assert graph.nodes[2].dependencies() == {0, 1}


def test_dispatch_success_end_to_end():
    @ct.electron
    def add(a, b):
        return a + b

    @ct.electron
    def square(a):
        return a * a

    @ct.lattice
    def flow(x, y):
        return square(add(x, y))

    dispatch_id = ct.dispatch(flow)(2, 3)
    result = ct.get_result(dispatch_id, wait=True, timeout=30)
    assert result.status is ct.Status.COMPLETED
    assert result.result == 25
    assert result.node_outputs == {0: 5, 1: 25}


def test_dispatch_failure_semantics():
    """Failure lattice per the reference functional test
    (basic_workflow_test.py:32-49): status FAILED, error recorded."""

    @ct.electron
    def boom():
        raise ValueError("workflow failure")

    @ct.electron
    def downstream(x):
        return x

    @ct.lattice
    def failing_flow():
        return downstream(boom())

    result = ct.dispatch_sync(failing_flow)()
    assert result.status is ct.Status.FAILED
    assert "workflow failure" in result.error
    assert 0 in result.node_errors


def test_independent_electrons_run_concurrently():
    @ct.electron
    def slow(tag):
        time.sleep(0.3)
        return tag

    @ct.lattice
    def fan_out():
        return [slow(i) for i in range(4)]

    start = time.perf_counter()
    result = ct.dispatch_sync(fan_out)()
    elapsed = time.perf_counter() - start
    assert result.status is ct.Status.COMPLETED
    assert result.result == [0, 1, 2, 3]
    # 4 × 0.3 s serial would be 1.2 s; concurrent should be well under.
    assert elapsed < 1.0


def test_unknown_executor_alias_fails_dispatch():
    @ct.electron(executor="warp-drive")
    def task():
        return 1

    @ct.lattice
    def flow():
        return task()

    result = ct.dispatch_sync(flow)()
    assert result.status is ct.Status.FAILED
    assert "warp-drive" in result.error


def test_downstream_of_failure_marked_skipped_not_failed():
    """Only the actually-failing node carries an error; dependents are
    skipped without duplicating/misattributing the upstream traceback."""

    @ct.electron
    def boom():
        raise ValueError("only-here")

    @ct.electron
    def downstream(x):
        return x

    @ct.lattice
    def flow():
        return downstream(boom())

    result = ct.dispatch_sync(flow)()
    assert result.status is ct.Status.FAILED
    assert list(result.node_errors) == [0]
    # one traceback, not one per downstream node
    assert result.error.count("ValueError: only-here") == 1


def test_positional_electron_call_keeps_executor():
    marker = object()
    e = ct.electron(lambda: 1, executor=marker)
    assert e.executor is marker


def test_get_result_unknown_id_raises():
    with pytest.raises(ValueError, match="unknown dispatch_id"):
        ct.get_result("nope")


def test_tpu_alias_registered():
    from covalent_tpu_plugin import TPUExecutor

    executor = ct.resolve_executor("local")
    assert isinstance(executor, ct.LocalExecutor)
    assert ct.resolve_executor(TPUExecutor(transport="local")).transport_kind == "local"


def test_deps_bash_runs_before_electron(tmp_path):
    marker = tmp_path / "bash_ran"

    @ct.electron(deps_bash=[f"echo before > {marker}"])
    def task():
        return marker.read_text().strip()

    @ct.lattice
    def flow():
        return task()

    result = ct.dispatch_sync(flow)()
    assert result.status is ct.Status.COMPLETED
    assert result.result == "before"


def test_deps_bash_failure_fails_electron():
    @ct.electron(deps_bash=["exit 3"])
    def task():
        return "unreachable"

    @ct.lattice
    def flow():
        return task()

    result = ct.dispatch_sync(flow)()
    assert result.status is ct.Status.FAILED
    assert "DepsBash" in result.error and "exit 3" in result.error


def test_cancel_running_dispatch(tmp_path):
    started = tmp_path / "started"
    finished = tmp_path / "finished"

    @ct.electron
    def slow():
        import time as _time

        started.write_text("y")
        _time.sleep(30)
        finished.write_text("y")
        return "done"

    @ct.lattice
    def flow():
        return slow()

    dispatch_id = ct.dispatch(flow)()
    for _ in range(100):
        if started.exists():
            break
        time.sleep(0.05)
    t0 = time.perf_counter()
    result = ct.cancel(dispatch_id)
    elapsed = time.perf_counter() - t0
    assert result.status is ct.Status.CANCELLED
    assert elapsed < 10  # did not sleep out the electron
    assert not finished.exists()


def test_cancel_finished_dispatch_is_noop():
    @ct.electron
    def quick():
        return 5

    @ct.lattice
    def flow():
        return quick()

    dispatch_id = ct.dispatch(flow)()
    result = ct.get_result(dispatch_id, wait=True)
    assert result.status is ct.Status.COMPLETED
    assert ct.cancel(dispatch_id).status is ct.Status.COMPLETED


def test_cancel_immediately_after_dispatch_prevents_execution(tmp_path):
    marker = tmp_path / "ran"

    @ct.electron
    def task():
        marker.write_text("y")
        return 1

    @ct.lattice
    def flow():
        return task()

    dispatch_id = ct.dispatch(flow)()
    result = ct.cancel(dispatch_id)
    assert result.status in (ct.Status.CANCELLED, ct.Status.COMPLETED)
    if result.status is ct.Status.CANCELLED and not result.node_outputs:
        # The pre-loop cancel path: no electron may have run at all, or the
        # race let it start — either way the status must be final, not hung.
        pass
    assert result._done.is_set()


def test_cancel_racing_completion_returns_final_result():
    @ct.electron
    def quick():
        return 9

    @ct.lattice
    def flow():
        return quick()

    dispatch_id = ct.dispatch(flow)()
    # Cancel may land before, during, or after completion; it must never
    # raise and must always return a final result.
    result = ct.cancel(dispatch_id)
    assert result._done.is_set()
    assert result.status in (ct.Status.CANCELLED, ct.Status.COMPLETED)


def test_results_store_bounded_retention(monkeypatch):
    """Terminal Results beyond the retention bound are evicted (with the
    eviction counter ticking); newer dispatches stay fetchable."""
    from covalent_tpu_plugin.obs.metrics import REGISTRY
    from covalent_tpu_plugin.workflow import runner

    monkeypatch.setenv("COVALENT_TPU_RESULT_RETENTION", "2")

    @ct.electron
    def ident(x):
        return x

    @ct.lattice
    def flow(x):
        return ident(x)

    evicted = REGISTRY.get("covalent_tpu_results_evicted_total")
    evicted0 = evicted.value if evicted else 0.0

    ids = []
    for i in range(5):
        dispatch_id = ct.dispatch(flow)(i)
        assert ct.get_result(dispatch_id, wait=True, timeout=30).result == i
        ids.append(dispatch_id)

    with runner._RESULTS_LOCK:
        terminal = [
            k for k, r in runner._RESULTS.items() if r._done.is_set()
        ]
    assert len(terminal) <= 2
    # The oldest dispatch was evicted; the newest is still fetchable.
    with pytest.raises(ValueError, match="unknown dispatch_id"):
        ct.get_result(ids[0])
    assert ct.get_result(ids[-1]).result == 4
    evicted_now = REGISTRY.get("covalent_tpu_results_evicted_total").value
    assert evicted_now - evicted0 >= 3


def test_result_retention_invalid_env_falls_back(monkeypatch):
    from covalent_tpu_plugin.workflow import runner

    monkeypatch.setenv("COVALENT_TPU_RESULT_RETENTION", "not-a-number")
    assert runner._result_retention() == runner._DEFAULT_RESULT_RETENTION
    monkeypatch.setenv("COVALENT_TPU_RESULT_RETENTION", "0")
    assert runner._result_retention() == 1  # never evict the only result
