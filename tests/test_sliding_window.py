"""Sliding-window attention: kernel-vs-oracle, tile-skip coverage,
model-level decode/pipeline consistency.

The decisive properties: the flash kernels (fwd + both backward sweeps)
match a handcrafted dense windowed softmax bit-for-tolerance at window
sizes that exercise the band's tile geometry (window inside one tile,
spanning tiles, larger than the sequence); cached decode equals full
recompute for a windowed model; the pipeline path stays equal to dense.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from covalent_tpu_plugin.models import TransformerConfig, TransformerLM, generate
from covalent_tpu_plugin.ops.attention import flash_attention, mha_reference


def dense_window_oracle(q, k, v, window):
    """Straight-line windowed causal softmax, no shared code with either
    implementation under test."""
    s_q, s_k = q.shape[2], k.shape[2]
    scores = jnp.einsum(
        "bhqd,bhkd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * (q.shape[-1] ** -0.5)
    qi = np.arange(s_q)[:, None]
    ki = np.arange(s_k)[None, :]
    visible = jnp.asarray((qi >= ki) & (qi - ki < window))
    scores = jnp.where(visible, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v.astype(jnp.float32))


def qkv(b=1, h=2, s=256, d=64, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(key, (b, h, s, d), dtype) for key in ks)


@pytest.mark.parametrize("window", [1, 37, 128, 200, 10_000])
def test_reference_matches_handwritten_oracle(window):
    q, k, v = qkv()
    want = np.asarray(dense_window_oracle(q, k, v, window))
    got = np.asarray(
        mha_reference(q, k, v, causal=True, window=window), np.float32
    )
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("window", [1, 37, 128, 200, 10_000])
def test_flash_forward_matches_reference(window):
    # block 64x64 => a 4x4 tile grid at s=256: the window tile-skip
    # branch really executes (a wrong skip bound zeroes live tiles here;
    # default blocks would fit the whole sequence in one tile and pass).
    q, k, v = qkv()
    want = np.asarray(
        mha_reference(q, k, v, causal=True, window=window), np.float32
    )
    got = np.asarray(
        flash_attention(
            q, k, v, causal=True, window=window, block_q=64, block_k=64
        ),
        np.float32,
    )
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("window", [300, 1500, 10_000])
def test_flash_backward_matches_reference_multitile(window):
    # s=2048 with the fixed 1024 backward tile edge => 2x2 tile grids in
    # both backward sweeps, so their window skip predicates execute.
    q, k, v = qkv(s=2048, h=1)

    def loss(fn):
        return lambda q, k, v: (
            fn(q, k, v).astype(jnp.float32) * jnp.cos(jnp.arange(64.0))
        ).sum()

    g_ref = jax.grad(
        loss(lambda q, k, v: mha_reference(q, k, v, causal=True, window=window)),
        argnums=(0, 1, 2),
    )(q, k, v)
    g_flash = jax.grad(
        loss(lambda q, k, v: flash_attention(q, k, v, causal=True, window=window)),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            atol=5e-5, rtol=5e-5,
        )


@pytest.mark.parametrize("window", [37, 128, 10_000])
def test_flash_backward_matches_reference(window):
    q, k, v = qkv(s=256)

    def loss(fn):
        return lambda q, k, v: (
            fn(q, k, v).astype(jnp.float32) * jnp.cos(jnp.arange(64.0))
        ).sum()

    g_ref = jax.grad(
        loss(lambda q, k, v: mha_reference(q, k, v, causal=True, window=window)),
        argnums=(0, 1, 2),
    )(q, k, v)
    g_flash = jax.grad(
        loss(lambda q, k, v: flash_attention(q, k, v, causal=True, window=window)),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            atol=3e-5, rtol=3e-5,
        )


def test_banded_grid_static_geometry():
    """The band-only grid really shrinks the inner sweep: at S=16k,
    w=1k with the default fwd/bwd tiles the key-tile (and query-tile)
    sweeps drop from 16 steps to 2 — this is the DMA-skip that turns the
    windowed win from ~2x into ~O(S/w)."""
    from covalent_tpu_plugin.ops.attention import (
        _banded_n_inner_kt, _banded_n_inner_qt,
    )

    assert _banded_n_inner_kt(16384, 16384, 512, 1024, 1024) == 2
    assert _banded_n_inner_qt(16384, 16384, 1024, 1024, 1024) == 2
    # Window >= sequence: no shrink possible, full grid (None) expected.
    assert _banded_n_inner_kt(256, 256, 64, 64, 10_000) is None
    assert _banded_n_inner_qt(256, 256, 64, 64, 10_000) is None
    # Tiny window still visits >= 1 tile per query tile.
    assert _banded_n_inner_kt(256, 256, 64, 64, 1) == 1
    # Sinks add a leading sink-tile run: one extra step here (sinks <= 64
    # fit one tile), still far below the 16-tile full sweep.
    assert _banded_n_inner_kt(16384, 16384, 512, 1024, 1024, sinks=4) == 3
    # Overlap folds into the sink run (band lo clamps to the sink tiles).
    assert _banded_n_inner_kt(256, 256, 64, 64, 37, sinks=4) == 3


@pytest.mark.parametrize("bq,bk", [(64, 128), (128, 64), (64, 64)])
def test_banded_grid_clamped_edges_exact(bq, bk):
    """Block shapes where the band's first tiles clamp at 0 and the causal
    edge produces duplicate (dead) DMA steps: liveness must come from grid
    arithmetic, not the clamped position tiles, or edge tiles double-count."""
    q, k, v = qkv(s=512)
    for window in (100, 130, 257):
        want = np.asarray(
            mha_reference(q, k, v, causal=True, window=window), np.float32
        )
        got = np.asarray(
            flash_attention(
                q, k, v, causal=True, window=window, block_q=bq, block_k=bk
            ),
            np.float32,
        )
        np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


def test_banded_backward_gqa_exact():
    """Banded dk/dv sweep must still sum gradients over the GQA group."""
    ks = jax.random.split(jax.random.PRNGKey(7), 3)
    q = jax.random.normal(ks[0], (1, 4, 512, 64))
    k = jax.random.normal(ks[1], (1, 2, 512, 64))
    v = jax.random.normal(ks[2], (1, 2, 512, 64))

    def loss(fn):
        return lambda q, k, v: (
            fn(q, k, v).astype(jnp.float32) * jnp.cos(jnp.arange(64.0))
        ).sum()

    g_ref = jax.grad(
        loss(lambda q, k, v: mha_reference(q, k, v, causal=True, window=150)),
        argnums=(0, 1, 2),
    )(q, k, v)
    g_flash = jax.grad(
        loss(lambda q, k, v: flash_attention(
            q, k, v, causal=True, window=150, block_q=128, block_k=128
        )),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            atol=5e-5, rtol=5e-5,
        )


def test_windowed_block_picker():
    """Windowed defaults follow the r4 hardware sweep winners
    (benchmarks/WINDOW_SWEEP.md): (512, 512) for w <= 512, (1024, 1024)
    wider; full-attention calls keep the full-attention defaults; fitted
    down for short sequences; explicit blocks always win."""
    from covalent_tpu_plugin.ops.attention import (
        _DEFAULT_BLOCK_K,
        _DEFAULT_BLOCK_Q,
        _fit_block,
        _pick_windowed_blocks,
    )

    assert _pick_windowed_blocks(16384, 16384, 512) == (512, 512)
    assert _pick_windowed_blocks(16384, 16384, 1024) == (1024, 1024)
    assert _pick_windowed_blocks(4096, 4096, 2048) == (1024, 1024)
    # The picker feeds _fit_block, so short sequences still tile.
    bq, bk = _pick_windowed_blocks(256, 256, 1024)
    assert _fit_block(bq, 256) == 256 and _fit_block(bk, 256) == 256
    # Full attention unaffected by the windowed table.
    assert (_DEFAULT_BLOCK_Q, _DEFAULT_BLOCK_K) == (512, 1024)
    # End to end: a windowed call with default blocks stays exact.
    q, k, v = qkv(s=1024)
    np.testing.assert_allclose(
        np.asarray(flash_attention(q, k, v, causal=True, window=600),
                   np.float32),
        np.asarray(mha_reference(q, k, v, causal=True, window=600),
                   np.float32),
        atol=2e-5, rtol=2e-5,
    )


def test_window_equals_full_causal_when_wider_than_sequence():
    q, k, v = qkv(s=128)
    full = np.asarray(flash_attention(q, k, v, causal=True), np.float32)
    windowed = np.asarray(
        flash_attention(q, k, v, causal=True, window=128), np.float32
    )
    np.testing.assert_allclose(windowed, full, atol=2e-6, rtol=2e-6)


def test_window_validation():
    q, k, v = qkv(s=128)
    with pytest.raises(ValueError, match="requires causal"):
        flash_attention(q, k, v, causal=False, window=8)
    with pytest.raises(ValueError, match="window must be"):
        mha_reference(q, k, v, causal=True, window=0)


BASE = TransformerConfig(
    vocab_size=64,
    d_model=32,
    n_layers=2,
    n_heads=4,
    d_ff=64,
    max_seq=32,
    dtype=jnp.float32,
    attention="reference",
    sliding_window=6,
)


def test_windowed_model_cached_decode_matches_recompute():
    """The decode path's cache band mask must agree with the training
    forward's window mask token-for-token."""
    model = TransformerLM(BASE)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 5), 0, BASE.vocab_size)
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]

    got = generate(model, params, prompt, max_new_tokens=8)
    tokens = prompt
    for _ in range(8):  # naive full-recompute oracle
        logits = model.apply({"params": params}, tokens)
        nxt = jnp.argmax(logits[:, -1].astype(jnp.float32), axis=-1)
        tokens = jnp.concatenate([tokens, nxt[:, None].astype(jnp.int32)], axis=1)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(tokens))


def test_windowed_model_differs_from_unwindowed():
    model = TransformerLM(BASE)
    tokens = jax.random.randint(jax.random.PRNGKey(2), (1, 20), 0, BASE.vocab_size)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    full_model = TransformerLM(dataclasses.replace(BASE, sliding_window=None))
    assert not np.allclose(
        np.asarray(model.apply({"params": params}, tokens)),
        np.asarray(full_model.apply({"params": params}, tokens)),
    )


def test_windowed_pipeline_matches_dense():
    from covalent_tpu_plugin.models.pipeline_lm import pipeline_lm_forward
    from covalent_tpu_plugin.parallel import MeshPlan, make_mesh

    cfg = dataclasses.replace(BASE, scan_layers=True, n_layers=4)
    mesh = make_mesh(MeshPlan(pipe=4))
    model = TransformerLM(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(3), (2, 16), 0, cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    logits_pp = pipeline_lm_forward(model, params, tokens, mesh, n_micro=2)
    logits_ref = model.apply({"params": params}, tokens)
    np.testing.assert_allclose(
        np.asarray(logits_pp), np.asarray(logits_ref), atol=2e-4, rtol=2e-4
    )


def test_windowed_ring_model_matches_reference_model():
    """sliding_window + attention='ring' compose (the banded ring): the
    model's logits must equal the windowed reference-attention model's."""
    from covalent_tpu_plugin.parallel import MeshPlan, make_mesh

    mesh = make_mesh(MeshPlan(seq=2, data=4))
    cfg = dataclasses.replace(BASE, attention="ring", mesh=mesh)
    model = TransformerLM(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(4), (4, 8), 0, BASE.vocab_size)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    ref_model = TransformerLM(dataclasses.replace(BASE))
    got = model.apply({"params": params}, tokens)
    want = ref_model.apply({"params": params}, tokens)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-4, rtol=2e-4
    )


def test_config_rejects_nonpositive_window():
    with pytest.raises(ValueError, match="sliding_window must be"):
        dataclasses.replace(BASE, sliding_window=0)


ROLLING = dataclasses.replace(BASE, rolling_cache=True)


def test_rolling_cache_matches_standard_within_max_seq():
    """While total length fits max_seq, the ring must produce exactly the
    standard windowed cache's tokens AND logits — including after the
    ring wraps.  (Token-only comparison once hid a phantom-slot bug whose
    logit error didn't happen to flip an argmax.)"""
    from covalent_tpu_plugin.models.decode import _decode_model, init_cache

    model = TransformerLM(BASE)
    rolling = TransformerLM(ROLLING)
    for seed in (1, 2, 3):
        prompt = jax.random.randint(
            jax.random.PRNGKey(seed), (2, 4), 0, BASE.vocab_size
        )
        params = model.init(jax.random.PRNGKey(0), prompt)["params"]
        # Prefill logits bit-for-tolerance, not just their argmax.
        std_logits, _ = _decode_model(model).apply(
            {"params": params, "cache": init_cache(model, 2)}, prompt,
            mutable=["cache"],
        )
        roll_logits, _ = _decode_model(rolling).apply(
            {"params": params, "cache": init_cache(rolling, 2)}, prompt,
            mutable=["cache"],
        )
        np.testing.assert_allclose(
            np.asarray(roll_logits), np.asarray(std_logits),
            atol=1e-5, rtol=1e-5,
        )
        want = generate(model, params, prompt, 20)  # wraps the ring 3x
        got = generate(rolling, params, prompt, 20)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_rolling_cache_generates_past_max_seq():
    """The point of the ring: generation beyond max_seq at O(window)
    memory, with finite outputs and an intact prompt."""
    model = TransformerLM(ROLLING)
    prompt = jax.random.randint(jax.random.PRNGKey(2), (1, 5), 0, BASE.vocab_size)
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]
    n_new = BASE.max_seq + 10  # 42 > max_seq=32
    out = jax.jit(lambda p, t: generate(model, p, t, n_new))(params, prompt)
    assert out.shape == (1, 5 + n_new)
    arr = np.asarray(out)
    np.testing.assert_array_equal(arr[:, :5], np.asarray(prompt))
    assert (arr >= 0).all() and (arr < BASE.vocab_size).all()
    # The ring really is window-sized, not max_seq-sized.
    from covalent_tpu_plugin.models.decode import init_cache

    cache = init_cache(model, 1)
    k_leaves = [
        leaf for path, leaf in jax.tree_util.tree_flatten_with_path(cache)[0]
        if any(getattr(e, "key", None) == "cached_k" for e in path)
    ]
    assert all(leaf.shape[-3] == BASE.sliding_window for leaf in k_leaves)


def test_rolling_cache_validation():
    with pytest.raises(ValueError, match="rolling_cache requires"):
        dataclasses.replace(BASE, sliding_window=None, rolling_cache=True)
    model = TransformerLM(ROLLING)
    long_prompt = jnp.zeros((1, 10), jnp.int32)  # > window of 6
    params = TransformerLM(BASE).init(
        jax.random.PRNGKey(0), long_prompt[:, :4]
    )["params"]
    # Past-capacity prompts stream by default (auto chunk = window, r4);
    # only wider-than-window chunks stay rejected (two slab tokens would
    # scatter into one ring slot).
    out = generate(model, params, long_prompt, 4)
    assert out.shape == (1, 14)
    with pytest.raises(ValueError, match="exceed sliding_window"):
        generate(model, params, long_prompt, 4, prefill_chunk=7)
    # Speculative decoding refuses rolling models outright.
    from covalent_tpu_plugin.models import speculative_generate

    with pytest.raises(ValueError, match="rolling_cache"):
        speculative_generate(
            model, params, model, params, long_prompt[:, :4], 4
        )


def test_rolling_chunked_prefill_exact_past_capacity():
    """The r4 exact chunked prefill: a past-capacity prompt streamed in
    chunks of ANY width <= sliding_window must reproduce the
    prefill_chunk=1 stream (the long-established exact path) bit for
    bit — logits at the boundary and every generated token.  Chunk 5
    does not divide P=24, so the last slab is ragged; chunk 6 == window
    is the new auto-default."""
    from covalent_tpu_plugin.models.decode import _decode_model, init_cache

    model = TransformerLM(ROLLING)
    prompt = jax.random.randint(
        jax.random.PRNGKey(5), (2, 24), 0, BASE.vocab_size  # 4x capacity
    )
    params = TransformerLM(BASE).init(
        jax.random.PRNGKey(0), prompt[:, :4]
    )["params"]

    def stream_logits(chunk):
        """Last-position logits after prefilling the prompt in chunks."""
        decoder = _decode_model(model)
        cache = init_cache(model, 2)
        for start in range(0, prompt.shape[1], chunk):
            logits, mutated = decoder.apply(
                {"params": params, "cache": cache},
                prompt[:, start:start + chunk], mutable=["cache"],
            )
            cache = mutated["cache"]
        return np.asarray(logits[:, -1])

    want_logits = stream_logits(1)
    want_tokens = np.asarray(
        generate(model, params, prompt, 8, prefill_chunk=1)
    )
    for chunk in (2, 3, 5, 6):
        np.testing.assert_allclose(
            stream_logits(chunk), want_logits, atol=1e-5, rtol=1e-5,
            err_msg=f"chunk={chunk}",
        )
        np.testing.assert_array_equal(
            np.asarray(
                generate(model, params, prompt, 8, prefill_chunk=chunk)
            ),
            want_tokens, err_msg=f"chunk={chunk}",
        )
    # The auto default (prefill_chunk unset) matches too.
    np.testing.assert_array_equal(
        np.asarray(generate(model, params, prompt, 8)), want_tokens
    )


def test_rolling_chunked_prefill_exact_with_quantized_kv():
    """Chunked past-capacity prefill composes with the int8 KV cache:
    the slab branch must quantise/dequantise exactly like the cache
    branch, so chunk=window reproduces the chunk=1 token stream."""
    cfg = dataclasses.replace(ROLLING, quantized_kv_cache=True)
    model = TransformerLM(cfg)
    prompt = jax.random.randint(
        jax.random.PRNGKey(6), (2, 24), 0, BASE.vocab_size
    )
    params = TransformerLM(BASE).init(
        jax.random.PRNGKey(0), prompt[:, :4]
    )["params"]
    want = np.asarray(generate(model, params, prompt, 8, prefill_chunk=1))
    got = np.asarray(generate(model, params, prompt, 8))
    np.testing.assert_array_equal(got, want)
