"""Shared test fakes.

``FakeTransport`` is the analog of the reference's hand-rolled ``_FakeConn``
(``tests/ssh_test.py:120-132``): an in-memory Transport with scripted
responses keyed by command substring, recording every call so orchestration
tests can assert the control-plane conversation.
"""

from __future__ import annotations

import sys

import cloudpickle

from covalent_tpu_plugin.transport.base import CommandResult, Transport


def pin_cpu_task_env(kwargs: dict) -> dict:
    """Merge ``JAX_PLATFORMS=cpu`` under a kwargs dict's ``task_env``.

    Harness subprocesses must run on CPU in tests: a sandbox sitecustomize
    can re-pin the platform to an experimental PJRT plugin whose backend
    init hangs, and only the harness's jax.config pin (driven by spec env)
    reliably overrides it.  Caller-provided task_env keys win.
    """
    kwargs["task_env"] = {"JAX_PLATFORMS": "cpu", **kwargs.get("task_env", {})}
    return kwargs


def make_local_executor(tmp_path, **kwargs):
    """A TPUExecutor over the local transport, staged under tmp_path."""
    from covalent_tpu_plugin import TPUExecutor

    kwargs.setdefault("transport", "local")
    kwargs.setdefault("cache_dir", str(tmp_path / "cache"))
    kwargs.setdefault("remote_cache", str(tmp_path / "remote"))
    kwargs.setdefault("python_path", sys.executable)
    kwargs.setdefault("poll_freq", 0.2)
    kwargs.setdefault("use_agent", False)  # dedicated agent tests opt in
    return TPUExecutor(**pin_cpu_task_env(kwargs))


class FakeTransport(Transport):
    def __init__(self, responses: dict | None = None, address: str = "fake-worker"):
        self.address = address
        self.commands: list[str] = []
        self.puts: list[tuple[str, str]] = []
        self.gets: list[tuple[str, str]] = []
        self.closed = False
        #: substring -> CommandResult | callable(command) -> CommandResult
        self.responses = responses or {}
        #: what query_result's download materialises locally
        self.result_payload: tuple = (None, None)

    async def run(self, command: str, timeout: float | None = None) -> CommandResult:
        self.commands.append(command)
        for pattern, response in self.responses.items():
            if pattern in command:
                return response(command) if callable(response) else response
        return CommandResult(0, "", "")

    async def put(self, local_path: str, remote_path: str) -> None:
        self.puts.append((local_path, remote_path))

    async def get(self, remote_path: str, local_path: str) -> None:
        self.gets.append((remote_path, local_path))
        with open(local_path, "wb") as f:
            cloudpickle.dump(self.result_payload, f)

    async def close(self) -> None:
        self.closed = True


def scripted_ok_responses(
    pid: int = 12345, status: str = "READY"
) -> dict:
    """Happy-path responses for a full run(): preflight, submit, status."""
    return {
        "mkdir -p": CommandResult(0, "3\n", ""),
        "nohup": CommandResult(0, f"{pid}\n", ""),
        "if test -f": CommandResult(0, f"{status}\n", ""),
        "tail -n": CommandResult(0, "log tail\n", ""),
        "rm -f": CommandResult(0, "", ""),
    }
