"""Config system tests (reference analog: config mocking at ssh_test.py:29-52)."""

from covalent_tpu_plugin.utils import config as config_mod


def test_get_config_missing_returns_default(tmp_config):
    assert config_mod.get_config("executors.tpu.nope", "fallback") == "fallback"


def test_set_then_get_roundtrip(tmp_config):
    config_mod.set_config("executors.tpu.python_path", "/opt/py/bin/python3")
    assert config_mod.get_config("executors.tpu.python_path") == "/opt/py/bin/python3"


def test_set_persists_to_toml(tmp_config):
    config_mod.set_config("executors.tpu.poll_freq", 0.25)
    config_mod.set_config("executors.tpu.create_unique_workdir", True)
    config_mod._reset_cache_for_tests()
    assert config_mod.get_config("executors.tpu.poll_freq") == 0.25
    assert config_mod.get_config("executors.tpu.create_unique_workdir") is True


def test_update_config_does_not_clobber_user_values(tmp_config):
    config_mod.set_config("executors.tpu.remote_workdir", "/custom")
    config_mod.update_config({"remote_workdir": "/default", "new_key": "v"})
    assert config_mod.get_config("executors.tpu.remote_workdir") == "/custom"
    assert config_mod.get_config("executors.tpu.new_key") == "v"


def test_update_config_without_file_stays_in_memory(tmp_config):
    # No config file on disk: defaults must merge in memory but not create one.
    config_mod.update_config({"some_default": 1})
    assert config_mod.get_config("executors.tpu.some_default") == 1
    assert not tmp_config.exists()


def test_nested_sections_and_list_values(tmp_config):
    config_mod.set_config("executors.tpu.workers", ["h1", "h2"])
    config_mod._reset_cache_for_tests()
    assert config_mod.get_config("executors.tpu.workers") == ["h1", "h2"]
