"""Worker-side crash-recovery protocol: epoch fencing, inventories,
stream resume, and orphan-mode re-adoption.

These tests drive a pool server as a raw subprocess over JSONL pipes —
no AgentClient — because the scenario under test IS the death of that
client: the dispatcher-side pipes are closed mid-stream and the worker
must hold its sessions, publish a rendezvous, and hand the protocol to
whoever adopts it over the unix socket.  The harness file is copied to a
tmp dir first, exactly as the dispatcher stages it into the remote
cache, so the rendezvous artifacts land next to the copy (the contract
`_orphan_dir()` implements), never inside the source tree.
"""

import json
import os
import shutil
import socket
import subprocess
import sys
import threading
import time

import cloudpickle

from covalent_tpu_plugin import harness as harness_mod
from covalent_tpu_plugin.cache import bytes_digest


def _make_factory(step_delay=0.0, slots=2, chunk=2, default_cap=6):
    """Deterministic closure-local engine (same contract as test_serving):
    prompt ``[..., base]`` streams ``base+1 .. base+cap``."""

    def factory():
        import time as time_mod

        class Engine:
            def __init__(self):
                self.slots = slots
                self.lanes = {}

            def admit(self, rid, prompt, params):
                cap = int((params or {}).get("max_new_tokens", default_cap))
                base = int(prompt[-1])
                self.lanes[rid] = [base + i + 1 for i in range(cap)]

            def step(self):
                if step_delay:
                    time_mod.sleep(step_delay)
                events = []
                for rid in list(self.lanes):
                    taken = self.lanes[rid][:chunk]
                    self.lanes[rid] = self.lanes[rid][chunk:]
                    done = not self.lanes[rid]
                    if done:
                        del self.lanes[rid]
                    events.append({"rid": rid, "tokens": taken, "done": done})
                return events

            def cancel(self, rid):
                self.lanes.pop(rid, None)

        return Engine()

    return factory


class Worker:
    """A pool server over raw pipes, with a background JSONL reader."""

    def __init__(self, tmp_path, env=None):
        self.dir = tmp_path / "pool"
        self.dir.mkdir(exist_ok=True)
        self.harness = self.dir / "harness.py"
        shutil.copyfile(harness_mod.__file__, self.harness)
        full_env = dict(os.environ)
        full_env.update({
            "COVALENT_TPU_AGENT_FRAMES": "0",  # JSONL only: asserted shapes
            "COVALENT_TPU_POOL_PRELOAD": "cloudpickle",
            "JAX_PLATFORMS": "cpu",
        })
        full_env.update(env or {})
        self.proc = subprocess.Popen(
            [sys.executable, str(self.harness), "--serve"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, env=full_env,
        )
        self.events: list = []
        self._cond = threading.Condition()
        self._reader = threading.Thread(
            target=self._read, args=(self.proc.stdout,), daemon=True
        )
        self._reader.start()

    def _read(self, stream) -> None:
        try:
            for raw in stream:
                try:
                    event = json.loads(raw)
                except ValueError:
                    continue
                with self._cond:
                    self.events.append(event)
                    self._cond.notify_all()
        except (OSError, ValueError):
            pass  # read end torn down by the test: the "crash"

    def stage(self, factory):
        payload = cloudpickle.dumps(factory)
        digest = bytes_digest(payload)
        path = self.dir / f"{digest}.pkl"
        path.write_bytes(payload)
        return digest, str(path)

    def send(self, **cmd) -> None:
        self.proc.stdin.write((json.dumps(cmd) + "\n").encode())
        self.proc.stdin.flush()

    def wait_for(self, pred, timeout=20.0):
        deadline = time.monotonic() + timeout
        with self._cond:
            while True:
                for event in self.events:
                    if pred(event):
                        return event
                left = deadline - time.monotonic()
                if left <= 0:
                    raise AssertionError(
                        f"no matching event within {timeout}s; saw "
                        f"{[e.get('event') for e in self.events]}"
                    )
                self._cond.wait(left)

    def tokens(self, rid):
        """Exactly-once splice of every serve.token chunk seen for rid."""
        out: list = []
        with self._cond:
            chunks = [
                e["data"] for e in self.events
                if e.get("event") == "telemetry"
                and (e.get("data") or {}).get("type") == "serve.token"
                and e["data"].get("rid") == rid
            ]
        for data in chunks:
            idx = int(data.get("idx") or 0)
            toks = list(data.get("tokens") or [])
            if idx > len(out):
                raise AssertionError(f"token gap for {rid}: idx {idx} > have {len(out)}")
            fresh = toks[len(out) - idx:]
            out.extend(fresh)
        return out

    def crash_dispatcher(self) -> None:
        """Sever both pipes without touching the child: stdout first so
        in-flight emits hit a dead pipe (tokens genuinely lost), then
        stdin EOF to trigger the worker's orphan path."""
        try:
            self.proc.stdout.close()
        except OSError:
            pass
        try:
            self.proc.stdin.close()
        except OSError:
            pass

    def close(self) -> None:
        if self.proc.poll() is None:
            try:
                self.proc.stdin.close()
            except (OSError, ValueError):
                pass
            try:
                self.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait()


class SockChannel:
    """JSONL over the adoption unix socket — the successor dispatcher."""

    def __init__(self, path: str):
        self.sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self.sock.settimeout(20.0)
        self.sock.connect(path)
        self._file = self.sock.makefile("rb")
        self.events: list = []
        self._cond = threading.Condition()
        self._reader = threading.Thread(target=self._read, daemon=True)
        self._reader.start()

    def _read(self) -> None:
        try:
            for raw in self._file:
                try:
                    event = json.loads(raw)
                except ValueError:
                    continue
                with self._cond:
                    self.events.append(event)
                    self._cond.notify_all()
        except (OSError, ValueError):
            pass

    def send(self, **cmd) -> None:
        self.sock.sendall((json.dumps(cmd) + "\n").encode())

    def wait_for(self, pred, timeout=20.0):
        deadline = time.monotonic() + timeout
        with self._cond:
            while True:
                for event in self.events:
                    if pred(event):
                        return event
                left = deadline - time.monotonic()
                if left <= 0:
                    raise AssertionError(
                        f"no matching socket event within {timeout}s; saw "
                        f"{[e.get('event') for e in self.events]}"
                    )
                self._cond.wait(left)

    def tokens(self, rid, base=0):
        """Splice with absolute idx positions; ``base`` is the resumed
        stream's starting offset (the client-held high-water mark)."""
        out: list = []
        with self._cond:
            chunks = [
                e["data"] for e in self.events
                if e.get("event") == "telemetry"
                and (e.get("data") or {}).get("type") == "serve.token"
                and e["data"].get("rid") == rid
            ]
        for data in chunks:
            idx = int(data.get("idx") or 0) - base
            toks = list(data.get("tokens") or [])
            if idx > len(out):
                raise AssertionError(f"token gap for {rid}: idx {idx} > have {len(out)}")
            out.extend(toks[len(out) - idx:])
        return out

    def close(self) -> None:
        # makefile() dups the fd: both must close for the worker's read
        # end to see EOF.
        for closer in (self.sock.close, self._file.close):
            try:
                closer()
            except OSError:
                pass


def _open_session(worker, sid="s-rec", **factory_kw):
    digest, path = worker.stage(_make_factory(**factory_kw))
    worker.send(cmd="serve_open", id=sid, digest=digest, path=path,
                options={"stats_interval_s": 30.0})
    worker.wait_for(
        lambda e: e.get("event") == "serve_opened" and e.get("id") == sid
    )
    return sid


# -- epoch fencing -----------------------------------------------------------


def test_epoch_fencing_refuses_stale_dispatcher(tmp_path):
    worker = Worker(tmp_path)
    try:
        worker.wait_for(lambda e: e.get("event") == "ready")
        worker.send(cmd="epoch", epoch=2)
        worker.wait_for(
            lambda e: e.get("event") == "epoch_ok" and e.get("epoch") == 2
        )

        # A stale dispatcher declares an older epoch: refused outright...
        worker.send(cmd="epoch", epoch=1)
        worker.wait_for(
            lambda e: e.get("event") == "error"
            and e.get("code") == "stale_epoch"
        )
        # ...and every mutating verb on that channel is fenced, each with
        # its caller-shaped refusal.
        worker.send(cmd="serve_open", id="s-x", digest="d", path="p")
        worker.wait_for(
            lambda e: e.get("event") == "serve_error" and e.get("id") == "s-x"
            and e.get("code") == "stale_epoch" and e.get("permanent")
        )
        worker.send(cmd="serve_request", id="s-x", rid="r-x", prompt=[1])
        worker.wait_for(
            lambda e: e.get("event") == "telemetry"
            and (e.get("data") or {}).get("type") == "serve.reject"
            and e["data"].get("code") == "stale_epoch"
        )
        worker.send(cmd="serve_resume", id="s-x", rid="r-x")
        worker.wait_for(
            lambda e: e.get("event") == "serve_resumed"
            and e.get("state") == "refused"
        )
        # Read-only verbs stay live: a stale dispatcher may look, not touch.
        worker.send(cmd="ping")
        worker.wait_for(lambda e: e.get("event") == "pong")
        worker.send(cmd="serve_inventory")
        worker.wait_for(
            lambda e: e.get("event") == "serve_inventory"
            and e.get("epoch") == 2
        )

        # The rightful successor re-declares and the fence lifts.
        worker.send(cmd="epoch", epoch=3)
        worker.wait_for(
            lambda e: e.get("event") == "epoch_ok" and e.get("epoch") == 3
        )
        _open_session(worker, "s-ok")
    finally:
        worker.close()


# -- inventories + resume ----------------------------------------------------


def test_inventory_reports_sessions_and_streams(tmp_path):
    worker = Worker(tmp_path)
    try:
        sid = _open_session(worker, "s-inv", default_cap=4)
        worker.send(cmd="serve_request", id=sid, rid="r-1", prompt=[100])
        worker.wait_for(
            lambda e: e.get("event") == "telemetry"
            and (e.get("data") or {}).get("type") == "serve.token"
            and e["data"].get("rid") == "r-1" and e["data"].get("done")
        )
        worker.send(cmd="serve_inventory")
        inv = worker.wait_for(lambda e: e.get("event") == "serve_inventory")
        assert [s["sid"] for s in inv["sessions"]] == [sid]
        entry = inv["sessions"][0]
        assert entry["finished"]["r-1"]["tokens"] == 4
        assert entry["finished"]["r-1"]["error"] == ""
        assert entry["served"] == 1

        worker.send(cmd="task_inventory")
        tasks = worker.wait_for(lambda e: e.get("event") == "task_inventory")
        assert tasks["tasks"] == []
    finally:
        worker.close()


def test_serve_resume_states(tmp_path):
    worker = Worker(tmp_path)
    try:
        sid = _open_session(
            worker, "s-res", slots=1, step_delay=0.25, chunk=2,
            default_cap=20,
        )
        worker.send(cmd="serve_request", id=sid, rid="r-live", prompt=[0])
        worker.send(cmd="serve_request", id=sid, rid="r-queued", prompt=[50])
        worker.wait_for(
            lambda e: e.get("event") == "telemetry"
            and (e.get("data") or {}).get("type") == "serve.token"
            and e["data"].get("rid") == "r-live"
        )

        # Mid-decode: full history re-emitted from the asked offset.
        worker.send(cmd="serve_resume", id=sid, rid="r-live", **{"from": 0})
        ack = worker.wait_for(
            lambda e: e.get("event") == "serve_resumed"
            and e.get("rid") == "r-live"
        )
        assert ack["state"] == "streaming"
        assert ack["from"] == 0 and ack["sent"] >= 2

        # Queued behind the single slot: pending, nothing re-emitted.
        worker.send(cmd="serve_resume", id=sid, rid="r-queued", **{"from": 0})
        assert worker.wait_for(
            lambda e: e.get("event") == "serve_resumed"
            and e.get("rid") == "r-queued"
        )["state"] == "pending"

        # Never submitted here: unknown — the dispatcher re-sends in full.
        worker.send(cmd="serve_resume", id=sid, rid="r-ghost", **{"from": 0})
        assert worker.wait_for(
            lambda e: e.get("event") == "serve_resumed"
            and e.get("rid") == "r-ghost"
        )["state"] == "unknown"

        # Unknown session id entirely.
        worker.send(cmd="serve_resume", id="s-ghost", rid="r-1", **{"from": 0})
        assert worker.wait_for(
            lambda e: e.get("event") == "serve_resumed"
            and e.get("id") == "s-ghost"
        )["state"] == "unknown"

        # Drain both, then resume a FINISHED stream from an offset: the
        # bounded finished-ring re-emits the tail plus the done marker.
        worker.wait_for(
            lambda e: e.get("event") == "telemetry"
            and (e.get("data") or {}).get("type") == "serve.token"
            and e["data"].get("rid") == "r-queued" and e["data"].get("done"),
            timeout=40.0,
        )
        assert worker.tokens("r-live") == list(range(1, 21))
        worker.send(cmd="serve_resume", id=sid, rid="r-live", **{"from": 18})
        done_ack = worker.wait_for(
            lambda e: e.get("event") == "serve_resumed"
            and e.get("rid") == "r-live" and e.get("state") == "done"
        )
        assert done_ack["from"] == 18 and done_ack["sent"] == 2
    finally:
        worker.close()


# -- orphan mode + re-adoption ----------------------------------------------


def _wait_rendezvous(worker, timeout=20.0):
    path = worker.dir / "pool_orphan.json"
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if path.exists():
            try:
                return json.loads(path.read_text())
            except ValueError:
                pass  # mid-replace
        if worker.proc.poll() is not None:
            raise AssertionError("worker exited instead of orphaning")
        time.sleep(0.05)
    raise AssertionError("orphan rendezvous never published")


def test_orphan_adoption_resumes_streams_exactly_once(tmp_path):
    worker = Worker(tmp_path, env={"COVALENT_TPU_ORPHAN_TTL_S": "60"})
    try:
        worker.wait_for(lambda e: e.get("event") == "ready")
        worker.send(cmd="epoch", epoch=5)
        worker.wait_for(lambda e: e.get("event") == "epoch_ok")
        sid = _open_session(
            worker, "s-adopt", step_delay=0.1, chunk=2, default_cap=40
        )
        worker.send(cmd="serve_request", id=sid, rid="r-a", prompt=[1000])
        worker.wait_for(
            lambda e: e.get("event") == "telemetry"
            and (e.get("data") or {}).get("type") == "serve.token"
            and e["data"].get("rid") == "r-a"
        )
        hwm = len(worker.tokens("r-a"))
        assert hwm >= 2

        # The dispatcher dies mid-stream.  Tokens emitted from here land
        # in the dead pipe and are gone — only the worker's history and
        # our high-water mark survive.
        worker.crash_dispatcher()
        meta = _wait_rendezvous(worker)
        assert meta["pid"] == worker.proc.pid
        assert meta["epoch"] == 5
        assert meta["sessions"] == [sid]

        # A stale successor (older epoch) is refused and the worker keeps
        # waiting for the rightful one.
        stale = SockChannel(meta["sock"])
        stale.send(cmd="adopt", epoch=4)
        stale.wait_for(
            lambda e: e.get("event") == "error"
            and e.get("code") == "stale_epoch"
        )
        stale.close()

        # The real successor adopts: fresh banner, session roster intact.
        chan = SockChannel(meta["sock"])
        chan.send(cmd="adopt", epoch=6)
        banner = chan.wait_for(lambda e: e.get("event") == "ready")
        assert banner.get("reattach") is True
        assert banner.get("epoch") == 6
        assert banner.get("sessions") == [sid]
        # Rendezvous artifacts are cleaned up once adopted.
        deadline = time.monotonic() + 10
        while (worker.dir / "pool_orphan.json").exists():
            assert time.monotonic() < deadline
            time.sleep(0.05)

        # Resume from OUR high-water mark: worker re-emits history[hwm:]
        # and live chunks follow — splice must come out byte-equal.
        chan.send(cmd="serve_resume", id=sid, rid="r-a", **{"from": hwm})
        ack = chan.wait_for(
            lambda e: e.get("event") == "serve_resumed"
            and e.get("rid") == "r-a"
        )
        assert ack["state"] in ("streaming", "done")
        chan.wait_for(
            lambda e: e.get("event") == "telemetry"
            and (e.get("data") or {}).get("type") == "serve.token"
            and e["data"].get("rid") == "r-a" and e["data"].get("done"),
            timeout=40.0,
        )
        resumed = chan.tokens("r-a", base=hwm)
        assert [t for t in range(1001, 1001 + hwm)] + resumed == list(
            range(1001, 1041)
        )

        # New traffic flows on the adopted channel too.
        chan.send(cmd="serve_request", id=sid, rid="r-b", prompt=[2000],
                  params={"max_new_tokens": 4})
        chan.wait_for(
            lambda e: e.get("event") == "telemetry"
            and (e.get("data") or {}).get("type") == "serve.token"
            and e["data"].get("rid") == "r-b" and e["data"].get("done"),
            timeout=40.0,
        )
        assert chan.tokens("r-b") == [2001, 2002, 2003, 2004]

        chan.send(cmd="serve_close", id=sid)
        chan.wait_for(
            lambda e: e.get("event") == "serve_closed" and e.get("id") == sid
        )
        chan.close()
        worker.proc.wait(timeout=15)
    finally:
        worker.close()


def test_orphan_ttl_expiry_drains_and_exits(tmp_path):
    """Satellite: the never-returning dispatcher.  A worker must not leak
    forever — after the grace TTL it drains its sessions and exits."""
    worker = Worker(tmp_path, env={"COVALENT_TPU_ORPHAN_TTL_S": "1"})
    try:
        sid = _open_session(worker, "s-ttl", default_cap=2)
        worker.send(cmd="serve_request", id=sid, rid="r-1", prompt=[1])
        worker.wait_for(
            lambda e: e.get("event") == "telemetry"
            and (e.get("data") or {}).get("type") == "serve.token"
            and e["data"].get("done")
        )
        worker.crash_dispatcher()
        _wait_rendezvous(worker)
        worker.proc.wait(timeout=20)  # nobody adopts: drain + exit
        assert not (worker.dir / "pool_orphan.json").exists()
        assert not list(worker.dir.glob("pool_orphan.*.sock"))
    finally:
        worker.close()


def test_no_ttl_means_no_orphan_mode(tmp_path):
    """Without the knob the historical contract holds: sessions die with
    the channel and the server exits promptly."""
    worker = Worker(tmp_path)
    try:
        _open_session(worker, "s-plain", default_cap=2)
        worker.crash_dispatcher()
        worker.proc.wait(timeout=15)
        assert not (worker.dir / "pool_orphan.json").exists()
    finally:
        worker.close()
