"""End-to-end causal tracing: carrier hardening, the tail-sampled trace
store, waterfall assembly, exemplar-linked histograms, the ``/traces``
ops routes, and the continuity contracts — one trace id follows an
electron across gang retries (``op`` -> ``op.r1``) and a serving request
across the warm handoff (ISSUE 16 acceptance).

Unit tests construct private :class:`TraceStore`/:class:`Registry`
instances with explicit bounds and sample rates (no env, no globals);
the integration tests at the bottom drive the REAL local transport and
read the process-wide store the ops endpoint serves.
"""

from __future__ import annotations

import asyncio
import json
import urllib.error
import urllib.request

import pytest

from covalent_tpu_plugin.obs import events as obs_events
from covalent_tpu_plugin.obs.metrics import Registry
from covalent_tpu_plugin.obs.tracestore import TRACE_STORE, TraceStore
from covalent_tpu_plugin.obs.trace import (
    Span,
    context_of,
    extract_context,
    record_span,
)

from .helpers import make_local_executor


# --------------------------------------------------------------------- #
# Carrier round-trip + malformed-carrier hardening
# --------------------------------------------------------------------- #


def test_context_roundtrip():
    with Span("root", emit=False) as root:
        carrier = context_of(root, attempt=2)
    assert carrier["trace_id"] == root.trace_id
    assert carrier["span_id"] == root.span_id
    assert carrier["attempt"] == 2  # extras ride along verbatim
    assert extract_context(carrier) == (root.trace_id, root.span_id)
    # The round trip survives JSON (the carrier rides a frame header).
    wired = json.loads(json.dumps(carrier))
    assert extract_context(wired) == (root.trace_id, root.span_id)


@pytest.mark.parametrize(
    "carrier",
    [
        None,
        "",
        "tid:sid",
        42,
        [],
        ["trace_id", "span_id"],
        {},
        {"trace_id": "t"},                      # span_id missing
        {"span_id": "s"},                       # trace_id missing
        {"trace_id": "", "span_id": "s"},       # falsy id
        {"trace_id": None, "span_id": "s"},
        {"trace_id": ["t"], "span_id": "s"},    # wrong type
        {"trace_id": "t", "span_id": {"x": 1}},
    ],
)
def test_extract_context_rejects_malformed_carriers(carrier):
    assert extract_context(carrier) is None


def test_extract_context_coerces_int_ids():
    # JSON off an old/foreign producer may carry numeric ids; they
    # stringify rather than poison downstream string handling.
    assert extract_context({"trace_id": 7, "span_id": 9}) == ("7", "9")


def test_span_adopts_remote_context():
    carrier = {"trace_id": "t" * 32, "span_id": "p" * 16}
    with Span("remote.child", emit=False,
              context=extract_context(carrier)) as child:
        pass
    assert child.trace_id == "t" * 32
    assert child.parent_id == "p" * 16
    # A live LOCAL parent still wins over a remote carrier.
    with Span("local.root", emit=False) as root:
        with Span("leaf", emit=False,
                  context=extract_context(carrier)) as leaf:
            pass
    assert leaf.trace_id == root.trace_id
    assert leaf.parent_id == root.span_id


def test_record_span_mints_and_preserves_ids():
    seen: list[dict] = []
    listener = seen.append
    obs_events.add_listener(listener)
    try:
        sid = record_span("retro.minted", duration_s=-0.5)
        record_span(
            "retro.given",
            trace_id="T1",
            parent_id="P1",
            span_id="S1",
            start_ts=123.0,
            duration_s=0.25,
            status="ERROR",
            attributes={"segment": "x"},
        )
    finally:
        obs_events.remove_listener(listener)
    minted = next(e for e in seen if e["name"] == "retro.minted")
    assert minted["span_id"] == sid and len(sid) == 16
    assert len(minted["trace_id"]) == 32  # fresh root trace minted
    assert minted["duration_s"] == 0.0   # negative clamps, never raises
    given = next(e for e in seen if e["name"] == "retro.given")
    assert given["trace_id"] == "T1" and given["parent_id"] == "P1"
    assert given["span_id"] == "S1" and given["start_ts"] == 123.0
    assert given["status"] == "ERROR"
    assert given["attributes"]["segment"] == "x"


# --------------------------------------------------------------------- #
# Trace store: assembly + tail-based keep decisions
# --------------------------------------------------------------------- #


def feed(store, trace_id, name, *, parent=None, span_id=None,
         start_ts=100.0, duration_s=0.01, status="OK", attributes=None):
    store.record_event({
        "type": "span",
        "name": name,
        "trace_id": trace_id,
        "span_id": span_id or f"{name}-id",
        "parent_id": parent,
        "start_ts": start_ts,
        "duration_s": duration_s,
        "status": status,
        **({"attributes": attributes} if attributes else {}),
    })


def test_store_assembles_on_root_close():
    store = TraceStore(sample=1.0)
    feed(store, "t1", "child", parent="root-id", start_ts=100.1)
    assert store.waterfall("t1")["keep_reason"] == "open"  # still pending
    feed(store, "t1", "root", span_id="root-id", duration_s=0.5)
    view = store.waterfall("t1")
    assert view["keep_reason"] == "sampled"
    assert view["root"] == "root" and view["duration_s"] == 0.5
    assert view["span_count"] == 2
    index = store.index()
    assert index["traces"][0]["trace_id"] == "t1"
    assert index["finalized"] == 1 and index["kept_total"] == 1


def test_store_sampling_drops_unremarkable_traces():
    store = TraceStore(sample=0.0)
    feed(store, "t1", "root")
    assert store.waterfall("t1") is None
    assert store.index()["count"] == 0
    # ... and the dropped memory refuses straggler resurrection.
    feed(store, "t1", "straggler", parent="root-id")
    assert store.waterfall("t1") is None
    assert store.index()["pending"] == 0


def test_store_always_keeps_errors():
    store = TraceStore(sample=0.0)
    feed(store, "t1", "child", parent="root-id", status="ERROR")
    feed(store, "t1", "root", span_id="root-id")
    assert store.waterfall("t1")["keep_reason"] == "error"


def test_store_keeps_slo_burn_window_traces():
    store = TraceStore(sample=0.0)
    store.record_event({"type": "slo.burn", "slo": "serve_p95"})
    feed(store, "t1", "root")
    store.record_event({"type": "slo.recovered", "slo": "serve_p95"})
    feed(store, "t2", "root")
    assert store.waterfall("t1")["keep_reason"] == "slo_burn"
    assert store.waterfall("t2") is None  # burn over: back to sampling


def test_store_keeps_p99_outliers():
    store = TraceStore(sample=0.0)
    # Gently DECREASING durations: each root stays under the p99 of its
    # history, so nothing trips the outlier rule while the baseline
    # accumulates past the minimum-history gate.
    for i in range(25):
        feed(store, f"fast{i}", "serve.request", duration_s=0.05 - 0.001 * i)
    assert store.index()["count"] == 0  # unremarkable, all sampled out
    feed(store, "slow", "serve.request", duration_s=5.0)
    assert store.waterfall("slow")["keep_reason"] == "p99_outlier"


def test_store_splices_stragglers_into_kept_traces():
    store = TraceStore(sample=1.0, max_spans=3)
    feed(store, "t1", "root", span_id="root-id")
    feed(store, "t1", "worker.decode", parent="root-id", start_ts=100.2)
    view = store.waterfall("t1")
    assert view["span_count"] == 2
    assert [s["name"] for s in view["spans"]] == ["root", "worker.decode"]
    # Splice respects the span cap: overflow is counted, not stored.
    feed(store, "t1", "late1", parent="root-id")
    feed(store, "t1", "late2", parent="root-id")
    view = store.waterfall("t1")
    assert view["span_count"] == 3
    assert view["dropped_spans"] == 1


def test_store_bounds_kept_and_pending():
    store = TraceStore(sample=1.0, max_traces=2, max_pending=2)
    for tid in ("a", "b", "c"):
        feed(store, tid, "root")
    ids = [t["trace_id"] for t in store.index()["traces"]]
    assert ids == ["c", "b"]  # newest-first, LRU-evicted past the cap
    # Pending overflow finalizes the stalest open trace as "evicted"
    # (sampled like the rest; sample=1.0 keeps it, root unknown).
    feed(store, "p1", "child1", parent="x")
    feed(store, "p2", "child2", parent="y")
    feed(store, "p3", "child3", parent="z")
    assert store.index()["pending"] == 2
    evicted = store.waterfall("p1")
    assert evicted is not None and evicted["keep_reason"] == "evicted"
    assert evicted["duration_s"] is None  # root never closed


def test_waterfall_offsets_depths_orphans_segments_coverage():
    store = TraceStore(sample=1.0)
    feed(store, "t1", "serve.prefill", parent="root-id",
         start_ts=100.0, duration_s=0.3,
         attributes={"segment": "prefill"})
    feed(store, "t1", "serve.ttft_wait", parent="root-id",
         start_ts=100.3, duration_s=0.5,
         attributes={"segment": "ttft_wait"})
    feed(store, "t1", "worker.decode", parent="missing-parent",
         start_ts=100.4, duration_s=0.1)
    feed(store, "t1", "serve.request", span_id="root-id",
         start_ts=100.0, duration_s=1.0)
    view = store.waterfall("t1")
    by_name = {s["name"]: s for s in view["spans"]}
    assert by_name["serve.request"]["depth"] == 0
    assert by_name["serve.prefill"]["depth"] == 1
    assert by_name["serve.prefill"]["offset_s"] == 0.0
    assert by_name["serve.ttft_wait"]["offset_s"] == pytest.approx(0.3)
    assert by_name["worker.decode"]["orphan"] is True
    assert not by_name["serve.prefill"]["orphan"]
    assert view["segments"] == {
        "prefill": {"duration_s": 0.3, "count": 1},
        "ttft_wait": {"duration_s": 0.5, "count": 1},
    }
    assert view["coverage"] == pytest.approx(0.8)
    # Spans come back start-ordered for direct waterfall rendering.
    assert [s["name"] for s in view["spans"]][0] in (
        "serve.request", "serve.prefill"
    )
    dump = store.dump()
    assert [t["trace_id"] for t in dump["traces"]] == ["t1"]
    json.dumps(dump)  # artifact-ready end to end


# --------------------------------------------------------------------- #
# Exemplars: histogram -> trace cross-link
# --------------------------------------------------------------------- #


def test_histogram_exemplars_in_snapshot():
    reg = Registry()
    h = reg.histogram("rt_seconds", "", buckets=(0.5, 2.0))
    h.observe(0.1, trace_id="trace-fast")
    h.observe(1.0, trace_id="trace-mid-old")
    h.observe(1.2, trace_id="trace-mid-new")
    h.observe(0.7)  # no trace: must not clobber the bucket's exemplar
    series = reg.snapshot()["metrics"]["rt_seconds"]["series"][0]
    exemplars = series["exemplars"]
    by_trace = {e["trace_id"]: e for e in exemplars.values()}
    assert "trace-fast" in by_trace
    # Most-recent-per-bucket: the newer mid-bucket observation wins.
    assert "trace-mid-new" in by_trace
    assert "trace-mid-old" not in by_trace
    assert by_trace["trace-mid-new"]["value"] == 1.2


def test_openmetrics_exposition_carries_exemplars():
    reg = Registry()
    h = reg.histogram("rt_seconds", "round trips", buckets=(0.5,))
    h.observe(0.1, trace_id="abc123")
    plain = reg.prometheus_text()
    assert "# {" not in plain and "# EOF" not in plain
    om = reg.prometheus_text(openmetrics=True)
    assert '# {trace_id="abc123"}' in om
    assert om.endswith("# EOF\n")


# --------------------------------------------------------------------- #
# Ops routes: /traces index + waterfall, OpenMetrics negotiation
# --------------------------------------------------------------------- #


@pytest.fixture()
def ops_server(monkeypatch):
    from covalent_tpu_plugin.obs import opsserver as ops_mod

    monkeypatch.setenv("COVALENT_TPU_OPS_PORT", "0")
    server = ops_mod.OpsServer(port=0)
    yield server
    server.close()


def http_get(port: int, path: str, accept: str | None = None):
    request = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        headers={"Accept": accept} if accept else {},
    )
    with urllib.request.urlopen(request, timeout=10) as response:
        return (
            response.status,
            response.read(),
            response.headers.get("Content-Type", ""),
        )


@pytest.fixture()
def kept_trace():
    """One finalized trace in the process-wide store, removed after."""
    TRACE_STORE.sample = 1.0
    tid = "ops-route-trace"
    try:
        TRACE_STORE.record_event({
            "type": "span", "name": "serve.request", "trace_id": tid,
            "span_id": "root-id", "parent_id": None,
            "start_ts": 100.0, "duration_s": 0.5, "status": "OK",
        })
        yield tid
    finally:
        TRACE_STORE._sample_override = None
        with TRACE_STORE._lock:
            TRACE_STORE._kept.pop(tid, None)


def test_ops_traces_routes(ops_server, kept_trace):
    status, body, _ = http_get(ops_server.port, "/traces")
    assert status == 200
    index = json.loads(body)
    assert kept_trace in [t["trace_id"] for t in index["traces"]]
    status, body, _ = http_get(ops_server.port, f"/traces/{kept_trace}")
    assert status == 200
    view = json.loads(body)
    assert view["root"] == "serve.request"
    assert view["spans"][0]["span_id"] == "root-id"
    with pytest.raises(urllib.error.HTTPError) as err:
        http_get(ops_server.port, "/traces/no-such-trace")
    assert err.value.code == 404


def test_ops_metrics_openmetrics_negotiation(ops_server):
    status, body, ctype = http_get(ops_server.port, "/metrics")
    assert status == 200
    assert "openmetrics" not in ctype
    assert not body.decode().endswith("# EOF\n")
    for request_kwargs in (
        {"path": "/metrics?format=openmetrics"},
        {"path": "/metrics",
         "accept": "application/openmetrics-text; version=1.0.0"},
    ):
        status, body, ctype = http_get(ops_server.port, **request_kwargs)
        assert status == 200
        assert "application/openmetrics-text" in ctype
        assert body.decode().endswith("# EOF\n")


def test_flightrec_cross_links_traces():
    from covalent_tpu_plugin.obs.flightrec import FlightRecorder

    rec = FlightRecorder()
    rec.record_event({
        "type": "task.state", "operation_id": "xl_0.r1",
        "state": "submitted", "trace_id": "trace-xl",
    })
    view = rec.view("xl_0")  # retry records file under the base op id
    assert view["trace_id"] == "trace-xl"
    assert view["trace_url"] == "/traces/trace-xl"


# --------------------------------------------------------------------- #
# Continuity: one trace across gang retries and the warm handoff
# --------------------------------------------------------------------- #


@pytest.fixture()
def events_file(tmp_path):
    path = tmp_path / "events.jsonl"
    obs_events.configure(str(path))
    yield path
    obs_events.reset()


def read_events(path) -> list[dict]:
    if not path.exists():
        return []
    return [json.loads(line) for line in path.read_text().splitlines()]


def test_retry_keeps_one_trace_across_attempts(
    tmp_path, run_async, events_file
):
    """``op`` -> ``op.r1``: the channel dies mid-poll, the gang is retried,
    and every span + worker event of BOTH attempts shares one trace id."""
    from covalent_tpu_plugin.transport import ChaosPlan

    plan = ChaosPlan(drop_match="if test -f", max_faults=1)
    ex = make_local_executor(
        tmp_path, chaos=plan, max_task_retries=2,
        retry_base_delay=0.05, retry_max_delay=0.1, poll_freq=0.1,
    )

    async def flow():
        try:
            return await ex.run(
                lambda a, b: a + b, [20, 22], {},
                {"dispatch_id": "tracecont", "node_id": 0},
            )
        finally:
            await ex.close()

    assert run_async(flow()) == 42
    assert plan.faults_injected == 1 and ex.last_attempts == 2
    events = read_events(events_file)
    runs = [e for e in events if e["type"] == "span"
            and e["name"] == "executor.run"]
    assert len(runs) == 2  # one span per attempt...
    assert len({e["trace_id"] for e in runs}) == 1  # ...one trace
    assert [e["attributes"]["attempt"] for e in runs] == [0, 1]
    trace_id = runs[0]["trace_id"]
    worker = [e for e in events if e["type"].startswith("worker.")]
    ops = {e["operation_id"] for e in worker}
    # The retried attempt ran to completion, so its worker records are
    # guaranteed; the killed first attempt's are racy (the gang may die
    # before its harness wrote anything) — but whatever DID land carries
    # the one trace id.
    assert "tracecont_0.r1" in ops
    assert all(e["trace_id"] == trace_id for e in worker)


def test_warm_handoff_keeps_one_serving_trace(tmp_path, run_async):
    """The request's root span survives the drain-and-reopen: same trace
    id on both generations, one finalized store entry whose waterfall
    segments tile the request end to end with zero orphan spans."""
    from covalent_tpu_plugin.obs.tracestore import ensure_trace_store
    from covalent_tpu_plugin.serving import open_session

    from .test_serving import make_factory, make_serve_executor

    store = ensure_trace_store()
    store.sample = 1.0

    async def flow():
        ex = make_serve_executor(tmp_path)
        try:
            handle = await open_session(
                ex, make_factory(step_delay=0.1, default_cap=12)
            )
            requests = [await handle.request([100 * i]) for i in range(2)]
            for _ in range(200):
                if all(len(r.tokens) >= 4 for r in requests):
                    break
                await asyncio.sleep(0.05)
            before = [r.span.trace_id for r in requests]
            moved = await handle.handoff(reason="trace-test")
            results = [await r.result(timeout=60) for r in requests]
            after = [r.span.trace_id for r in requests]
            await handle.close()
        finally:
            await ex.close()
        return moved, results, before, after

    try:
        moved, results, before, after = run_async(flow())
    finally:
        store._sample_override = None
    assert moved is True
    for i, tokens in enumerate(results):
        assert tokens == [100 * i + j + 1 for j in range(12)], tokens
    assert before == after  # continuity: the handoff never re-rooted
    for trace_id in after:
        view = store.waterfall(trace_id)
        assert view is not None, f"trace {trace_id} never finalized"
        assert view["root"] == "serve.request"
        assert not any(s["orphan"] for s in view["spans"]), view["spans"]
        segments = view["segments"]
        # The streaming tiles must be there; route/dispatch tiles may
        # collapse to zero width on the local transport and drop out.
        assert "ttft_wait" in segments and "decode_stream" in segments
        # Tiling covers the request end to end (within rounding).
        assert view["coverage"] == pytest.approx(1.0, abs=0.11)
        # Worker-side spans off BOTH generations joined the trace.
        names = {s["name"] for s in view["spans"]}
        assert "serve.worker.decode" in names
