"""SSH transport, both backends, exercised for real (VERDICT r1 missing #1).

The reference's entire transport is asyncssh
(``covalent_ssh_plugin/ssh.py:263-268``, scp at ``ssh.py:360-361, 451``);
this sandbox has neither asyncssh nor the OpenSSH binaries, so two tiers
substitute:

* **fake-binary tier** — a fake ``ssh``/``scp`` pair on PATH that parse the
  real OpenSSH option syntax and execute locally.  The CLI backend then runs
  its genuine code path end to end: argv construction, exec, exit-status
  classification, scp copies, persistent process pipes — including a full
  electron dispatched over ``hostname="127.0.0.1"``.
* **stub-asyncssh tier** — a fake asyncssh module patched into the
  transport, covering the asyncssh branch of ``_open``/``run``/``put``/
  ``get``/``start_process``/``close`` (connect kwargs, known_hosts policy,
  scp argument shapes, wait_closed discipline).
"""

from __future__ import annotations

import os
import stat
import sys
import types

import pytest

from covalent_tpu_plugin.transport import ssh as ssh_mod
from covalent_tpu_plugin.transport.base import TransportError
from covalent_tpu_plugin.transport.ssh import SSHTransport, connect_with_retries

FAKE_SSH = r"""#!/bin/sh
# Fake OpenSSH client: parse real ssh options, run the command locally.
# FAKE_SSH_FAIL_FILE: while it holds a positive count, decrement and exit 255
# (ssh's own connect-failure code) to script flaky-network retries.
if [ -n "$FAKE_SSH_FAIL_FILE" ] && [ -s "$FAKE_SSH_FAIL_FILE" ]; then
  n=$(cat "$FAKE_SSH_FAIL_FILE")
  if [ "$n" -gt 0 ]; then
    echo $((n - 1)) > "$FAKE_SSH_FAIL_FILE"
    echo "ssh: connect to host refused" >&2
    exit 255
  fi
fi
while [ $# -gt 0 ]; do
  case "$1" in
    -p|-o|-i) shift 2 ;;
    -*) shift ;;
    *) break ;;
  esac
done
host="$1"; shift
[ -n "$FAKE_SSH_LOG" ] && echo "$host" >> "$FAKE_SSH_LOG"
exec sh -c "$*"
"""

FAKE_SCP = r"""#!/bin/sh
while [ $# -gt 0 ]; do
  case "$1" in
    -P|-o|-i) shift 2 ;;
    -*) shift ;;
    *) break ;;
  esac
done
src="$1"; dst="$2"
case "$src" in *:*) src="${src#*:}" ;; esac
case "$dst" in *:*) dst="${dst#*:}" ;; esac
# The transport shell-quotes the remote side (scp passes it through a remote
# shell); strip one level of quoting for the local stand-in.
src=$(eval "printf %s $src"); dst=$(eval "printf %s $dst")
exec cp "$src" "$dst"
"""


@pytest.fixture()
def fake_ssh_bin(tmp_path, monkeypatch):
    """Install fake ssh/scp ahead of PATH; returns the bin directory."""
    bindir = tmp_path / "fakebin"
    bindir.mkdir()
    for name, body in (("ssh", FAKE_SSH), ("scp", FAKE_SCP)):
        path = bindir / name
        path.write_text(body)
        path.chmod(path.stat().st_mode | stat.S_IXUSR)
    monkeypatch.setenv("PATH", f"{bindir}{os.pathsep}{os.environ['PATH']}")
    return bindir


# --------------------------------------------------------------------- #
# OpenSSH-CLI backend over the fake binaries
# --------------------------------------------------------------------- #


def make_cli_transport(**kwargs) -> SSHTransport:
    t = SSHTransport(hostname=kwargs.pop("hostname", "127.0.0.1"), **kwargs)
    assert not t._use_asyncssh  # sandbox has no asyncssh
    return t


def test_cli_open_and_run(fake_ssh_bin, run_async):
    async def flow():
        t = make_cli_transport(username="tester", strict_host_keys=False)
        await t._open()  # probes with `true`; exit 0 means connected
        result = await t.run("echo hello; echo oops >&2; exit 3")
        assert (result.exit_status, result.stdout.strip(), result.stderr.strip()) == (
            3, "hello", "oops"
        )
        await t.close()
        with pytest.raises(TransportError, match="closed"):
            await t.run("true")

    run_async(flow())


def test_cli_open_classifies_connect_failure(fake_ssh_bin, tmp_path,
                                             monkeypatch, run_async):
    fail_file = tmp_path / "failcount"
    fail_file.write_text("1")
    monkeypatch.setenv("FAKE_SSH_FAIL_FILE", str(fail_file))
    t = make_cli_transport()
    with pytest.raises(ConnectionRefusedError, match="refused"):
        run_async(t._open())


def test_cli_connect_with_retries_eventual_success(fake_ssh_bin, tmp_path,
                                                   monkeypatch, run_async):
    """The reference's flaky-network script (ssh_test.py:199-257): fail
    twice, succeed on the third attempt."""
    fail_file = tmp_path / "failcount"
    fail_file.write_text("2")
    monkeypatch.setenv("FAKE_SSH_FAIL_FILE", str(fail_file))
    t = make_cli_transport()
    got = run_async(
        connect_with_retries(t, max_attempts=5, retry_wait_time=0.01)
    )
    assert got is t


def test_cli_connect_with_retries_exhausts(fake_ssh_bin, tmp_path,
                                           monkeypatch, run_async):
    fail_file = tmp_path / "failcount"
    fail_file.write_text("99")
    monkeypatch.setenv("FAKE_SSH_FAIL_FILE", str(fail_file))
    t = make_cli_transport()
    with pytest.raises(TransportError, match="after 3 attempts"):
        run_async(connect_with_retries(t, max_attempts=3, retry_wait_time=0.01))


def test_cli_retry_connect_false_reraises(fake_ssh_bin, tmp_path,
                                          monkeypatch, run_async):
    """retry_connect=False re-raises immediately (reference ssh.py:271-273)."""
    fail_file = tmp_path / "failcount"
    fail_file.write_text("9")
    monkeypatch.setenv("FAKE_SSH_FAIL_FILE", str(fail_file))
    t = make_cli_transport()
    with pytest.raises(ConnectionRefusedError):
        run_async(
            connect_with_retries(
                t, max_attempts=5, retry_wait_time=0.01, retry_connect=False
            )
        )
    assert fail_file.read_text().strip() == "8"  # exactly one attempt


def test_cli_put_get_roundtrip(fake_ssh_bin, tmp_path, run_async):
    src = tmp_path / "src.txt"
    src.write_text("payload")
    up = tmp_path / "up.txt"
    down = tmp_path / "down.txt"

    async def flow():
        t = make_cli_transport()
        await t.put(str(src), str(up))
        await t.get(str(up), str(down))
        await t.close()

    run_async(flow())
    assert down.read_text() == "payload"


def test_cli_put_failure_raises(fake_ssh_bin, tmp_path, run_async):
    t = make_cli_transport()
    with pytest.raises(TransportError, match="scp upload failed"):
        run_async(t.put(str(tmp_path / "missing"), str(tmp_path / "x")))


def test_cli_start_process_line_protocol(fake_ssh_bin, run_async):
    async def flow():
        t = make_cli_transport()
        proc = await t.start_process("while read line; do echo got:$line; done")
        await proc.write_line("ping")
        assert await proc.read_line(timeout=5) == "got:ping"
        await proc.close()

    run_async(flow())


def test_cli_argv_shapes():
    t = SSHTransport(
        hostname="h", username="u", ssh_key_file="/k", port=2222,
        strict_host_keys=False,
    )
    ssh = t._ssh_base()
    assert ssh[:3] == ["ssh", "-p", "2222"]
    assert ssh[-1] == "u@h"
    assert ["-i", "/k"] == ssh[ssh.index("-i"):ssh.index("-i") + 2]
    assert "StrictHostKeyChecking=no" in " ".join(ssh)
    scp = t._scp_base()
    assert scp[:3] == ["scp", "-P", "2222"]
    strict = SSHTransport(hostname="h")._ssh_base()
    assert "StrictHostKeyChecking=no" not in " ".join(strict)


def test_auto_falls_through_to_minissh(monkeypatch):
    """With no asyncssh and no ssh binary on PATH, auto resolves to the
    vendored pure-python stack instead of failing — an image with NO ssh
    stack at all still gets a working control plane (round 5)."""
    monkeypatch.setattr(ssh_mod, "_HAVE_ASYNCSSH", False)  # CI has asyncssh
    monkeypatch.setenv("PATH", "/nonexistent")
    t = SSHTransport(hostname="127.0.0.1")
    assert t.backend == "minissh"
    assert not t._use_asyncssh


def test_pinned_openssh_without_binary_fails(fake_ssh_bin, monkeypatch,
                                             run_async):
    t = make_cli_transport(backend="openssh")
    monkeypatch.setenv("PATH", "/nonexistent")
    with pytest.raises(TransportError, match="no SSH backend"):
        run_async(t._open())


def test_minissh_strict_without_known_key_fails(run_async):
    t = SSHTransport(
        hostname="127.0.0.1", backend="minissh", strict_host_keys=True
    )
    with pytest.raises(TransportError, match="known_host_key"):
        run_async(t._open())


def test_invalid_backend_rejected():
    with pytest.raises(ValueError, match="backend"):
        SSHTransport(hostname="h", backend="telnet")


# --------------------------------------------------------------------- #
# Full executor lifecycle over ssh://127.0.0.1 (fake binaries)
# --------------------------------------------------------------------- #


def test_electron_end_to_end_over_ssh(fake_ssh_bin, tmp_path, run_async):
    """One electron through the REAL ssh transport path: connect (probe),
    preflight, scp staging, nohup launch, poll, scp fetch, cleanup —
    the reference's whole lifecycle (ssh.py:466-591) on the CLI backend."""
    from covalent_tpu_plugin import TPUExecutor

    key = tmp_path / "id_rsa"
    key.write_text("dummy key material")
    remote = tmp_path / "remote-cache"
    ex = TPUExecutor(
        transport="ssh",
        hostname="127.0.0.1",
        username="",
        ssh_key_file=str(key),
        strict_host_keys=False,
        cache_dir=str(tmp_path / "cache"),
        remote_cache=str(remote),
        python_path=sys.executable,
        poll_freq=0.1,
        use_agent=False,
        task_env={"JAX_PLATFORMS": "cpu"},
    )

    def electron(a, b):
        return {"sum": a + b, "host": True}

    async def flow():
        result = await ex.run(
            electron, [2, 40], {}, {"dispatch_id": "ssh-e2e", "node_id": 0}
        )
        timings = dict(ex.last_timings)
        await ex.close()
        return result, timings

    result, timings = run_async(flow())
    assert result == {"sum": 42, "host": True}
    assert timings["overhead"] > 0
    # Staged artifacts were cleaned up on both "sides".
    leftovers = [p for p in remote.glob("*") if "ssh-e2e" in p.name]
    assert leftovers == []


def test_executor_parses_user_host_port_addresses(tmp_path):
    """Worker addresses accept user@host:port; the ssh port never leaks
    into the jax.distributed coordinator address."""
    from covalent_tpu_plugin import TPUExecutor

    key = tmp_path / "id_rsa"
    key.write_text("k")
    ex = TPUExecutor(
        transport="ssh",
        workers=["alice@w0:2222", "w1"],
        ssh_key_file=str(key),
        cache_dir=str(tmp_path / "cache"),
        use_agent=False,
    )
    t0 = ex._make_transport("alice@w0:2222")
    assert (t0.hostname, t0.username, t0.port) == ("w0", "alice", 2222)
    t1 = ex._make_transport("w1")
    assert (t1.hostname, t1.port) == ("w1", 22)
    assert ex._coordinator_address() == f"w0:{ex.coordinator_port}"
    # IPv6-style colon-bearing hosts pass through whole, not as host:port.
    t6 = ex._make_transport("fe80::1")
    assert (t6.hostname, t6.port) == ("fe80::1", 22)


def test_executor_missing_key_raises(fake_ssh_bin, tmp_path, run_async):
    """Reference _validate_credentials (ssh.py:317-335)."""
    from covalent_tpu_plugin import TPUExecutor

    ex = TPUExecutor(
        transport="ssh",
        hostname="127.0.0.1",
        ssh_key_file=str(tmp_path / "nope"),
        cache_dir=str(tmp_path / "cache"),
        remote_cache=str(tmp_path / "remote"),
        use_agent=False,
    )
    with pytest.raises(RuntimeError, match="no SSH key file"):
        run_async(
            ex.run(lambda: 1, [], {}, {"dispatch_id": "d", "node_id": 0})
        )


# --------------------------------------------------------------------- #
# Stub-asyncssh tier
# --------------------------------------------------------------------- #


class FakeSSHCompleted:
    def __init__(self, exit_status=0, stdout="ok\n", stderr=""):
        self.exit_status = exit_status
        self.stdout = stdout
        self.stderr = stderr


class FakeAsyncsshConn:
    def __init__(self):
        self.commands: list[str] = []
        self.closed = False
        self.wait_closed_called = False

    async def run(self, command):
        self.commands.append(command)
        return FakeSSHCompleted(stdout=f"ran:{command}\n")

    async def create_process(self, command, encoding=None):
        self.commands.append(("process", command, encoding))
        return types.SimpleNamespace(stdout="r", stdin="w", exit_status=None)

    def close(self):
        self.closed = True

    async def wait_closed(self):
        self.wait_closed_called = True


@pytest.fixture()
def stub_asyncssh(monkeypatch):
    module = types.SimpleNamespace()
    module.connects: list[tuple] = []
    module.scps: list[tuple] = []
    module.conn = FakeAsyncsshConn()

    async def connect(hostname, **kwargs):
        module.connects.append((hostname, kwargs))
        return module.conn

    async def scp(src, dst):
        module.scps.append((src, dst))

    module.connect = connect
    module.scp = scp
    module.ConnectionLost = type("ConnectionLost", (Exception,), {})
    monkeypatch.setattr(ssh_mod, "asyncssh", module)
    monkeypatch.setattr(ssh_mod, "_HAVE_ASYNCSSH", True)
    return module


def test_asyncssh_open_connect_kwargs(stub_asyncssh, run_async):
    t = SSHTransport(
        hostname="tpu-w0", username="u", ssh_key_file="/k", port=2222,
        strict_host_keys=False, connect_timeout=7.0,
    )
    assert t._use_asyncssh
    run_async(t._open())
    hostname, kwargs = stub_asyncssh.connects[0]
    assert hostname == "tpu-w0"
    assert kwargs["username"] == "u"
    assert kwargs["client_keys"] == ["/k"]
    assert kwargs["port"] == 2222
    assert kwargs["connect_timeout"] == 7.0
    # Lax mode disables host-key checks the way the reference always did
    # (ssh.py:267); strict mode must NOT pass known_hosts at all.
    assert kwargs["known_hosts"] is None
    run_async(SSHTransport(hostname="h2", strict_host_keys=True)._open())
    _, strict_kwargs = stub_asyncssh.connects[1]
    assert "known_hosts" not in strict_kwargs
    assert strict_kwargs["username"] is None  # empty -> user default


def test_asyncssh_run_and_close(stub_asyncssh, run_async):
    async def flow():
        t = SSHTransport(hostname="w0")
        await t._open()
        result = await t.run("hostname")
        assert (result.exit_status, result.stdout) == (0, "ran:hostname\n")
        await t.close()
        await t.close()  # idempotent

    run_async(flow())
    assert stub_asyncssh.conn.closed
    assert stub_asyncssh.conn.wait_closed_called


def test_asyncssh_put_get_shapes(stub_asyncssh, run_async):
    async def flow():
        t = SSHTransport(hostname="w0")
        await t._open()
        await t.put("/local/a", "/remote/a")
        await t.get("/remote/b", "/local/b")

    run_async(flow())
    up, down = stub_asyncssh.scps
    # Upload: (local, (conn, remote)); download: ((conn, remote), local) —
    # the reference's exact call shapes (ssh.py:360-361, 451).
    assert up == ("/local/a", (stub_asyncssh.conn, "/remote/a"))
    assert down == ((stub_asyncssh.conn, "/remote/b"), "/local/b")


def test_asyncssh_start_process_wraps_transport_process(stub_asyncssh, run_async):
    from covalent_tpu_plugin.transport.process import TransportProcess

    async def flow():
        t = SSHTransport(hostname="w0")
        await t._open()
        return await t.start_process("agent --serve", describe="agent")

    proc = run_async(flow())
    assert isinstance(proc, TransportProcess)
    assert ("process", "agent --serve", None) in stub_asyncssh.conn.commands


def test_asyncssh_connection_lost_is_retryable(stub_asyncssh, monkeypatch,
                                               run_async):
    """A mid-handshake ConnectionLost must be retried like the reference's
    asyncssh.ConnectionLost branch (ssh.py:249-253)."""
    attempts = {"n": 0}

    async def flaky_connect(hostname, **kwargs):
        attempts["n"] += 1
        if attempts["n"] < 3:
            raise ConnectionResetError("lost")
        return stub_asyncssh.conn

    stub_asyncssh.connect = flaky_connect
    t = SSHTransport(hostname="w0")
    run_async(connect_with_retries(t, max_attempts=5, retry_wait_time=0.01))
    assert attempts["n"] == 3
