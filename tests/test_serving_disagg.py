"""Disaggregated prefill/decode serving: KV transfer plane + router.

Three tiers:

* **Protocol** — the ``serve_prefill`` verb and KV-attached
  ``serve_request`` against the real pool server: bundle round trip with
  worker-announced digest, unknown-session fast failure, engines without
  the surface, and the digest-mismatch degrade-to-full-prefill path
  (``kv_fallbacks`` counted, stream byte-equal).
* **Router units** — prefix-affinity ranked below sticky and above
  least-loaded with DRR fairness untouched, affinity sites forgotten
  with their replica.
* **Set integration** — a real :class:`DisaggregatedSet` (prefill tier +
  decode tier over pool-server processes): long prompts ride the KV
  road (transfer bytes/latency accounted), short prompts go direct,
  streams are byte-identical either way, and a SIGKILLed prefill
  replica mid-traffic degrades every request to a full prefill on the
  decode tier with byte-equal streams and zero user-visible errors.

The real-LM half of the contract (bit-equal greedy streams through
``prefill_only``/``admit_from_kv`` against the decode oracle) lives in
``tests/test_continuous.py``.
"""

import asyncio
import hashlib
import pickle
import sys
import time

import pytest

from covalent_tpu_plugin.agent import AgentError, start_pool_server
from covalent_tpu_plugin.fleet.pools import Pool, PoolSpec, parse_pool_specs
from covalent_tpu_plugin.fleet.queue import WorkItem
from covalent_tpu_plugin.resilience import FaultClass, classify_error
from covalent_tpu_plugin.serving import (
    ReplicaRouter,
    ReplicaView,
    open_disaggregated_set,
    open_session,
)
from covalent_tpu_plugin.transport import LocalTransport

from .test_serving import (
    drain_until,
    make_serve_executor,
    stage_factory,
)
from .test_serving_replicas import FakeClock, make_replica_executor


def make_kv_factory(
    slots=2, chunk=2, default_cap=6, step_delay=0.0, prefill_s_per_tok=0.0
):
    """A stub engine speaking the FULL disaggregated surface
    (``prefill_only``/``admit_from_kv`` on top of admit/step/cancel),
    cloudpickled by value.  Streams are deterministic per prompt —
    ``base+1, base+2, ...`` off the last prompt token — and IDENTICAL
    whichever admission road is taken, so byte-equality across the
    disagg/fallback/direct paths is checkable.  ``prefill_s_per_tok``
    models prefill compute occupying the engine loop (the cost
    disaggregation moves off the decode tier)."""

    def factory():
        import pickle as pickle_mod
        import time as time_mod

        class Engine:
            def __init__(self):
                self.slots = slots
                self.lanes = {}
                self.stats = {
                    "prefix_hits": 0, "prefix_misses": 0,
                    "prefill_positions": 0, "kv_exports": 0,
                }

            def _tokens(self, prompt, cap):
                base = int(prompt[-1])
                return [base + i + 1 for i in range(cap)]

            def admit(self, rid, prompt, params):
                cap = int((params or {}).get("max_new_tokens", default_cap))
                if prefill_s_per_tok:
                    time_mod.sleep(prefill_s_per_tok * len(prompt))
                self.stats["prefill_positions"] += len(prompt)
                self.lanes[rid] = self._tokens(prompt, cap)

            def prefill_only(self, prompt, params):
                if prefill_s_per_tok:
                    time_mod.sleep(prefill_s_per_tok * len(prompt))
                self.stats["prefill_positions"] += len(prompt)
                self.stats["kv_exports"] += 1
                return pickle_mod.dumps({
                    "prompt": [int(t) for t in prompt],
                    "first": int(prompt[-1]) + 1,
                })

            def admit_from_kv(self, rid, data, params):
                bundle = pickle_mod.loads(bytes(data))
                cap = int((params or {}).get("max_new_tokens", default_cap))
                # Zero prefill positions: the bundle carries the work.
                self.lanes[rid] = self._tokens(bundle["prompt"], cap)

            def step(self):
                if step_delay:
                    time_mod.sleep(step_delay)
                events = []
                for rid in list(self.lanes):
                    taken = self.lanes[rid][:chunk]
                    self.lanes[rid] = self.lanes[rid][chunk:]
                    done = not self.lanes[rid]
                    if done:
                        del self.lanes[rid]
                    events.append(
                        {"rid": rid, "tokens": taken, "done": done}
                    )
                return events

            def cancel(self, rid):
                self.lanes.pop(rid, None)

        return Engine()

    return factory


def view(rid, load=0, capacity=4, open=True):
    return ReplicaView(rid, open=open, load=load, capacity=capacity)


def item(tenant="default", sticky="", prefix_key=""):
    return WorkItem(
        fn=None, args=(), kwargs={},
        task_metadata={
            "request": None, "sticky": sticky, "prefix_key": prefix_key,
        },
        tenant=tenant,
    )


# ---------------------------------------------------------------------------
# Protocol: serve_prefill + KV-attached serve_request on the pool server
# ---------------------------------------------------------------------------


def test_pool_serve_prefill_roundtrip_and_kv_admit(tmp_path, run_async):
    """serve_prefill streams a digest-announced bundle back; re-shipping
    it on a serve_request admits through admit_from_kv (kv_admits moves,
    the request never pays prefill) with a byte-equal stream."""

    async def flow():
        client = await start_pool_server(
            LocalTransport(), str(tmp_path / "remote"), sys.executable
        )
        records: list = []
        try:
            digest, path = stage_factory(tmp_path, make_kv_factory())
            client.watch_serve("s1", lambda sid, data: records.append(data))
            await client.serve_open(
                "s1", digest, path,
                options={"stats_interval_s": 0.1}, timeout=30.0,
            )
            event = await client.serve_prefill(
                "s1", "kv1", [3, 1, 7], params={"max_new_tokens": 4},
                timeout=20.0,
            )
            data = event["data_bytes"]
            assert hashlib.sha256(data).hexdigest() == event["digest"]
            await client.serve_request(
                "s1", "r1", [3, 1, 7], params={"max_new_tokens": 4},
                kv_bytes=data, kv_digest=event["digest"],
            )
            await drain_until(
                records,
                lambda r: r.get("type") == "serve.token" and r.get("done"),
            )
            stats = await drain_until(
                records,
                lambda r: r.get("type") == "serve.stats"
                and r.get("kv_admits"),
            )
            closed = await client.serve_close("s1", timeout=15.0)
        finally:
            await client.close()
        return event, records, stats, closed

    event, records, stats, closed = run_async(flow())
    bundle = pickle.loads(event["data_bytes"])
    assert bundle == {"prompt": [3, 1, 7], "first": 8}
    streamed: list = []
    for chunk in records:
        if chunk.get("type") == "serve.token":
            streamed.extend(chunk["tokens"])
    assert streamed == [8, 9, 10, 11]
    assert stats["kv_admits"] == 1
    assert stats.get("kv_fallbacks", 0) == 0
    # Engine-local counters surfaced in the stats record (satellite):
    assert stats["kv_exports"] == 1
    assert stats["prefill_positions"] == 3  # the prefill-only pass
    assert closed["served"] == 1


def test_pool_serve_prefill_unknown_session_and_unsupported(
    tmp_path, run_async
):
    """A prefill against a sid that was never opened fails fast with a
    serve_kv error; an engine without prefill_only answers
    ``unsupported`` — both raise AgentError for the caller to degrade."""
    from .test_serving import make_factory

    async def flow():
        client = await start_pool_server(
            LocalTransport(), str(tmp_path / "remote"), sys.executable
        )
        try:
            with pytest.raises(AgentError, match="unknown_session"):
                await client.serve_prefill("ghost", "k0", [1], timeout=15.0)
            digest, path = stage_factory(tmp_path, make_factory())
            await client.serve_open("plain", digest, path, timeout=30.0)
            with pytest.raises(AgentError, match="unsupported"):
                await client.serve_prefill(
                    "plain", "k1", [1, 2], timeout=15.0
                )
            await client.serve_close("plain", timeout=15.0)
        finally:
            await client.close()

    run_async(flow())


def test_pool_kv_digest_mismatch_degrades_to_full_prefill(
    tmp_path, run_async
):
    """A KV bundle whose bytes do not match the announced digest is
    NEVER unpickled: the worker counts a kv_fallback, runs the full
    prefill, and the stream is byte-identical to the clean road."""

    async def flow():
        client = await start_pool_server(
            LocalTransport(), str(tmp_path / "remote"), sys.executable
        )
        records: list = []
        try:
            digest, path = stage_factory(tmp_path, make_kv_factory())
            client.watch_serve("s1", lambda sid, data: records.append(data))
            await client.serve_open(
                "s1", digest, path,
                options={"stats_interval_s": 0.1}, timeout=30.0,
            )
            poison = pickle.dumps({"prompt": [99], "first": 1})
            await client.serve_request(
                "s1", "r1", [5], params={"max_new_tokens": 4},
                kv_bytes=poison,
                kv_digest="0" * 64,  # does not match the bytes
            )
            await drain_until(
                records,
                lambda r: r.get("type") == "serve.token" and r.get("done"),
            )
            stats = await drain_until(
                records,
                lambda r: r.get("type") == "serve.stats"
                and r.get("kv_fallbacks"),
            )
            await client.serve_close("s1", timeout=15.0)
        finally:
            await client.close()
        return records, stats

    records, stats = run_async(flow())
    streamed: list = []
    for chunk in records:
        if chunk.get("type") == "serve.token":
            streamed.extend(chunk["tokens"])
    # The FULL prefill road's stream (base 5), not the poison bundle's.
    assert streamed == [6, 7, 8, 9]
    assert stats["kv_fallbacks"] == 1
    assert stats.get("kv_admits", 0) == 0


# ---------------------------------------------------------------------------
# Router units: prefix affinity vs sticky vs DRR (no I/O)
# ---------------------------------------------------------------------------


def test_router_prefix_affinity_steers_and_sticky_wins():
    """A remembered prefix site attracts same-key requests
    (outcome=prefix_affinity); a sticky pin outranks it; and the site
    moves with the traffic (last placement wins)."""
    router = ReplicaRouter(clock=FakeClock())
    views = {
        "r0": view("r0", load=0), "r1": view("r1", load=0),
    }
    router.record_prefix_site("pfx", "r1")
    router.submit(item(prefix_key="pfx"))
    [(_, replica, outcome)] = router.pump(views)
    assert (replica, outcome) == ("r1", "prefix_affinity")
    # Sticky beats prefix affinity.
    router.pin("caller", "r0")
    router.submit(item(sticky="caller", prefix_key="pfx"))
    [(_, replica, outcome)] = router.pump(views)
    assert (replica, outcome) == ("r0", "sticky")
    # ... and that sticky placement re-recorded the site onto r0.
    assert router.prefix_site("pfx") == "r0"


def test_router_prefix_affinity_never_defers_and_respects_headroom():
    """A full (or dead) prefix site does NOT defer the request (unlike a
    sticky pin): placement falls through to least-loaded, and the site
    is forgotten with its replica."""
    router = ReplicaRouter(clock=FakeClock())
    router.record_prefix_site("pfx", "r1")
    views = {
        "r0": view("r0", load=0, capacity=4),
        "r1": view("r1", load=4, capacity=4),  # no headroom
    }
    router.submit(item(prefix_key="pfx"))
    [(_, replica, outcome)] = router.pump(views)
    assert (replica, outcome) == ("r0", "least_loaded")
    router.record_prefix_site("pfx2", "r1")
    router.forget_replica("r1")
    assert router.prefix_site("pfx2") is None


def test_router_drr_fairness_preserved_under_prefix_affinity():
    """With prefix-affinity ranking in play, per-tenant DRR still
    decides WHOSE request dispatches next: a 3:1 weighted tenant drains
    3x the other under a one-slot trickle, prefix keys or not."""
    clock = FakeClock()
    router = ReplicaRouter(weights={"gold": 3.0, "econ": 1.0}, clock=clock)
    for i in range(12):
        router.submit(item(tenant="gold", prefix_key="g"))
        router.submit(item(tenant="econ", prefix_key="e"))
    router.record_prefix_site("g", "r0")
    router.record_prefix_site("e", "r0")
    drained = {"gold": 0, "econ": 0}
    views = {"r0": view("r0", load=3, capacity=4)}
    for _ in range(8):  # 8 single-slot pumps
        assigned = router.pump(views)
        assert len(assigned) == 1
        drained[assigned[0][0].tenant] += 1
    assert drained["gold"] == 6 and drained["econ"] == 2, drained


# ---------------------------------------------------------------------------
# Set integration: real prefill/decode tiers over pool servers
# ---------------------------------------------------------------------------


def test_disaggregated_set_routes_long_prompts_through_kv(
    tmp_path, run_async
):
    """1 prefill + 2 decode replicas: long prompts ride the KV road
    (transfer bytes + latency accounted, decode tier pays zero prefill
    positions for them), short prompts go direct, and every stream is
    byte-exact.  Roles land on the role-declared pools."""

    async def flow():
        pre = make_replica_executor(tmp_path, "pre")
        dec1 = make_replica_executor(tmp_path, "dec1")
        dec2 = make_replica_executor(tmp_path, "dec2")
        [pre_spec] = parse_pool_specs("prefill-pool=local@2!prefill")
        pre_spec.fallback = False
        pools = [
            Pool(pre_spec, executor=pre),
            Pool(PoolSpec(name="dec1", role="decode", capacity=2),
                 executor=dec1),
            Pool(PoolSpec(name="dec2", role="decode", capacity=2),
                 executor=dec2),
        ]
        try:
            dset = await open_disaggregated_set(
                pools,
                make_kv_factory(),
                decode_replicas=2,
                prefill_replicas=1,
                min_prompt_tokens=8,
                name="disagg",
                stats_interval_s=0.1,
            )
            long_prompts = [
                list(range(i, i + 11)) + [100 * (i + 1)] for i in range(4)
            ]
            short_prompts = [[7 * (i + 1)] for i in range(3)]
            requests = []
            for prompt in long_prompts + short_prompts:
                requests.append(await dset.request(
                    prompt, params={"max_new_tokens": 4}
                ))
            results = [await r.result(timeout=30) for r in requests]
            status = dset.status()
            roles = dict(dset._role_of)
            placements = {
                rid: dset._placements[rid][1].name
                for rid in dset._placements
            }
            await dset.close()
        finally:
            await pre.close()
            await dec1.close()
            await dec2.close()
        return results, status, roles, placements, long_prompts, \
            short_prompts

    (results, status, roles, placements, long_prompts,
     short_prompts) = run_async(flow())
    for prompt, tokens in zip(long_prompts + short_prompts, results):
        base = prompt[-1]
        assert tokens == [base + j + 1 for j in range(4)], (prompt, tokens)
    assert status["requests_by_path"].get("disagg") == len(long_prompts)
    assert status["requests_by_path"].get("direct") == len(short_prompts)
    assert status["kv_bytes_total"] > 0
    assert status["kv_transfer_p50_ms"] > 0
    assert roles == {"r0": "prefill", "r1": "decode", "r2": "decode"}
    # Role-aware placement: the prefill replica landed on the pool that
    # declared role=prefill.
    assert placements["r0"] == "prefill-pool"


def test_disaggregated_prefill_kill_mid_traffic_degrades_byte_equal(
    tmp_path, run_async
):
    """SIGKILL the prefill replica's resident server mid-traffic: every
    in-flight and subsequent long-prompt request completes via the
    decode tier's full prefill — byte-equal streams, exactly-once, zero
    user-visible errors — and the fallback is visible in the path
    accounting."""

    async def flow():
        pre = make_replica_executor(
            tmp_path, "pre", retry_base_delay=0.05, retry_max_delay=0.2
        )
        dec = make_replica_executor(
            tmp_path, "dec", retry_base_delay=0.05, retry_max_delay=0.2
        )
        try:
            dset = await open_disaggregated_set(
                [pre, dec],
                make_kv_factory(step_delay=0.05),
                decode_replicas=1,
                prefill_replicas=1,
                min_prompt_tokens=4,
                kv_timeout_s=10.0,
                name="killpre",
                retries=1,
            )
            warm = await dset.request(
                list(range(6)) + [500], params={"max_new_tokens": 4}
            )
            warm_result = await warm.result(timeout=30)
            # Kill the prefill replica's resident server, then keep the
            # long-prompt traffic coming while it is down.
            pre._agents["localhost"]._process._proc.kill()
            requests = [
                await dset.request(
                    list(range(6)) + [1000 * (i + 1)],
                    params={"max_new_tokens": 4},
                )
                for i in range(3)
            ]
            results = [await r.result(timeout=30) for r in requests]
            status = dset.status()
            await dset.close()
        finally:
            await pre.close()
            await dec.close()
        return warm_result, results, status

    warm_result, results, status = run_async(flow())
    assert warm_result == [501, 502, 503, 504]
    for i, tokens in enumerate(results):
        base = 1000 * (i + 1)
        assert tokens == [base + j + 1 for j in range(4)], (i, tokens)
    paths = status["requests_by_path"]
    assert paths.get("disagg", 0) >= 1        # the pre-kill request
    assert paths.get("fallback", 0) >= 1      # the post-kill requests
    assert status["state"] in ("open", "reconnecting")


def test_disaggregated_sticky_rides_decode_tier(tmp_path, run_async):
    """Sticky sids pin to DECODE replicas only (the prefill tier is
    invisible to the router), and multi-turn callers stay put across
    short and long prompts alike."""

    async def flow():
        pre = make_replica_executor(tmp_path, "spre")
        dec1 = make_replica_executor(tmp_path, "sdec1")
        dec2 = make_replica_executor(tmp_path, "sdec2")
        try:
            dset = await open_disaggregated_set(
                [pre, dec1, dec2],
                make_kv_factory(slots=4),
                decode_replicas=2,
                prefill_replicas=1,
                min_prompt_tokens=6,
                name="sticky",
            )
            requests = []
            for i in range(6):
                prompt = (
                    list(range(8)) + [50 * (i + 1)]
                    if i % 2 else [50 * (i + 1)]
                )
                requests.append(await dset.request(
                    prompt, params={"max_new_tokens": 3},
                    sticky="caller-1",
                ))
            results = [await r.result(timeout=30) for r in requests]
            status = dset.status()
            served_by = {
                rid: v["served"] for rid, v in status["replicas"].items()
            }
            roles = dict(dset._role_of)
            await dset.close()
        finally:
            await pre.close()
            await dec1.close()
            await dec2.close()
        return results, served_by, roles

    results, served_by, roles = run_async(flow())
    for i, tokens in enumerate(results):
        base = 50 * (i + 1)
        assert tokens == [base + 1, base + 2, base + 3]
    decode_served = {
        rid: n for rid, n in served_by.items() if roles[rid] == "decode"
    }
    # One sticky caller -> exactly one decode replica took every stream.
    assert sorted(decode_served.values()) == [0, 6], decode_served
    assert served_by[next(
        rid for rid, role in roles.items() if role == "prefill"
    )] == 0


# ---------------------------------------------------------------------------
# Satellite: typed rolling_cache refusal through a REAL open_session
# ---------------------------------------------------------------------------


def test_rolling_cache_refusal_permanent_through_open_session(
    tmp_path, run_async
):
    """lm_engine_factory with a rolling_cache model surfaces
    RollingCacheUnsupported as serve_model_unsupported PERMANENT through
    a real open_session — one refusal, no gang-retry burn."""
    import dataclasses

    import jax.numpy as jnp

    from covalent_tpu_plugin.models import TransformerConfig, TransformerLM
    from covalent_tpu_plugin.models.serve import lm_engine_factory

    cfg = TransformerConfig(
        vocab_size=32, d_model=16, n_layers=1, n_heads=2, d_ff=32,
        max_seq=32, dtype=jnp.float32, attention="reference",
        sliding_window=8, rolling_cache=True,
    )
    model = TransformerLM(cfg)
    # Construction refuses before params are ever touched, so none are
    # needed — the worker only pays the jax import.
    factory = lm_engine_factory(model, None)

    async def flow():
        import os

        import cloudpickle

        cloudpickle.register_pickle_by_value(
            sys.modules["covalent_tpu_plugin.models.serve"]
        )
        repo_root = os.path.dirname(os.path.dirname(__file__))
        ex = make_serve_executor(
            tmp_path,
            task_env={
                "PYTHONPATH": repo_root + os.pathsep
                + os.environ.get("PYTHONPATH", ""),
            },
        )
        try:
            t0 = time.monotonic()
            with pytest.raises(Exception) as err:
                await open_session(
                    ex, factory, name="rolling", open_timeout_s=120.0,
                )
            elapsed = time.monotonic() - t0
        finally:
            await ex.close()
        return err.value, elapsed

    failure, _elapsed = run_async(flow())
    fault, label = classify_error(failure)
    assert fault is FaultClass.PERMANENT
    assert label == "serve_model_unsupported"


def test_disaggregated_kv_rides_cas_road_without_frames(
    tmp_path, run_async
):
    """With binary frames off (JSONL channel), the KV bundle ships ONCE
    into the decode worker's remote CAS and the request references it
    by path — kv_admits still moves, streams stay byte-exact, and the
    digest-named artifact lands in the worker's CAS dir."""
    import os

    async def flow():
        pre = make_replica_executor(tmp_path, "cpre", agent_frames=False)
        dec = make_replica_executor(tmp_path, "cdec", agent_frames=False)
        try:
            dset = await open_disaggregated_set(
                [pre, dec],
                make_kv_factory(),
                decode_replicas=1,
                prefill_replicas=1,
                min_prompt_tokens=4,
                name="casroad",
                stats_interval_s=0.1,
            )
            requests = [
                await dset.request(
                    [1, 2, 3, 4, 5, 40 * (i + 1)],
                    params={"max_new_tokens": 3},
                )
                for i in range(2)
            ]
            results = [await r.result(timeout=30) for r in requests]
            # Wait for a stats record carrying the worker's kv counters.
            decode_sup = next(
                sup for rid, sup in dset._replicas.items()
                if dset._role_of[rid] == "decode"
            )
            for _ in range(100):
                if decode_sup.stats.get("kv_admits"):
                    break
                await asyncio.sleep(0.05)
            kv_admits = decode_sup.stats.get("kv_admits")
            status = dset.status()
            cas_dir = os.path.join(str(tmp_path / "remote-cdec"), "cas")
            staged = [
                name for name in os.listdir(cas_dir)
                if name.endswith(".kv")
            ]
            await dset.close()
        finally:
            await pre.close()
            await dec.close()
        return results, kv_admits, status, staged

    results, kv_admits, status, staged = run_async(flow())
    for i, tokens in enumerate(results):
        base = 40 * (i + 1)
        assert tokens == [base + 1, base + 2, base + 3]
    assert kv_admits == 2
    assert status["requests_by_path"].get("disagg") == 2
    assert len(staged) == 2  # one digest-named artifact per bundle
