"""LoRA / QLoRA: exact-at-init, masked training, merge equivalence.

The decisive properties: adapters with B=0 leave the model bit-identical
to the base; optax.masked training moves ONLY the adapters; folding the
adapters back in reproduces the adapted model with plain dense kernels.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from covalent_tpu_plugin.models import (
    TransformerConfig,
    TransformerLM,
    add_lora,
    lora_mask,
    merge_lora,
    quantize_then_lora,
)
from covalent_tpu_plugin.models.train import lm_loss

BASE = TransformerConfig(
    vocab_size=64,
    d_model=32,
    n_layers=2,
    n_heads=4,
    d_ff=64,
    max_seq=32,
    dtype=jnp.float32,
    attention="reference",
    scan_layers=False,
)


def setup(rank=4, cfg=BASE):
    model = TransformerLM(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 9), 0, cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    lmodel, lparams = add_lora(model, params, rank=rank)
    return model, params, lmodel, lparams, tokens


def test_lora_is_identity_at_init():
    model, params, lmodel, lparams, tokens = setup()
    base = model.apply({"params": params}, tokens)
    adapted = lmodel.apply({"params": lparams}, tokens)
    np.testing.assert_array_equal(np.asarray(base), np.asarray(adapted))


def test_lora_mask_marks_only_adapters():
    _, _, _, lparams, _ = setup()
    mask = lora_mask(lparams)
    flat = jax.tree_util.tree_flatten_with_path(mask)[0]
    adapters = [m for path, m in flat if any(
        getattr(e, "key", None) in ("lora_a", "lora_b") for e in path)]
    others = [m for path, m in flat if not any(
        getattr(e, "key", None) in ("lora_a", "lora_b") for e in path)]
    assert adapters and all(adapters)
    assert others and not any(others)


def test_masked_training_moves_only_adapters_and_learns():
    from covalent_tpu_plugin.models.lora import lora_optimizer

    _, _, lmodel, lparams, tokens = setup(rank=8)
    tx = lora_optimizer(optax.adam(3e-2), lparams)
    opt_state = tx.init(lparams)

    @jax.jit
    def step(params, opt_state):
        loss, grads = jax.value_and_grad(
            lambda p: lm_loss(p, lmodel.apply, {"tokens": tokens})
        )(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    params = lparams
    losses = []
    for _ in range(12):
        params, opt_state, loss = step(params, opt_state)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses

    # Base leaves are untouched; adapter leaves moved.
    flat_before = jax.tree_util.tree_flatten_with_path(lparams)[0]
    flat_after = dict(jax.tree_util.tree_flatten_with_path(params)[0])
    for path, before in flat_before:
        after = flat_after[path]
        is_adapter = any(
            getattr(e, "key", None) in ("lora_a", "lora_b") for e in path
        )
        same = np.array_equal(np.asarray(before), np.asarray(after))
        if is_adapter and "lora_b" in str(path):
            assert not same, f"adapter {path} never trained"
        if not is_adapter:
            assert same, f"frozen leaf {path} moved"


def test_merge_lora_reproduces_adapted_model():
    _, _, lmodel, lparams, tokens = setup(rank=8)
    # Nudge the adapters off zero so the merge is non-trivial.
    lparams = jax.tree_util.tree_map_with_path(
        lambda path, leaf: (
            leaf + 0.01
            if any(getattr(e, "key", None) == "lora_b" for e in path)
            else leaf
        ),
        lparams,
    )
    adapted = lmodel.apply({"params": lparams}, tokens)
    plain_model, plain_params = merge_lora(lmodel, lparams)
    merged = plain_model.apply({"params": plain_params}, tokens)
    np.testing.assert_allclose(
        np.asarray(merged), np.asarray(adapted), atol=2e-5, rtol=2e-5
    )
    # The merged tree is a plain checkpoint: no adapter leaves anywhere.
    assert not any(
        getattr(e, "key", None) in ("lora_a", "lora_b")
        for path, _ in jax.tree_util.tree_flatten_with_path(plain_params)[0]
        for e in path
    )


def test_qlora_runs_and_starts_at_quant_baseline():
    from covalent_tpu_plugin.models import quantize_lm

    model = TransformerLM(BASE)
    tokens = jax.random.randint(jax.random.PRNGKey(3), (1, 7), 0, BASE.vocab_size)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    qmodel, qparams = quantize_lm(model, params)
    qlmodel, qlparams = quantize_then_lora(model, params, rank=4)
    np.testing.assert_array_equal(
        np.asarray(qmodel.apply({"params": qparams}, tokens)),
        np.asarray(qlmodel.apply({"params": qlparams}, tokens)),
    )
    # int8 base survived the adapter attach.
    kernels = [
        leaf
        for path, leaf in jax.tree_util.tree_flatten_with_path(qlparams)[0]
        if any(getattr(e, "key", None) == "kernel" for e in path)
    ]
    assert kernels and all(k.dtype == jnp.int8 for k in kernels)


def test_add_lora_validation():
    model = TransformerLM(dataclasses.replace(BASE, scan_layers=True))
    tokens = jnp.zeros((1, 4), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    with pytest.raises(ValueError, match="scan_layers"):
        add_lora(model, params, rank=4)
    with pytest.raises(ValueError, match="rank"):
        add_lora(TransformerLM(BASE), params, rank=0)


def test_qlora_training_updates_only_adapters():
    """The split train step differentiates only adapter leaves, so a
    frozen int8 base trains without jax.grad's inexact-dtype error."""
    import optax as _optax

    from covalent_tpu_plugin.models import (
        lora_train_params,
        make_lora_train_state,
        make_lora_train_step,
    )

    model = TransformerLM(BASE)
    tokens = jax.random.randint(jax.random.PRNGKey(5), (2, 9), 0, BASE.vocab_size)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    qlmodel, qlparams = quantize_then_lora(model, params, rank=8)

    tx = _optax.adam(3e-2)
    state = make_lora_train_state(qlparams, tx)
    step = make_lora_train_step(lm_loss, qlmodel.apply)
    losses = []
    for _ in range(12):
        state, loss = step(state, {"tokens": tokens})
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses

    # Frozen int8 base untouched; the reassembled tree still applies.
    out_params = lora_train_params(state)
    kernels = [
        leaf
        for path, leaf in jax.tree_util.tree_flatten_with_path(out_params)[0]
        if any(getattr(e, "key", None) == "kernel" for e in path)
    ]
    assert kernels and all(k.dtype == jnp.int8 for k in kernels)
    out = qlmodel.apply({"params": out_params}, tokens)
    assert np.isfinite(np.asarray(out, np.float32)).all()


def test_make_lora_train_state_rejects_plain_params():
    from covalent_tpu_plugin.models import make_lora_train_state
    import optax as _optax

    model = TransformerLM(BASE)
    tokens = jnp.zeros((1, 4), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    with pytest.raises(ValueError, match="add_lora"):
        make_lora_train_state(params, _optax.adam(1e-3))
