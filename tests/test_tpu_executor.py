"""TPUExecutor unit tests — the reference's ``tests/ssh_test.py`` inventory
(SURVEY §4.1) rebuilt for the TPU lifecycle: constructor/config resolution,
fallback policy both ways, staged file layout, unique workdirs, orchestration
against scripted fake transports, failure routing, cancel, and timings.
No network, no TPU.
"""

import asyncio

import pytest

from covalent_tpu_plugin.tpu import (
    _EXECUTOR_PLUGIN_DEFAULTS,
    EXECUTOR_PLUGIN_NAME,
    TaskStatus,
    TPUExecutor,
)
from covalent_tpu_plugin.transport import TransportError
from covalent_tpu_plugin.transport.base import CommandResult

from .helpers import FakeTransport, pin_cpu_task_env, scripted_ok_responses


def make_executor(tmp_path, fake: FakeTransport | None = None, **kwargs):
    """Executor wired to a FakeTransport (method-level patch pattern,
    ssh_test.py:139-146)."""
    kwargs.setdefault("transport", "local")
    kwargs.setdefault("cache_dir", str(tmp_path / "cache"))
    kwargs.setdefault("remote_cache", str(tmp_path / "remote"))
    kwargs.setdefault("poll_freq", 0.05)
    kwargs.setdefault("use_agent", False)  # dedicated agent tests opt in
    ex = TPUExecutor(**pin_cpu_task_env(kwargs))
    if fake is not None:

        async def fake_connect(address):
            return fake

        ex._client_connect = fake_connect
    return ex


METADATA = {"dispatch_id": "d123", "node_id": 1}


# --------------------------------------------------------------------- #
# Constructor / config resolution (reference: test_init, ssh_test.py:46-69)
# --------------------------------------------------------------------- #


def test_plugin_identity():
    assert EXECUTOR_PLUGIN_NAME == "TPUExecutor"
    assert set(_EXECUTOR_PLUGIN_DEFAULTS) >= {
        "username",
        "hostname",
        "ssh_key_file",
        "python_path",
        "conda_env",
        "remote_cache",
        "remote_workdir",
        "create_unique_workdir",
        "run_local_on_dispatch_fail",
    }


def test_init_explicit_args_win(tmp_path, tmp_config):
    from covalent_tpu_plugin.utils.config import set_config

    set_config("executors.tpu.python_path", "/from/config")
    ex = make_executor(tmp_path, python_path="/explicit")
    assert ex.python_path == "/explicit"


def test_init_falls_back_to_config(tmp_path, tmp_config):
    from covalent_tpu_plugin.utils.config import set_config

    set_config("executors.tpu.python_path", "/from/config")
    ex = make_executor(tmp_path)
    assert ex.python_path == "/from/config"


def test_init_falls_back_to_default(tmp_path, tmp_config):
    ex = make_executor(tmp_path)
    assert ex.python_path == "python3"
    assert ex.poll_freq == 0.05  # explicit in make_executor
    assert ex.create_unique_workdir is False


def test_reference_compat_alias_run_local_on_ssh_fail(tmp_path):
    ex = make_executor(tmp_path, run_local_on_ssh_fail=True)
    assert ex.run_local_on_dispatch_fail is True


def test_ssh_key_file_expanded(tmp_path):
    ex = make_executor(tmp_path, ssh_key_file="~/somekey")
    assert "~" not in ex.ssh_key_file


# --------------------------------------------------------------------- #
# Credentials (reference: test_client_connect, ssh_test.py:170-190)
# --------------------------------------------------------------------- #


def test_validate_credentials_missing_key_raises(tmp_path, run_async):
    ex = make_executor(
        tmp_path, transport="ssh", hostname="tpu-vm", ssh_key_file=str(tmp_path / "nope")
    )
    with pytest.raises(RuntimeError, match="no SSH key"):
        run_async(ex._validate_credentials())


def test_validate_credentials_local_transport_skips_key(tmp_path, run_async):
    ex = make_executor(tmp_path, ssh_key_file=str(tmp_path / "nope"))
    assert run_async(ex._validate_credentials()) is True


def test_worker_addresses_require_topology(tmp_path):
    ex = make_executor(tmp_path, transport="ssh")
    with pytest.raises(ValueError, match="hostname"):
        ex._worker_addresses()


def test_worker_addresses_explicit_workers_win(tmp_path):
    ex = make_executor(tmp_path, transport="ssh", hostname="solo", workers=["w0", "w1"])
    assert ex._worker_addresses() == ["w0", "w1"]
    assert ex._num_processes() == 2
    assert ex._coordinator_address() == f"w0:{ex.coordinator_port}"


def test_coordinator_address_strips_username(tmp_path):
    ex = make_executor(
        tmp_path, transport="ssh", workers=["alice@w0", "alice@w1"], coordinator_port=9000
    )
    assert ex._coordinator_address() == "w0:9000"


def test_coordinator_address_local_transport_is_loopback(tmp_path):
    ex = make_executor(tmp_path, workers=["w0", "w1"], coordinator_port=9000)
    assert ex._coordinator_address() == "127.0.0.1:9000"


def test_duplicate_worker_addresses_rejected(tmp_path):
    ex = make_executor(tmp_path, workers=["w0", "w0"])
    with pytest.raises(ValueError, match="duplicate"):
        ex._worker_addresses()


# --------------------------------------------------------------------- #
# Fallback policy (reference: test_on_ssh_fail, ssh_test.py:72-110)
# --------------------------------------------------------------------- #


def test_on_dispatch_fail_runs_locally_when_enabled(tmp_path):
    ex = make_executor(tmp_path, run_local_on_dispatch_fail=True)
    assert ex._on_dispatch_fail(lambda x: x + 1, (41,), {}, "oops") == 42


def test_on_dispatch_fail_raises_when_disabled(tmp_path):
    ex = make_executor(tmp_path, run_local_on_dispatch_fail=False)
    with pytest.raises(RuntimeError, match="oops"):
        ex._on_dispatch_fail(lambda: None, (), {}, "oops")


# --------------------------------------------------------------------- #
# Staging (reference: test_file_writes ssh_test.py:319-360,
#          test_current_remote_workdir ssh_test.py:260-316)
# --------------------------------------------------------------------- #


def test_file_writes_single_worker(tmp_path):
    ex = make_executor(tmp_path)
    staged = ex._write_function_files("d123_1", lambda: 1, (), {}, "/wd")
    assert staged.function_file.endswith("function_d123_1.pkl")
    # Immutable artifacts are content-addressed under remote_cache/cas/;
    # mutable per-operation files keep their operation-scoped names.
    assert f"/cas/{staged.function_digest}.pkl" in staged.remote_function_file
    assert f"/cas/{staged.harness_digest}.py" in staged.remote_harness_file
    assert staged.remote_result_file.endswith("/result_d123_1.pkl")
    assert len(staged.local_spec_files) == 1
    assert staged.remote_spec_file(0).endswith(
        f"/cas/{staged.spec_digests[0]}.json"
    )
    import json

    spec = json.load(open(staged.local_spec_files[0]))
    assert spec["workdir"] == "/wd"
    assert spec["function_digest"] == staged.function_digest
    assert spec["function_file"] == staged.remote_function_file
    assert "distributed" not in spec  # single process: no data plane


def test_file_writes_multi_worker_specs(tmp_path):
    ex = make_executor(tmp_path, workers=["w0", "w1", "w2"], coordinator_port=8111)
    staged = ex._write_function_files("op", lambda: 1, (), {}, "/wd")
    assert len(staged.local_spec_files) == 3
    import json

    for process_id, path in enumerate(staged.local_spec_files):
        spec = json.load(open(path))
        assert spec["distributed"] == {
            "coordinator_address": "127.0.0.1:8111",  # local transport -> loopback
            "num_processes": 3,
            "process_id": process_id,
        }


def test_unique_workdir_layout(tmp_path, run_async):
    fake = FakeTransport(scripted_ok_responses())
    fake.result_payload = ("ok", None)
    ex = make_executor(
        tmp_path, fake, create_unique_workdir=True, remote_workdir="/base"
    )
    captured = {}
    original = ex._write_function_files

    def spy(op_id, fn, args, kwargs, workdir, **kw):
        captured["workdir"] = workdir
        return original(op_id, fn, args, kwargs, workdir, **kw)

    ex._write_function_files = spy
    run_async(ex.run(lambda: "ok", [], {}, METADATA))
    # {workdir}/{dispatch_id}/node_{node_id} — ssh.py:486-491
    assert captured["workdir"] == "/base/d123/node_1"


# --------------------------------------------------------------------- #
# Pre-flight batching
# --------------------------------------------------------------------- #


def test_preflight_is_one_round_trip(tmp_path, run_async):
    fake = FakeTransport({"mkdir -p": CommandResult(0, "3\n", "")})
    ex = make_executor(tmp_path, fake)
    run_async(ex._preflight(fake))
    assert len(fake.commands) == 1  # vs the reference's 3 (ssh.py:508-532)
    assert "mkdir -p" in fake.commands[0]
    assert ex.python_path in fake.commands[0]


def test_preflight_includes_conda_activation(tmp_path):
    ex = make_executor(tmp_path, conda_env="tpu-env")
    cmd = ex._preflight_command()
    assert "conda activate tpu-env" in cmd  # pattern: ssh.py:379-380, 508-519


def test_preflight_rejects_python2(tmp_path, run_async):
    fake = FakeTransport({"mkdir -p": CommandResult(0, "2\n", "")})
    ex = make_executor(tmp_path, fake)
    with pytest.raises(TransportError, match="not python3"):
        run_async(ex._preflight(fake))


# --------------------------------------------------------------------- #
# Status probe / poll
# --------------------------------------------------------------------- #


def test_get_status_ready_running_dead(tmp_path, run_async):
    ex = make_executor(tmp_path)
    for token in ("READY", "RUNNING", "DEAD"):
        fake = FakeTransport({"if test -f": CommandResult(0, f"{token}\n", "")})
        assert run_async(ex.get_status(fake, "/r.pkl", 1)) is TaskStatus(token)


def test_get_status_pid_file_liveness(tmp_path, run_async):
    """With the dispatcher-side pid lost, the harness's pid file is the
    liveness source (VERDICT r1 weak #4) — real shell semantics."""
    import os

    from covalent_tpu_plugin.transport.local import LocalTransport

    conn = LocalTransport()
    ex = make_executor(tmp_path)
    result_file = str(tmp_path / "result.pkl")
    pid_file = str(tmp_path / "pid.0")

    async def status():
        return await ex.get_status(conn, result_file, None, pid_file)

    # Launch window: neither result nor pid file yet.
    assert run_async(status()) is TaskStatus.STARTING
    # Live harness: pid file holds this test process's own pid.
    with open(pid_file, "w") as f:
        f.write(str(os.getpid()))
    assert run_async(status()) is TaskStatus.RUNNING
    # Dead harness: a pid that cannot exist.
    with open(pid_file, "w") as f:
        f.write("2147483600")
    assert run_async(status()) is TaskStatus.DEAD
    # Result outranks everything.
    with open(result_file, "w") as f:
        f.write("x")
    assert run_async(status()) is TaskStatus.READY


def test_poll_task_dead_harness_with_lost_pid_fails_fast(tmp_path, run_async):
    """VERDICT r1 'done' criterion: harness dies without writing a result,
    pid unknown -> the poller must fail fast instead of polling forever."""
    from covalent_tpu_plugin.transport.local import LocalTransport

    conn = LocalTransport()
    ex = make_executor(tmp_path, poll_freq=0.05)
    pid_file = str(tmp_path / "pid.0")
    with open(pid_file, "w") as f:
        f.write("2147483600")  # dead
    status = run_async(
        ex._poll_task(conn, str(tmp_path / "never.pkl"), None, pid_file)
    )
    assert status is TaskStatus.DEAD


def test_poll_task_starting_grace_expires_to_dead(tmp_path, run_async):
    """A harness that never writes its pid file (died pre-first-write) is
    declared dead after the bounded grace, not polled forever."""
    fake = FakeTransport({"if test -f": CommandResult(0, "STARTING\n", "")})
    ex = make_executor(tmp_path, poll_freq=0.05)
    ex.STARTING_GRACE_S = 0.15
    status = run_async(ex._poll_task(fake, "/r.pkl", None, "/pid.0"))
    assert status is TaskStatus.DEAD


def test_poll_task_waits_until_ready(tmp_path, run_async):
    ex = make_executor(tmp_path)
    countdown = {"n": 3}

    def probe(command):
        countdown["n"] -= 1
        return CommandResult(0, "READY\n" if countdown["n"] <= 0 else "RUNNING\n", "")

    fake = FakeTransport({"if test -f": probe})
    assert run_async(ex._poll_task(fake, "/r.pkl", 1)) is TaskStatus.READY


def test_poll_task_detects_dead_process(tmp_path, run_async):
    fake = FakeTransport({"if test -f": CommandResult(0, "DEAD\n", "")})
    ex = make_executor(tmp_path)
    assert run_async(ex._poll_task(fake, "/r.pkl", 1)) is TaskStatus.DEAD


def test_poll_task_timeout(tmp_path, run_async):
    """task_timeout expiry surfaces as TIMEOUT (escalation fodder), not
    DEAD — the caller kills the gang and classifies for retry."""
    fake = FakeTransport({"if test -f": CommandResult(0, "RUNNING\n", "")})
    ex = make_executor(tmp_path, task_timeout=0.15, poll_freq=0.05)
    assert run_async(ex._poll_task(fake, "/r.pkl", 1)) is TaskStatus.TIMEOUT


def test_poll_all_blames_dead_nonzero_worker(tmp_path, run_async):
    """A worker that dies before the barrier (e.g. failed pip install) must
    fail the task fast, not leave process 0 hung in jax.distributed."""
    w0 = FakeTransport({"if test -f": CommandResult(0, "RUNNING\n", "")}, address="w0")
    w1 = FakeTransport({"if test -f": CommandResult(0, "DEAD\n", "")}, address="w1")
    ex = make_executor(tmp_path, workers=["w0", "w1"])
    staged = ex._write_function_files("op", lambda: 1, (), {}, "/wd")
    status, blamed = run_async(ex._poll_all([w0, w1], staged, {"w0": 1, "w1": 2}))
    assert status is TaskStatus.DEAD
    assert blamed == 1
    # worker 1 was probed at its done-marker, not the result file
    assert any(".done.1" in c for c in w1.commands)


def test_poll_task_tolerates_transient_garbled_probe(tmp_path, run_async):
    """One corrupted status line on a flaky channel must not abort the task;
    the probe repeats and succeeds on the next round-trip."""
    countdown = {"n": 2}

    def probe(command):
        countdown["n"] -= 1
        if countdown["n"] >= 1:
            return CommandResult(1, "garbage\n", "channel hiccup")
        return CommandResult(0, "READY\n", "")

    fake = FakeTransport({"if test -f": probe})
    ex = make_executor(tmp_path, poll_freq=0.05)
    assert run_async(ex._poll_task(fake, "/r.pkl", 1)) is TaskStatus.READY


def test_poll_task_raises_after_consecutive_garbled_probes(tmp_path, run_async):
    """A persistently broken channel still surfaces as TransportError."""
    from covalent_tpu_plugin.transport import TransportError

    fake = FakeTransport({"if test -f": CommandResult(1, "garbage\n", "broken")})
    ex = make_executor(tmp_path, poll_freq=0.05)
    with pytest.raises(TransportError):
        run_async(ex._poll_task(fake, "/r.pkl", 1))


def test_poll_all_tolerates_flaky_nonzero_worker_probe(tmp_path, run_async):
    """A single garbled probe on worker 1's channel must not abort a healthy
    multi-worker task (same tolerance the straggler-reap path has)."""
    hiccup = {"n": 1}

    def w1_probe(command):
        if hiccup["n"] > 0:
            hiccup["n"] -= 1
            return CommandResult(1, "garbage\n", "channel hiccup")
        return CommandResult(0, "RUNNING\n", "")

    ready = {"n": 3}

    def w0_probe(command):
        ready["n"] -= 1
        return CommandResult(0, "READY\n" if ready["n"] <= 0 else "RUNNING\n", "")

    w0 = FakeTransport({"if test -f": w0_probe}, address="w0")
    w1 = FakeTransport({"if test -f": w1_probe}, address="w1")
    ex = make_executor(tmp_path, workers=["w0", "w1"], poll_freq=0.05)
    staged = ex._write_function_files("op", lambda: 1, (), {}, "/wd")
    status, blamed = run_async(ex._poll_all([w0, w1], staged, {"w0": 1, "w1": 2}))
    assert status is TaskStatus.READY
    assert blamed == 0


def test_poll_all_ready_from_worker_zero(tmp_path, run_async):
    w0 = FakeTransport({"if test -f": CommandResult(0, "READY\n", "")}, address="w0")
    w1 = FakeTransport({"if test -f": CommandResult(0, "RUNNING\n", "")}, address="w1")
    ex = make_executor(tmp_path, workers=["w0", "w1"])
    staged = ex._write_function_files("op", lambda: 1, (), {}, "/wd")
    status, blamed = run_async(ex._poll_all([w0, w1], staged, {"w0": 1, "w1": 2}))
    assert status is TaskStatus.READY
    assert blamed == 0


# --------------------------------------------------------------------- #
# Orchestration (reference: run()-level tests, ssh_test.py:113-167, 284-316)
# --------------------------------------------------------------------- #


def test_run_happy_path_returns_result(tmp_path, run_async):
    fake = FakeTransport(scripted_ok_responses())
    fake.result_payload = ({"loss": 0.5}, None)
    ex = make_executor(tmp_path, fake)
    result = run_async(ex.run(lambda: None, [], {}, METADATA))
    assert result == {"loss": 0.5}
    # staged files cleaned up locally (ssh.py:310-312)
    assert not any((tmp_path / "cache").glob("function_*"))
    # remote cleanup issued (ssh.py:313-315)
    assert any(c.startswith("rm -f") for c in fake.commands)


def test_run_reraises_remote_exception(tmp_path, run_async):
    fake = FakeTransport(scripted_ok_responses())
    fake.result_payload = (None, KeyError("remote boom"))
    ex = make_executor(tmp_path, fake)
    with pytest.raises(KeyError, match="remote boom"):
        run_async(ex.run(lambda: None, [], {}, METADATA))
    # timings recorded even on the exception path (vs leak at ssh.py:581-587)
    assert "overhead" in ex.last_timings


def test_run_dead_task_routes_to_fallback_raise(tmp_path, run_async):
    fake = FakeTransport(scripted_ok_responses(status="DEAD"))
    ex = make_executor(tmp_path, fake, run_local_on_dispatch_fail=False)
    with pytest.raises(RuntimeError, match="log tail"):
        run_async(ex.run(lambda: None, [], {}, METADATA))


def test_run_dead_task_falls_back_locally(tmp_path, run_async):
    fake = FakeTransport(scripted_ok_responses(status="DEAD"))
    ex = make_executor(tmp_path, fake, run_local_on_dispatch_fail=True)
    assert run_async(ex.run(lambda: "local-result", [], {}, METADATA)) == "local-result"


def test_run_submit_failure_routes_to_fallback(tmp_path, run_async):
    responses = scripted_ok_responses()
    responses["nohup"] = CommandResult(1, "", "launch denied")
    fake = FakeTransport(responses)
    ex = make_executor(tmp_path, fake, run_local_on_dispatch_fail=True)
    assert run_async(ex.run(lambda: 11, [], {}, METADATA)) == 11


def test_run_records_stage_timings(tmp_path, run_async):
    fake = FakeTransport(scripted_ok_responses())
    fake.result_payload = (1, None)
    ex = make_executor(tmp_path, fake)
    run_async(ex.run(lambda: None, [], {}, METADATA))
    for stage in ("validate", "connect", "preflight", "stage", "upload", "submit",
                  "execute", "fetch", "cleanup", "overhead", "total"):
        assert stage in ex.last_timings


def test_run_no_cleanup_when_disabled(tmp_path, run_async):
    fake = FakeTransport(scripted_ok_responses())
    fake.result_payload = (1, None)
    ex = make_executor(tmp_path, fake, do_cleanup=False)
    run_async(ex.run(lambda: None, [], {}, METADATA))
    assert not any(c.startswith("rm -f") for c in fake.commands)


# --------------------------------------------------------------------- #
# Cancel (the reference stubs this — ssh.py:460-464)
# --------------------------------------------------------------------- #


def test_cancel_kills_active_pids(tmp_path, run_async):
    fake = FakeTransport()
    ex = make_executor(tmp_path, fake)
    ex._active["op1"] = {"fake-worker": 999}
    run_async(ex.cancel("op1"))
    assert any("kill" in c and "999" in c for c in fake.commands)
    assert "op1" not in ex._active


def test_launch_all_is_all_or_nothing(tmp_path, run_async):
    """If one worker fails to launch, started workers are killed
    (SURVEY §7 'multi-host launch atomicity')."""
    good = FakeTransport(scripted_ok_responses(pid=111), address="w0")
    bad = FakeTransport(
        {**scripted_ok_responses(), "nohup": CommandResult(1, "", "denied")},
        address="w1",
    )
    ex = make_executor(tmp_path, workers=["w0", "w1"])

    async def fake_connect(address):
        return good if address == "w0" else bad

    ex._client_connect = fake_connect
    staged = ex._write_function_files("op", lambda: 1, (), {}, "/wd")

    async def flow():
        with pytest.raises(TransportError, match="launch failed"):
            await ex._dispatch_all([good, bad], staged, upload=False)

    run_async(flow())
    assert any("kill" in c and "111" in c for c in good.commands)


def test_mid_task_channel_death_discards_pool_and_redials(tmp_path, run_async):
    """A TransportError during execute must discard the pooled transport and
    the next electron must redial cleanly: pool miss counter increments
    again and pre-flight re-runs on the fresh channel."""
    from covalent_tpu_plugin.obs.metrics import REGISTRY

    def dying_probe(command):
        raise TransportError("channel died mid-task")

    dying = FakeTransport(
        {**scripted_ok_responses(), "if test -f": dying_probe},
        address="localhost",
    )
    healthy = FakeTransport(scripted_ok_responses(), address="localhost")
    healthy.result_payload = (5, None)
    transports = iter([dying, healthy])

    # Real TransportPool (no _client_connect patch): only _make_transport
    # is swapped, so discard/redial exercises the production path.
    ex = make_executor(tmp_path)
    ex._make_transport = lambda address: next(transports)

    def miss_count() -> float:
        counter = REGISTRY.get("covalent_tpu_pool_acquires_total")
        return counter.labels(result="miss").value if counter else 0.0

    misses0 = miss_count()

    async def flow():
        with pytest.raises(TransportError):
            await ex.run(lambda: 5, [], {}, {"dispatch_id": "d", "node_id": 0})
        # The dead channel was discarded (closed), its pre-flight evicted.
        assert dying.closed
        assert ex._pool_key("localhost") not in ex._preflighted
        return await ex.run(
            lambda: 5, [], {}, {"dispatch_id": "d", "node_id": 1}
        )

    assert run_async(flow()) == 5
    assert miss_count() - misses0 == 2  # fresh dial for each electron
    # Pre-flight re-ran on the new channel instead of being skipped.
    assert any("mkdir -p" in c for c in healthy.commands)
    assert not dying.commands or dying.commands != healthy.commands


def test_profile_dir_lands_in_spec_per_operation(tmp_path):
    ex = make_executor(tmp_path, profile_dir="/traces")
    staged = ex._write_function_files("opX", lambda: 1, (), {}, "/wd")
    import json

    spec = json.load(open(staged.local_spec_files[0]))
    assert spec["profile_dir"] == "/traces/opX"  # per-task subdir


def test_profile_dir_absent_by_default(tmp_path):
    ex = make_executor(tmp_path)
    staged = ex._write_function_files("opY", lambda: 1, (), {}, "/wd")
    import json

    spec = json.load(open(staged.local_spec_files[0]))
    assert "profile_dir" not in spec


def test_run_deferred_cleanup_completes_by_close(tmp_path, run_async):
    """defer_cleanup: run() returns before the rm round-trips; close()
    drains them, so by teardown the same artifacts are gone as in the
    synchronous path."""
    fake = FakeTransport(scripted_ok_responses())
    fake.result_payload = (1, None)
    ex = make_executor(tmp_path, fake, defer_cleanup=True)

    async def flow():
        out = await ex.run(lambda: None, [], {}, METADATA)
        # Deferred task may not have run yet; close() must wait for it.
        await ex.close()
        return out

    assert run_async(flow()) == 1
    assert any(c.startswith("rm -f") for c in fake.commands)
    assert not any((tmp_path / "cache").glob("function_*"))
    assert "cleanup" in ex.last_timings


def test_close_on_new_loop_drops_stale_cleanup_tasks(tmp_path):
    """defer_cleanup + successive asyncio.run(): close() on a fresh loop
    must not crash on tasks bound to the old loop (it drops + warns)."""
    import asyncio

    fake = FakeTransport(scripted_ok_responses())
    fake.result_payload = (1, None)
    ex = make_executor(tmp_path, fake, defer_cleanup=True)

    async def first():
        return await ex.run(lambda: None, [], {}, METADATA)

    assert asyncio.run(first()) == 1
    # The deferred task (if still pending) now belongs to a closed loop.
    asyncio.run(ex.close())  # must not raise
    assert not ex._cleanup_tasks
