"""Elastic gangs: cooperative checkpoint-resume under preemption.

End-to-end over the local transport with real harness subprocesses: the
interval checkpointer publishes digest-named bundles + an atomic manifest
into the remote CAS; a chaos-injected spot preemption (SIGTERM notice,
grace window, channel drop) triggers the final cooperative snapshot; the
retry driver discovers/verifies the newest complete checkpoint and the
replacement gang resumes from it instead of recomputing — with the
``worker_preempted`` retry label, ``task.resumed`` lineage events and the
saves/restores counters moving.  A torn-bundle-on-disk test proves resume
skips incomplete checkpoints and falls back to the previous complete step.
"""

from __future__ import annotations

import json
import os
import pathlib

from covalent_tpu_plugin import harness as harness_mod
from covalent_tpu_plugin.obs import events as obs_events
from covalent_tpu_plugin.obs.metrics import REGISTRY
from covalent_tpu_plugin.transport import ChaosPlan, LocalTransport

from .helpers import make_local_executor

REPO_ROOT = str(pathlib.Path(__file__).resolve().parents[1])


def counter_value(name: str, **labels) -> float:
    metric = REGISTRY.get(name)
    if metric is None:
        return 0.0
    child = metric.labels(**labels) if labels else metric
    return child.value


def make_elastic_executor(tmp_path, **kwargs):
    kwargs.setdefault("checkpoint_interval_s", 0.15)
    kwargs.setdefault("checkpoint_keep_n", 2)
    kwargs.setdefault("poll_freq", 0.1)
    # Heartbeats give the poll path a telemetry file: the preemption
    # notice lands there, and the failure handler's telemetry tail is how
    # the death gets its worker_preempted label without an agent channel.
    kwargs.setdefault("heartbeat_interval", 0.5)
    kwargs.setdefault("task_env", {
        "PYTHONPATH": REPO_ROOT + os.pathsep + os.environ.get(
            "PYTHONPATH", ""
        ),
    })
    return make_local_executor(tmp_path, **kwargs)


def elastic_train(steps: int, step_s: float, progress_path: str):
    """A checkpoint-cooperative training electron.

    Appends every executed step to ``progress_path`` (so the test can
    count recomputation across attempts), registers a snapshot hook, and
    resumes from the dispatcher-shipped bundle when one exists.
    """
    import time

    from covalent_tpu_plugin.utils import checkpoint as ckpt

    state = {"acc": 0.0, "step": -1}
    start = 0
    resumed = ckpt.resume_state()
    if resumed is not None:
        step0, tree = resumed
        state.update(tree)
        start = int(step0) + 1

    def snap():
        # One read of the rebinding variable: the hook runs from the
        # checkpointer thread AND the SIGTERM handler, and each step
        # publishes a fresh dict instead of mutating in place, so a
        # snapshot is always internally consistent.
        current = state
        return dict(current), current["step"]

    ckpt.register_snapshot(snap)
    try:
        for step in range(start, steps):
            with open(progress_path, "a") as f:
                f.write(f"{step}\n")
            time.sleep(step_s)
            state = {"acc": state["acc"] + step, "step": step}
    finally:
        ckpt.unregister_snapshot()
    return state["acc"], start


class EventLog:
    def __init__(self):
        self.events: list[dict] = []

    def __enter__(self):
        obs_events.add_listener(self.events.append)
        return self

    def __exit__(self, *exc):
        obs_events.remove_listener(self.events.append)

    def of(self, kind: str) -> list[dict]:
        return [e for e in self.events if e.get("type") == kind]


def test_interval_checkpoints_published_to_cas(tmp_path, run_async):
    """No faults: the interval checkpointer publishes sha256-named bundles
    plus a manifest whose history is bounded by keep_n, and the saves
    counter moves via the lifecycle event road."""
    ex = make_elastic_executor(tmp_path, checkpoint_interval_s=0.1)
    metadata = {"dispatch_id": "ckpt-pub", "node_id": 0}
    progress = tmp_path / "progress.txt"

    async def flow():
        try:
            return await ex.run(
                elastic_train, [10, 0.06, str(progress)], {}, metadata
            )
        finally:
            await ex.close()

    acc, start = run_async(flow())
    assert acc == sum(range(10)) and start == 0
    cas = tmp_path / "remote" / "cas"
    manifest_path = cas / "ckpt_ckpt-pub_0.json"
    assert manifest_path.exists(), list(cas.iterdir())
    manifest = json.loads(manifest_path.read_text())
    history = manifest["history"]
    assert 1 <= len(history) <= 2  # keep_n bounds the completed steps
    for entry in history:
        bundle = pathlib.Path(entry["file"])
        assert bundle.exists()
        from covalent_tpu_plugin.utils.checkpoint import verify_bundle_file

        assert verify_bundle_file(bundle, entry["digest"])
    # GC: bundles dropped off the manifest were unlinked.
    assert len(list(cas.glob("*.ckpt"))) == len(history)


def test_preemption_resume_not_recompute(tmp_path, run_async):
    """The tentpole contract: a preempted gang retries INTO a resume —
    correct result, recomputed steps bounded by the checkpoint interval
    (not the whole run), ``worker_preempted`` retry label, ``task.resumed``
    event, restores counter moving."""
    steps, step_s = 60, 0.05
    plan = ChaosPlan(preempt_after=25, preempt_grace=1.0, max_faults=1)
    ex = make_elastic_executor(
        tmp_path,
        max_task_retries=2,
        retry_base_delay=0.05,
        retry_max_delay=0.1,
        chaos=plan,
    )
    metadata = {"dispatch_id": "ckpt-resume", "node_id": 0}
    progress = tmp_path / "progress.txt"
    saves_before = sum(
        child.value for _, child in
        (REGISTRY.get("covalent_tpu_checkpoint_saves_total")._series())
    ) if REGISTRY.get("covalent_tpu_checkpoint_saves_total") else 0.0
    restores_before = counter_value(
        "covalent_tpu_checkpoint_restores_total"
    )
    preempt_retries_before = counter_value(
        "covalent_tpu_task_retries_total", reason="worker_preempted"
    )

    async def flow():
        try:
            return await ex.run(
                elastic_train, [steps, step_s, str(progress)], {}, metadata
            )
        finally:
            await ex.close()

    with EventLog() as log:
        acc, resumed_start = run_async(flow())
    assert acc == sum(range(steps))  # bit-equal train state
    assert plan.faults_injected == 1, "preemption never fired"
    assert resumed_start > 0, "final attempt did not resume"
    executed = [int(x) for x in progress.read_text().split()]
    recomputed = len(executed) - len(set(executed))
    assert recomputed < steps / 2, (recomputed, executed)
    assert counter_value(
        "covalent_tpu_task_retries_total", reason="worker_preempted"
    ) == preempt_retries_before + 1
    assert counter_value(
        "covalent_tpu_checkpoint_restores_total"
    ) == restores_before + 1
    resumed_events = log.of("task.resumed")
    assert resumed_events and resumed_events[0]["lineage"] == (
        "ckpt-resume_0"
    )
    assert int(resumed_events[0]["step"]) == resumed_start - 1
    # The preemption notice reached the dispatcher as an event too.
    assert log.of("task.resume_planned")
    # The flight recorder saw the lineage (task.resumed feeds it like any
    # other task event) — then the clean completion retired the ring.
    assert log.of("task.state")[-1]["state"] == "completed"


def test_torn_checkpoint_skipped_falls_back_to_previous(
    tmp_path, run_async
):
    """A bundle torn on disk (killed mid-write, truncated fs) fails its
    digest check during resume discovery: the previous complete step wins
    and a ``task.resume_skipped_torn`` event records the skip."""
    ex = make_elastic_executor(tmp_path)
    cas_dir = tmp_path / "remote" / "cas"
    cas_dir.mkdir(parents=True, exist_ok=True)
    lineage = "torn-lineage_0"
    harness_mod._write_checkpoint_bundle(
        str(cas_dir), lineage, 3, {"acc": 3.0, "step": 3}, keep_n=4
    )
    path, digest, _ = harness_mod._write_checkpoint_bundle(
        str(cas_dir), lineage, 7, {"acc": 21.0, "step": 7}, keep_n=4
    )
    # Tear the newest bundle ON DISK (its manifest entry still points
    # at it, exactly like a kill mid-fsync).
    data = pathlib.Path(path).read_bytes()
    pathlib.Path(path).write_bytes(data[: len(data) // 2])

    async def flow():
        conn = LocalTransport()
        try:
            with EventLog() as log:
                plan = await ex._discover_resume(lineage, [conn])
            return plan, log.of("task.resume_skipped_torn")
        finally:
            await conn.close()
            await ex.close()

    plan, torn_events = run_async(flow())
    assert plan is not None and plan["step"] == 3
    assert torn_events and torn_events[0]["step"] == 7
    assert torn_events[0]["digest"] == digest
    # The surviving plan's local mirror verifies.
    from covalent_tpu_plugin.utils.checkpoint import verify_bundle_file

    assert verify_bundle_file(plan["local"], plan["digest"])


def test_checkpoint_disabled_means_no_spec_block(tmp_path, run_async):
    """checkpoint_interval_s=0 (the default) ships no checkpoint config,
    installs no handler, and RPC preselect stays unaffected."""
    ex = make_local_executor(tmp_path)
    assert ex.checkpoint_interval_s == 0.0
    staged = ex._write_function_files(
        "nockpt", lambda: 1, (), {}, str(tmp_path / "wd"),
        lineage="nockpt",
    )
    spec = json.loads(
        pathlib.Path(staged.local_spec_files[0]).read_text()
    )
    assert "checkpoint" not in spec and "resume" not in spec

    ex2 = make_local_executor(
        tmp_path / "b", checkpoint_interval_s=5.0, dispatch_mode="auto",
        use_agent="pool",
    )
    assert ex2._rpc_preselect({}) is False  # checkpointing pins launch
    staged2 = ex2._write_function_files(
        "ckpt", lambda: 1, (), {}, str(tmp_path / "wd"), lineage="base",
    )
    spec2 = json.loads(
        pathlib.Path(staged2.local_spec_files[0]).read_text()
    )
    assert spec2["checkpoint"]["lineage"] == "base"
    assert spec2["checkpoint"]["interval_s"] == 5.0

    async def close():
        await ex.close()
        await ex2.close()

    run_async(close())
