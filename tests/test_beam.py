"""Beam search: width-1 greedy oracle, true-logprob scores, EOS freezing.

The decisive properties: beam_width=1 reproduces generate()'s greedy
tokens exactly; returned scores equal independently recomputed sequence
log-probs; frozen EOS beams only ever continue with EOS at zero cost.
(Wider beams beating greedy is a fixed-seed expectation, not an
invariant — beam search can prune the greedy path.)
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from covalent_tpu_plugin.models import (
    TransformerConfig,
    TransformerLM,
    beam_search,
    generate,
)

BASE = TransformerConfig(
    vocab_size=64,
    d_model=32,
    n_layers=2,
    n_heads=4,
    d_ff=64,
    max_seq=32,
    dtype=jnp.float32,
    attention="reference",
)


def build(cfg=BASE, batch=2, plen=4):
    model = TransformerLM(cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (batch, plen), 0,
                                cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]
    return model, params, prompt


def seq_logprob(model, params, tokens, prompt_len):
    """Sum of next-token log-probs over the generated span."""
    logits = model.apply({"params": params}, tokens[:, :-1])
    logprobs = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    picked = jnp.take_along_axis(
        logprobs, tokens[:, 1:, None], axis=-1
    )[..., 0]
    return np.asarray(picked[:, prompt_len - 1:].sum(axis=1))


@pytest.mark.parametrize("scan_layers", [True, False], ids=["scan", "unrolled"])
def test_beam1_equals_greedy(scan_layers):
    cfg = dataclasses.replace(BASE, scan_layers=scan_layers)
    model, params, prompt = build(cfg)
    want = np.asarray(generate(model, params, prompt, 8))
    tokens, scores = beam_search(model, params, prompt, 8, beam_width=1)
    assert tokens.shape == (2, 1, 12) and scores.shape == (2, 1)
    np.testing.assert_array_equal(np.asarray(tokens[:, 0]), want)


def test_beam_scores_are_true_logprobs_and_beat_greedy_here():
    """Returned scores must equal independently recomputed sequence
    log-probs (the load-bearing assertion).  The >= greedy check is a
    fixed-seed regression expectation, NOT an invariant: beam search can
    in principle prune the greedy path and land below it (it searches
    greedily in score-space, not exhaustively)."""
    model, params, prompt = build()
    greedy = generate(model, params, prompt, 8)
    greedy_lp = seq_logprob(model, params, greedy, prompt.shape[1])
    tokens, scores = beam_search(model, params, prompt, 8, beam_width=4)
    best_lp = seq_logprob(
        model, params, tokens[:, 0], prompt.shape[1]
    )
    np.testing.assert_allclose(np.asarray(scores[:, 0]), best_lp,
                               atol=1e-4, rtol=1e-4)
    assert (np.asarray(scores[:, 0]) >= greedy_lp - 1e-4).all()
    # Sorted best-first.
    s = np.asarray(scores)
    assert (np.diff(s, axis=1) <= 1e-6).all()


def test_beam_eos_freezes_hypotheses():
    model, params, prompt = build(batch=1)
    # Use the greedy first token as EOS: the top beam finishes immediately
    # and must then pad with EOS at unchanged score.
    greedy = np.asarray(generate(model, params, prompt, 6))
    eos = int(greedy[0, prompt.shape[1]])
    tokens, scores = beam_search(
        model, params, prompt, 6, beam_width=3, eos_token_id=eos
    )
    tokens = np.asarray(tokens)
    plen = prompt.shape[1]
    for w in range(3):
        row = tokens[0, w, plen:]
        hits = np.where(row == eos)[0]
        if hits.size:  # everything after the first EOS is EOS
            assert (row[hits[0]:] == eos).all()


def test_beam_is_jittable_and_validates():
    model, params, prompt = build(batch=1, plen=3)
    jitted = jax.jit(
        lambda p, t: beam_search(model, p, t, 5, beam_width=2)
    )
    tokens, scores = jitted(params, prompt)
    assert tokens.shape == (1, 2, 8)
    t2, s2 = jitted(params, prompt)
    np.testing.assert_array_equal(np.asarray(tokens), np.asarray(t2))
    with pytest.raises(ValueError, match="beam_width"):
        beam_search(model, params, prompt, 4, beam_width=0)
    with pytest.raises(ValueError, match="max_seq"):
        beam_search(model, params, prompt, 40)
    zero, zscores = beam_search(model, params, prompt, 0, beam_width=2)
    np.testing.assert_array_equal(
        np.asarray(zero[:, 0]), np.asarray(prompt)
    )


def test_rank_hypotheses_reorders_by_per_length_score():
    """The GNMT divisor must promote a long cheap-per-token hypothesis
    over a short expensive one that wins on raw sums — unit-checked on
    handcrafted scores/lengths so a regression in the ranking math can't
    hide behind search stochasticity."""
    from covalent_tpu_plugin.models.beam import rank_hypotheses

    # Beam A: 20 tokens, sum -1.0 (cheap per token, -0.05).  Beam B: 2
    # tokens, sum -0.9 (expensive per token, -0.45).  Raw sums prefer B
    # (-0.9 > -1.0); the per-length divisor must flip the order to A
    # (-0.05 > -0.45).
    scores = jnp.asarray([[-1.0, -0.9]])
    lengths = jnp.asarray([[20.0, 2.0]])
    raw = np.asarray(rank_hypotheses(scores, lengths, 0.0))
    assert np.argmax(raw[0]) == 1  # penalty off: B wins on raw sum
    gnmt = np.asarray(rank_hypotheses(scores, lengths, 1.0))
    assert np.argmax(gnmt[0]) == 0  # alpha=1: long cheap beam A wins


def test_length_penalty_search_sets_agree():
    """Penalty only affects the final ordering, never the search: raw
    per-beam score SETS agree between penalty settings end to end."""
    model, params, prompt = build(batch=2)
    greedy = np.asarray(generate(model, params, prompt, 8))
    eos = int(greedy[0, prompt.shape[1]])
    _, s0 = beam_search(model, params, prompt, 8, beam_width=4,
                        eos_token_id=eos, length_penalty=0.0)
    _, s1 = beam_search(model, params, prompt, 8, beam_width=4,
                        eos_token_id=eos, length_penalty=2.0)
    np.testing.assert_allclose(
        np.sort(np.asarray(s0), axis=1), np.sort(np.asarray(s1), axis=1),
        atol=1e-5, rtol=1e-5,
    )
