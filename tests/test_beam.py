"""Beam search: width-1 greedy oracle, true-logprob scores, EOS freezing.

The decisive properties: beam_width=1 reproduces generate()'s greedy
tokens exactly; returned scores equal independently recomputed sequence
log-probs; frozen EOS beams only ever continue with EOS at zero cost.
(Wider beams beating greedy is a fixed-seed expectation, not an
invariant — beam search can prune the greedy path.)
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from covalent_tpu_plugin.models import (
    TransformerConfig,
    TransformerLM,
    beam_search,
    generate,
)

BASE = TransformerConfig(
    vocab_size=64,
    d_model=32,
    n_layers=2,
    n_heads=4,
    d_ff=64,
    max_seq=32,
    dtype=jnp.float32,
    attention="reference",
)


def build(cfg=BASE, batch=2, plen=4):
    model = TransformerLM(cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (batch, plen), 0,
                                cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]
    return model, params, prompt


def seq_logprob(model, params, tokens, prompt_len):
    """Sum of next-token log-probs over the generated span."""
    logits = model.apply({"params": params}, tokens[:, :-1])
    logprobs = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    picked = jnp.take_along_axis(
        logprobs, tokens[:, 1:, None], axis=-1
    )[..., 0]
    return np.asarray(picked[:, prompt_len - 1:].sum(axis=1))


@pytest.mark.parametrize("scan_layers", [True, False], ids=["scan", "unrolled"])
def test_beam1_equals_greedy(scan_layers):
    cfg = dataclasses.replace(BASE, scan_layers=scan_layers)
    model, params, prompt = build(cfg)
    want = np.asarray(generate(model, params, prompt, 8))
    tokens, scores = beam_search(model, params, prompt, 8, beam_width=1)
    assert tokens.shape == (2, 1, 12) and scores.shape == (2, 1)
    np.testing.assert_array_equal(np.asarray(tokens[:, 0]), want)


def test_beam_scores_are_true_logprobs_and_beat_greedy_here():
    """Returned scores must equal independently recomputed sequence
    log-probs (the load-bearing assertion).  The >= greedy check is a
    fixed-seed regression expectation, NOT an invariant: beam search can
    in principle prune the greedy path and land below it (it searches
    greedily in score-space, not exhaustively)."""
    model, params, prompt = build()
    greedy = generate(model, params, prompt, 8)
    greedy_lp = seq_logprob(model, params, greedy, prompt.shape[1])
    tokens, scores = beam_search(model, params, prompt, 8, beam_width=4)
    best_lp = seq_logprob(
        model, params, tokens[:, 0], prompt.shape[1]
    )
    np.testing.assert_allclose(np.asarray(scores[:, 0]), best_lp,
                               atol=1e-4, rtol=1e-4)
    assert (np.asarray(scores[:, 0]) >= greedy_lp - 1e-4).all()
    # Sorted best-first.
    s = np.asarray(scores)
    assert (np.diff(s, axis=1) <= 1e-6).all()


def test_beam_eos_freezes_hypotheses():
    model, params, prompt = build(batch=1)
    # Use the greedy first token as EOS: the top beam finishes immediately
    # and must then pad with EOS at unchanged score.
    greedy = np.asarray(generate(model, params, prompt, 6))
    eos = int(greedy[0, prompt.shape[1]])
    tokens, scores = beam_search(
        model, params, prompt, 6, beam_width=3, eos_token_id=eos
    )
    tokens = np.asarray(tokens)
    plen = prompt.shape[1]
    for w in range(3):
        row = tokens[0, w, plen:]
        hits = np.where(row == eos)[0]
        if hits.size:  # everything after the first EOS is EOS
            assert (row[hits[0]:] == eos).all()


def test_beam_is_jittable_and_validates():
    model, params, prompt = build(batch=1, plen=3)
    jitted = jax.jit(
        lambda p, t: beam_search(model, p, t, 5, beam_width=2)
    )
    tokens, scores = jitted(params, prompt)
    assert tokens.shape == (1, 2, 8)
    t2, s2 = jitted(params, prompt)
    np.testing.assert_array_equal(np.asarray(tokens), np.asarray(t2))
    with pytest.raises(ValueError, match="beam_width"):
        beam_search(model, params, prompt, 4, beam_width=0)
    with pytest.raises(ValueError, match="max_seq"):
        beam_search(model, params, prompt, 40)
    zero, zscores = beam_search(model, params, prompt, 0, beam_width=2)
    np.testing.assert_array_equal(
        np.asarray(zero[:, 0]), np.asarray(prompt)
    )


class _ScriptedLM(TransformerLM):
    """Markov-table LM: logits for position t depend only on token t.

    ``config.decode=True`` makes ``_decode_model`` return it unchanged, so
    beam_search runs the scripted logits through its real cache/gather/
    pool machinery.  Deterministic with hand-set margins — no fp near-ties
    — which is what makes exact search-tree assertions possible.
    """

    table: tuple = ()  # (V, V) row = next-token logits given current token

    @__import__("flax").linen.compact
    def __call__(self, tokens):
        # A dummy cache var so init_cache/mutable=["cache"] have a leaf to
        # carry; the scripted logits themselves need no state.
        self.variable("cache", "cache_index", lambda: jnp.zeros((), jnp.int32))
        table = jnp.asarray(self.table, jnp.float32)
        return table[tokens]


def _scripted(table, vocab, max_seq=32):
    cfg = dataclasses.replace(
        BASE, vocab_size=vocab, max_seq=max_seq, decode=True
    )
    return _ScriptedLM(cfg, table=tuple(map(tuple, table)))


def test_finished_pool_rescues_evicted_hypothesis():
    """Handcrafted eviction: an early-finished beam is pushed out of the
    active top-W by ongoing beams, which then decay below its score — the
    returned best MUST be the banked finished hypothesis (without the
    pool it would be lost and a worse survivor returned)."""
    import math

    vocab, eos = 4, 3
    big = -1e9
    # From token 0: token 1 (lp ~ -0.18), token 2 (-0.29), eos (-3.3).
    # From 1 or 2: continue to {1, 2} at ~ -0.69 each, never eos.
    from_0 = [big, 2.0, 1.5, -1.0]
    from_12 = [big, 1.0, 1.0, big]
    table = [from_0, from_12, from_12, [big, 1.0, 1.0, big]]
    model = _scripted(table, vocab)
    prompt = jnp.zeros((1, 1), jnp.int32)  # start at token 0
    params = model.init(jax.random.PRNGKey(0), prompt).get("params", {})

    tokens, scores = beam_search(
        model, params, prompt, 10, beam_width=3, eos_token_id=eos,
        length_penalty=0.0,  # rank by raw scores: no length effects
    )
    # Step 1 seeds beams [1], [2], [eos]; the frozen [eos] beam is evicted
    # at step 2 (1->{1,2} and 2->{1,2} all outscore it), and every ongoing
    # beam ends near -0.18 - 9 * 0.69 << the eos path's score.
    lse0 = math.log(sum(math.exp(x) for x in from_0))
    eos_score = from_0[eos] - lse0
    got_best = float(scores[0, 0])
    assert abs(got_best - eos_score) < 1e-4, (got_best, eos_score)
    # The winning hypothesis is eos-from-the-start, padded with EOS.
    np.testing.assert_array_equal(
        np.asarray(tokens[0, 0]), np.asarray([0] + [eos] * 10)
    )
    # And the survivors (worse raw scores) rank behind it.
    assert (np.asarray(scores[0, 1:]) < got_best).all()


def test_finished_pool_keeps_best_of_many_evictions():
    """Several finished hypotheses evicted over time: the pool must retain
    and rank the best ones, not just the latest."""
    vocab, eos = 5, 4
    big = -1e9
    # From 0: two strong continuations (1, 2), a weak eos, and token 3.
    # From 1: eos is attractive (finishes second-generation beams), plus
    # strong 1/2 continuations that keep ongoing beams alive.
    table = [
        [big, 2.0, 1.8, 0.5, -0.5],   # from 0
        [big, 1.2, 1.0, big, 0.8],    # from 1: eos competitive
        [big, 1.0, 1.2, big, -2.0],   # from 2: eos weak
        [big, 1.0, 1.0, big, big],    # from 3
        [big, 1.0, 1.0, big, big],    # from eos (unused: frozen)
    ]
    model = _scripted(table, vocab)
    prompt = jnp.zeros((1, 1), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), prompt).get("params", {})
    tokens, scores = beam_search(
        model, params, prompt, 12, beam_width=3, eos_token_id=eos,
        length_penalty=0.0,
    )
    arr = np.asarray(tokens[0])
    s = np.asarray(scores[0])
    # Finished hypotheses (ending in EOS) must fill the top slots: any
    # 12-token ongoing beam has accumulated ~12 * 0.7+ of negative lp.
    assert (arr[0] == eos).any(), arr[0]
    # Scores sorted best-first and consistent with an EOS-terminated best.
    assert (np.diff(s) <= 1e-6).all()
    # Every returned score is a genuine prefix log-prob: recompute from
    # the scripted table directly.
    import math

    def path_logprob(row):
        lp = 0.0
        cur = 0
        for tok in row[1:]:
            logits = table[cur]
            lse = math.log(sum(math.exp(x) for x in logits))
            lp += logits[tok] - lse
            if tok == eos:
                break
            cur = tok
        return lp

    for w in range(3):
        np.testing.assert_allclose(
            s[w], path_logprob(arr[w].tolist()), atol=1e-4
        )


def test_beam_rolling_cache_past_max_seq():
    """Rolling-cache beam search: decode beyond max_seq at O(window)
    memory, width-1 equal to the (already-verified) rolling generate()."""
    cfg = dataclasses.replace(
        BASE, sliding_window=6, rolling_cache=True
    )
    model, params, prompt = build(cfg, batch=1)
    n_new = cfg.max_seq + 8  # 40 > max_seq=32
    tokens, scores = beam_search(model, params, prompt, n_new, beam_width=1)
    want = np.asarray(generate(model, params, prompt, n_new))
    np.testing.assert_array_equal(np.asarray(tokens[:, 0]), want)
    # Width > 1 past max_seq: shapes, range, intact prompt.
    tokens, _ = beam_search(model, params, prompt, n_new, beam_width=3)
    arr = np.asarray(tokens)
    assert arr.shape == (1, 3, 4 + n_new)
    assert (arr >= 0).all() and (arr < cfg.vocab_size).all()
    np.testing.assert_array_equal(
        arr[:, :, :4], np.broadcast_to(np.asarray(prompt)[:, None], (1, 3, 4))
    )
    # Prompts longer than the ring still refuse.
    with pytest.raises(ValueError, match="exceeds"):
        beam_search(model, params, jnp.zeros((1, 10), jnp.int32), 4)


def test_rank_hypotheses_reorders_by_per_length_score():
    """The GNMT divisor must promote a long cheap-per-token hypothesis
    over a short expensive one that wins on raw sums — unit-checked on
    handcrafted scores/lengths so a regression in the ranking math can't
    hide behind search stochasticity."""
    from covalent_tpu_plugin.models.beam import rank_hypotheses

    # Beam A: 20 tokens, sum -1.0 (cheap per token, -0.05).  Beam B: 2
    # tokens, sum -0.9 (expensive per token, -0.45).  Raw sums prefer B
    # (-0.9 > -1.0); the per-length divisor must flip the order to A
    # (-0.05 > -0.45).
    scores = jnp.asarray([[-1.0, -0.9]])
    lengths = jnp.asarray([[20.0, 2.0]])
    raw = np.asarray(rank_hypotheses(scores, lengths, 0.0))
    assert np.argmax(raw[0]) == 1  # penalty off: B wins on raw sum
    gnmt = np.asarray(rank_hypotheses(scores, lengths, 1.0))
    assert np.argmax(gnmt[0]) == 0  # alpha=1: long cheap beam A wins


def test_length_penalty_never_affects_active_search():
    """Penalty shapes pool retention and the final ordering, NEVER the
    active search.  With EOS unreachable (below every top-2W cut) the
    pool stays empty and the returned hypotheses are exactly the active
    beams — so token sets and score sets must agree across penalties."""
    vocab = 6
    big = -1e9
    rows = [
        [big, 1.0 + 0.13 * t, 0.8 - 0.07 * t, 0.5, 0.2 * t, big]
        for t in range(5)
    ] + [[big, 1.0, 1.0, 1.0, 1.0, big]]
    model = _scripted(rows, vocab)
    prompt = jnp.zeros((1, 1), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), prompt).get("params", {})
    t0, s0 = beam_search(model, params, prompt, 8, beam_width=3,
                         eos_token_id=5, length_penalty=0.0)
    t1, s1 = beam_search(model, params, prompt, 8, beam_width=3,
                         eos_token_id=5, length_penalty=2.0)
    np.testing.assert_allclose(
        np.sort(np.asarray(s0), axis=1), np.sort(np.asarray(s1), axis=1),
        atol=1e-5, rtol=1e-5,
    )
    # Same hypothesis sets, possibly different order.
    set0 = {tuple(r) for r in np.asarray(t0[0])}
    set1 = {tuple(r) for r in np.asarray(t1[0])}
    assert set0 == set1
