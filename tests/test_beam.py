"""Beam search: width-1 greedy oracle, score dominance, EOS freezing.

The decisive properties: beam_width=1 reproduces generate()'s greedy
tokens exactly; wider beams never score worse than greedy (they search a
superset); frozen EOS beams only ever continue with EOS at zero cost.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from covalent_tpu_plugin.models import (
    TransformerConfig,
    TransformerLM,
    beam_search,
    generate,
)

BASE = TransformerConfig(
    vocab_size=64,
    d_model=32,
    n_layers=2,
    n_heads=4,
    d_ff=64,
    max_seq=32,
    dtype=jnp.float32,
    attention="reference",
)


def build(cfg=BASE, batch=2, plen=4):
    model = TransformerLM(cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (batch, plen), 0,
                                cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]
    return model, params, prompt


def seq_logprob(model, params, tokens, prompt_len):
    """Sum of next-token log-probs over the generated span."""
    logits = model.apply({"params": params}, tokens[:, :-1])
    logprobs = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    picked = jnp.take_along_axis(
        logprobs, tokens[:, 1:, None], axis=-1
    )[..., 0]
    return np.asarray(picked[:, prompt_len - 1:].sum(axis=1))


@pytest.mark.parametrize("scan_layers", [True, False], ids=["scan", "unrolled"])
def test_beam1_equals_greedy(scan_layers):
    cfg = dataclasses.replace(BASE, scan_layers=scan_layers)
    model, params, prompt = build(cfg)
    want = np.asarray(generate(model, params, prompt, 8))
    tokens, scores = beam_search(model, params, prompt, 8, beam_width=1)
    assert tokens.shape == (2, 1, 12) and scores.shape == (2, 1)
    np.testing.assert_array_equal(np.asarray(tokens[:, 0]), want)


def test_wider_beam_never_scores_worse_than_greedy():
    model, params, prompt = build()
    greedy = generate(model, params, prompt, 8)
    greedy_lp = seq_logprob(model, params, greedy, prompt.shape[1])
    tokens, scores = beam_search(model, params, prompt, 8, beam_width=4)
    # Returned scores must equal the independently recomputed log-probs.
    best_lp = seq_logprob(
        model, params, tokens[:, 0], prompt.shape[1]
    )
    np.testing.assert_allclose(np.asarray(scores[:, 0]), best_lp,
                               atol=1e-4, rtol=1e-4)
    assert (np.asarray(scores[:, 0]) >= greedy_lp - 1e-4).all()
    # Sorted best-first.
    s = np.asarray(scores)
    assert (np.diff(s, axis=1) <= 1e-6).all()


def test_beam_eos_freezes_hypotheses():
    model, params, prompt = build(batch=1)
    # Use the greedy first token as EOS: the top beam finishes immediately
    # and must then pad with EOS at unchanged score.
    greedy = np.asarray(generate(model, params, prompt, 6))
    eos = int(greedy[0, prompt.shape[1]])
    tokens, scores = beam_search(
        model, params, prompt, 6, beam_width=3, eos_token_id=eos
    )
    tokens = np.asarray(tokens)
    plen = prompt.shape[1]
    for w in range(3):
        row = tokens[0, w, plen:]
        hits = np.where(row == eos)[0]
        if hits.size:  # everything after the first EOS is EOS
            assert (row[hits[0]:] == eos).all()


def test_beam_is_jittable_and_validates():
    model, params, prompt = build(batch=1, plen=3)
    jitted = jax.jit(
        lambda p, t: beam_search(model, p, t, 5, beam_width=2)
    )
    tokens, scores = jitted(params, prompt)
    assert tokens.shape == (1, 2, 8)
    t2, s2 = jitted(params, prompt)
    np.testing.assert_array_equal(np.asarray(tokens), np.asarray(t2))
    with pytest.raises(ValueError, match="beam_width"):
        beam_search(model, params, prompt, 4, beam_width=0)
    with pytest.raises(ValueError, match="max_seq"):
        beam_search(model, params, prompt, 40)
    zero, zscores = beam_search(model, params, prompt, 0, beam_width=2)
    np.testing.assert_array_equal(
        np.asarray(zero[:, 0]), np.asarray(prompt)
    )


def test_length_penalty_changes_ranking():
    """A short finished beam and a long beam must be re-ranked by the
    per-hypothesis GNMT divisor — construct directly from the returned
    raw scores and lengths semantics via two penalty settings."""
    model, params, prompt = build(batch=2)
    greedy = np.asarray(generate(model, params, prompt, 8))
    eos = int(greedy[0, prompt.shape[1]])
    t0, s0 = beam_search(model, params, prompt, 8, beam_width=4,
                         eos_token_id=eos, length_penalty=0.0)
    t1, s1 = beam_search(model, params, prompt, 8, beam_width=4,
                         eos_token_id=eos, length_penalty=2.0)
    # Raw per-beam score SETS agree between penalty settings (the search
    # itself is unchanged); only the ordering may differ.
    np.testing.assert_allclose(
        np.sort(np.asarray(s0), axis=1), np.sort(np.asarray(s1), axis=1),
        atol=1e-5, rtol=1e-5,
    )
