"""Pipeline parallelism (GPipe schedule over the `pipe` mesh axis).

The decisive property at every level: pipelined compute is numerically
transparent — identical outputs/losses/gradients to the dense single-path
program — while parameters live stage-sharded.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from covalent_tpu_plugin.models import TransformerConfig, TransformerLM, lm_loss
from covalent_tpu_plugin.models.pipeline_lm import (
    pipeline_lm_forward,
    pipeline_lm_loss,
)
from covalent_tpu_plugin.parallel import MeshPlan, make_mesh
from covalent_tpu_plugin.parallel.pipeline import (
    pipeline_stages,
    pipelined,
)


def toy_setup(n_layers=8, d=16):
    ws = jax.random.normal(jax.random.PRNGKey(0), (n_layers, d, d)) * 0.3
    micro = jax.random.normal(jax.random.PRNGKey(1), (4, 6, d))

    def dense(ws, x):
        for i in range(n_layers):
            x = jnp.tanh(x @ ws[i])
        return x

    def stage_fn(stage_ws, x):
        h, _ = jax.lax.scan(lambda h, w: (jnp.tanh(h @ w), None), x, stage_ws)
        return h

    return ws, micro, dense, stage_fn


def test_pipeline_forward_matches_dense():
    ws, micro, dense, stage_fn = toy_setup()
    mesh = make_mesh(MeshPlan(pipe=4))
    out = pipelined(stage_fn, mesh)(pipeline_stages(ws, 4), micro)
    ref = jnp.stack([dense(ws, micro[m]) for m in range(micro.shape[0])])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_pipeline_gradients_match_dense():
    ws, micro, dense, stage_fn = toy_setup()
    mesh = make_mesh(MeshPlan(pipe=4))
    fn = pipelined(stage_fn, mesh)
    stacked = pipeline_stages(ws, 4)

    def loss_pp(stacked, mb):
        return (fn(stacked, mb) ** 2).sum()

    def loss_ref(ws, mb):
        return (jnp.stack([dense(ws, mb[m]) for m in range(4)]) ** 2).sum()

    g_pp = jax.grad(loss_pp)(stacked, micro)
    g_ref = pipeline_stages(jax.grad(loss_ref)(ws, micro), 4)
    np.testing.assert_allclose(np.asarray(g_pp), np.asarray(g_ref), atol=1e-4)


def test_pipeline_composes_with_data_axis():
    ws, micro, dense, stage_fn = toy_setup()
    mesh = make_mesh(MeshPlan(data=2, pipe=4))
    out = pipelined(stage_fn, mesh)(pipeline_stages(ws, 4), micro)
    ref = jnp.stack([dense(ws, micro[m]) for m in range(4)])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_pipeline_fewer_microbatches_than_stages():
    """M < S (bubble-dominated edge): the diagonal schedule must still
    deliver every microbatch's output."""
    ws, micro, dense, stage_fn = toy_setup()
    micro = micro[:2]  # M=2 over S=4 stages
    mesh = make_mesh(MeshPlan(pipe=4))
    out = pipelined(stage_fn, mesh)(pipeline_stages(ws, 4), micro)
    ref = jnp.stack([dense(ws, micro[m]) for m in range(2)])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_pipeline_stages_validates_divisibility():
    ws = jnp.zeros((6, 4, 4))
    with pytest.raises(ValueError, match="divisible"):
        pipeline_stages(ws, 4)


LM_CFG = TransformerConfig(
    vocab_size=64,
    d_model=32,
    n_layers=4,
    n_heads=2,
    d_ff=64,
    max_seq=16,
    dtype=jnp.float32,
    attention="reference",
    scan_layers=True,
)


def test_pipeline_lm_matches_dense_model():
    """The whole 125M-shaped path in miniature: block stack pipelined over
    4 stages, embedding/norm/head replicated — logits, loss, and layer
    gradients must match the plain model."""
    mesh = make_mesh(MeshPlan(pipe=4))
    model = TransformerLM(LM_CFG)
    tokens = jax.random.randint(jax.random.PRNGKey(2), (4, 17), 0, 64)
    params = model.init(jax.random.PRNGKey(0), tokens[:, :-1])["params"]

    logits_pp = pipeline_lm_forward(
        model, params, tokens[:, :-1], mesh, n_micro=2
    )
    logits_ref = model.apply({"params": params}, tokens[:, :-1])
    np.testing.assert_allclose(
        np.asarray(logits_pp), np.asarray(logits_ref), atol=2e-4, rtol=2e-4
    )

    batch = {"tokens": tokens}
    loss_pp, grads_pp = jax.value_and_grad(
        lambda p: pipeline_lm_loss(model, p, batch, mesh, n_micro=2)
    )(params)
    loss_ref, grads_ref = jax.value_and_grad(
        lambda p: lm_loss(p, model.apply, batch)
    )(params)
    np.testing.assert_allclose(float(loss_pp), float(loss_ref), rtol=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(grads_pp), jax.tree_util.tree_leaves(grads_ref)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-4, rtol=2e-3
        )


def test_pipeline_lm_remat_matches():
    """config.remat must be honoured (recompute, same numbers)."""
    import dataclasses

    mesh = make_mesh(MeshPlan(pipe=4))
    cfg = dataclasses.replace(LM_CFG, remat=True)
    model = TransformerLM(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(3), (4, 17), 0, 64)
    params = model.init(jax.random.PRNGKey(0), tokens[:, :-1])["params"]
    batch = {"tokens": tokens}
    loss_r, grads_r = jax.value_and_grad(
        lambda p: pipeline_lm_loss(model, p, batch, mesh, n_micro=2)
    )(params)
    loss_ref = lm_loss(params, model.apply, batch)
    np.testing.assert_allclose(float(loss_r), float(loss_ref), rtol=1e-5)
    assert all(
        bool(jnp.isfinite(g).all()) for g in jax.tree_util.tree_leaves(grads_r)
    )


def test_pipeline_lm_requires_scanned_layers():
    import dataclasses

    mesh = make_mesh(MeshPlan(pipe=4))
    cfg = dataclasses.replace(LM_CFG, scan_layers=False)
    model = TransformerLM(cfg)
    tokens = jnp.zeros((2, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    with pytest.raises(ValueError, match="scan_layers"):
        pipeline_lm_forward(model, params, tokens, mesh, n_micro=2)


def test_pipeline_lm_matches_dense_at_nondefault_rope_base():
    """rope_base must thread into the pipelined block's rotary too —
    a hardcoded default there silently diverges from the dense model."""
    mesh = make_mesh(MeshPlan(pipe=4))
    import dataclasses

    cfg = dataclasses.replace(LM_CFG, rope_base=500_000.0)
    model = TransformerLM(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(3), (2, 17), 0, 64)
    params = model.init(jax.random.PRNGKey(0), tokens[:, :-1])["params"]
    logits_pp = pipeline_lm_forward(
        model, params, tokens[:, :-1], mesh, n_micro=2
    )
    logits_ref = model.apply({"params": params}, tokens[:, :-1])
    np.testing.assert_allclose(
        np.asarray(logits_pp), np.asarray(logits_ref), atol=2e-4, rtol=2e-4
    )
