"""RPC dispatch: execute-by-digest on the warm resident runtime.

The PR-8 fast path end to end over the real local transport: function
registered once per connection via the CAS, invoked by digest with args
inline on the agent channel, results streamed back — plus the lifecycle
guarantees around it (re-registration after an agent restart, eviction on
discard, the oversized-args CAS road, digest-mismatch permanence, the
dead-resident-worker transient, launch-path fallbacks, scheduler digest
affinity, and the AgentClient leak audit).
"""

import asyncio
import base64
import sys

import cloudpickle
import pytest

from covalent_tpu_plugin import TPUExecutor
from covalent_tpu_plugin.agent import AgentError, start_pool_server
from covalent_tpu_plugin.cache import bytes_digest
from covalent_tpu_plugin.obs.metrics import REGISTRY
from covalent_tpu_plugin.resilience import FaultClass, classify_error
from covalent_tpu_plugin.transport import LocalTransport

from .helpers import pin_cpu_task_env


def make_rpc_executor(tmp_path, **kwargs):
    kwargs.setdefault("transport", "local")
    kwargs.setdefault("cache_dir", str(tmp_path / "cache"))
    kwargs.setdefault("remote_cache", str(tmp_path / "remote"))
    kwargs.setdefault("python_path", sys.executable)
    kwargs.setdefault("poll_freq", 0.2)
    kwargs.setdefault("use_agent", "pool")
    kwargs.setdefault("dispatch_mode", "rpc")
    kwargs.setdefault("heartbeat_interval", 0.0)
    kwargs.setdefault("prewarm", False)
    return TPUExecutor(**pin_cpu_task_env(kwargs))


def counter_value(name: str, **labels) -> float:
    metric = REGISTRY.get(name)
    if metric is None:
        return 0.0
    total = 0.0
    for series_labels, counter in metric._series():
        if all(series_labels.get(k) == v for k, v in labels.items()):
            total += counter.value
    return total


def _make_square():
    # Nested on purpose: cloudpickle serializes module-level functions BY
    # REFERENCE (module + qualname), and the resident server cannot import
    # the tests package — a closure-local function pickles by value, like
    # real user electrons defined in scripts/notebooks.
    def square(x):
        return x * x

    return square


square = _make_square()


# ---------------------------------------------------------------------------
# The happy path
# ---------------------------------------------------------------------------


def test_rpc_executes_by_digest_and_matches_launch(tmp_path, run_async):
    """Same electron through both modes: equal results, byte-equal pickles,
    and the fast path actually engaged (no silent launch fallback)."""

    async def flow():
        rpc = make_rpc_executor(tmp_path / "rpc")
        launch = make_rpc_executor(tmp_path / "launch", dispatch_mode="launch")
        try:
            rpc_result = await rpc.run(
                square, [7], {}, {"dispatch_id": "r", "node_id": 0}
            )
            rpc_mode = rpc.last_dispatch_mode
            launch_result = await launch.run(
                square, [7], {}, {"dispatch_id": "l", "node_id": 0}
            )
            launch_mode = launch.last_dispatch_mode
        finally:
            await rpc.close()
            await launch.close()
        return rpc_result, rpc_mode, launch_result, launch_mode

    rpc_result, rpc_mode, launch_result, launch_mode = run_async(flow())
    assert rpc_result == launch_result == 49
    assert cloudpickle.dumps(rpc_result) == cloudpickle.dumps(launch_result)
    assert rpc_mode == "rpc"
    assert launch_mode == "launch"


def test_rpc_registers_once_per_connection(tmp_path, run_async):
    """Repeat electrons with different args share one registration: the
    warm path is invoke-by-digest, not re-ship + re-register."""

    async def flow():
        ex = make_rpc_executor(tmp_path)
        misses0 = counter_value(
            "covalent_tpu_rpc_registrations_total", result="miss"
        )
        hits0 = counter_value(
            "covalent_tpu_rpc_registrations_total", result="hit"
        )
        try:
            results = [
                await ex.run(
                    square, [i], {}, {"dispatch_id": "warm", "node_id": i}
                )
                for i in range(3)
            ]
            counts = ex._fn_registry.counts()
            digest_count = ex.rpc_digest_count()
        finally:
            await ex.close()
        return (
            results, counts, digest_count,
            counter_value(
                "covalent_tpu_rpc_registrations_total", result="miss"
            ) - misses0,
            counter_value(
                "covalent_tpu_rpc_registrations_total", result="hit"
            ) - hits0,
        )

    results, counts, digest_count, misses, hits = run_async(flow())
    assert results == [0, 1, 4]
    assert digest_count == 1 and list(counts.values()) == [1]
    assert misses == 1  # one register_fn round trip total
    assert hits == 2    # electrons 2 and 3 rode the registry


def test_rpc_exception_transported(tmp_path, run_async):
    def boom():
        raise KeyError("rpc-boom")

    async def flow():
        ex = make_rpc_executor(tmp_path)
        try:
            with pytest.raises(KeyError, match="rpc-boom"):
                await ex.run(boom, [], {}, {"dispatch_id": "b", "node_id": 0})
            return ex.last_dispatch_mode
        finally:
            await ex.close()

    assert run_async(flow()) == "rpc"


def test_rpc_oversized_args_take_cas_path_with_equal_results(
    tmp_path, run_async
):
    """Args past the inline threshold stage through the CAS (digest
    verified remotely) and the invocation still returns identical bytes."""
    big = "x" * 50_000

    async def flow():
        inline = make_rpc_executor(tmp_path / "inline")
        staged = make_rpc_executor(
            tmp_path / "staged", rpc_inline_args_max=64
        )
        cas0 = counter_value("covalent_tpu_cas_uploads_total", result="miss")
        try:
            inline_result = await inline.run(
                len, [big], {}, {"dispatch_id": "i", "node_id": 0}
            )
            cas_inline = counter_value(
                "covalent_tpu_cas_uploads_total", result="miss"
            ) - cas0
            staged_result = await staged.run(
                len, [big], {}, {"dispatch_id": "s", "node_id": 0}
            )
            cas_staged = counter_value(
                "covalent_tpu_cas_uploads_total", result="miss"
            ) - cas0 - cas_inline
            modes = (inline.last_dispatch_mode, staged.last_dispatch_mode)
        finally:
            await inline.close()
            await staged.close()
        return inline_result, staged_result, cas_inline, cas_staged, modes

    inline_result, staged_result, cas_inline, cas_staged, modes = run_async(
        flow()
    )
    assert inline_result == staged_result == 50_000
    assert modes == ("rpc", "rpc")
    # Inline arm ships only the function payload; the staged arm ships the
    # args artifact too — proof the CAS road was actually taken.
    assert cas_staged == cas_inline + 1


# ---------------------------------------------------------------------------
# Registry lifecycle
# ---------------------------------------------------------------------------


def test_rpc_reregisters_after_agent_restart(tmp_path, run_async):
    """A restarted resident runtime lost its in-process registry: the
    per-connection registered-set is bound to the client object, so the
    next dispatch re-registers instead of invoking into a void."""

    async def flow():
        ex = make_rpc_executor(tmp_path)
        misses0 = counter_value(
            "covalent_tpu_rpc_registrations_total", result="miss"
        )
        try:
            assert await ex.run(
                square, [3], {}, {"dispatch_id": "a", "node_id": 0}
            ) == 9
            first_client = ex._agents.get("localhost")
            # Kill the pool server out from under the executor: the next
            # run's lease pings the cached client, fails, and rebuilds.
            first_client._process._proc.kill()
            assert await ex.run(
                square, [4], {}, {"dispatch_id": "a2", "node_id": 0}
            ) == 16
            second_client = ex._agents.get("localhost")
            misses = counter_value(
                "covalent_tpu_rpc_registrations_total", result="miss"
            ) - misses0
            counts = dict(ex._fn_registry.counts())
        finally:
            await ex.close()
        return first_client is not second_client, misses, counts

    restarted, misses, counts = run_async(flow())
    assert restarted
    assert misses == 2  # registered once per runtime generation
    assert list(counts.values()) == [1]  # no stale duplicates


def test_rpc_registry_evicted_when_connection_discarded(tmp_path, run_async):
    async def flow():
        ex = make_rpc_executor(tmp_path)
        try:
            await ex.run(square, [2], {}, {"dispatch_id": "d", "node_id": 0})
            before = ex.rpc_digest_count()
            await ex._discard_workers()
            after = ex.rpc_digest_count()
        finally:
            await ex.close()
        return before, after

    before, after = run_async(flow())
    assert before == 1
    assert after == 0


def test_rpc_digest_mismatch_is_permanent(tmp_path, run_async):
    """A CAS artifact whose bytes don't match the registered digest is a
    torn payload: the runtime refuses it and the classifier reads the
    refusal as PERMANENT — no gang retries on deterministic corruption."""

    async def flow():
        conn = LocalTransport()
        client = await start_pool_server(
            conn, str(tmp_path), sys.executable
        )
        try:
            artifact = tmp_path / "payload.pkl"
            artifact.write_bytes(cloudpickle.dumps(square))
            wrong_digest = bytes_digest(b"entirely different bytes")
            with pytest.raises(AgentError) as excinfo:
                await client.register_fn(wrong_digest, str(artifact))
        finally:
            await client.close()
            await conn.close()
        return excinfo.value

    error = run_async(flow())
    fault, label = classify_error(error)
    assert fault is FaultClass.PERMANENT
    assert label == "rpc_digest_mismatch"


def test_pool_server_invoke_roundtrip(tmp_path, run_async):
    """Protocol-level register + invoke against the real pool server."""

    async def flow():
        conn = LocalTransport()
        client = await start_pool_server(
            conn, str(tmp_path), sys.executable
        )
        try:
            payload = cloudpickle.dumps(square)
            digest = bytes_digest(payload)
            artifact = tmp_path / f"{digest}.pkl"
            artifact.write_bytes(payload)
            await client.register_fn(digest, str(artifact))
            args_b64 = base64.b64encode(
                cloudpickle.dumps(((6,), {}))
            ).decode("ascii")
            pid = await client.invoke(
                "op-1", digest, spec={"operation_id": "op-1"},
                args_b64=args_b64,
            )
            event = await client.wait_result("op-1", timeout=30.0)
            result, exception = TPUExecutor._decode_rpc_result(event)
        finally:
            await client.close()
            await conn.close()
        return pid, event.get("ok"), result, exception

    pid, ok, result, exception = run_async(flow())
    assert isinstance(pid, int)
    assert ok is True and exception is None
    assert result == 36


# ---------------------------------------------------------------------------
# Resilience
# ---------------------------------------------------------------------------


def test_rpc_dead_resident_worker_is_transient_and_retried(
    tmp_path, run_async
):
    """Kill the resident worker mid-invoke: classified transient
    (``rpc_channel``), the gang torn down, and the retry completes."""

    def slow(i):
        import time

        time.sleep(3.0)
        return i * 3

    async def flow():
        ex = make_rpc_executor(
            tmp_path, max_task_retries=2,
            retry_base_delay=0.05, retry_max_delay=0.2,
        )
        retries0 = counter_value(
            "covalent_tpu_task_retries_total", reason="rpc_channel"
        )
        fallbacks0 = counter_value(
            "covalent_tpu_tasks_total", outcome="fallback_local"
        )
        try:
            task = asyncio.ensure_future(ex.run(
                slow, [5], {}, {"dispatch_id": "kill", "node_id": 0}
            ))
            for _ in range(300):
                state = ex._op_status.get("kill_0", {})
                if state.get("stage") == "executing":
                    break
                await asyncio.sleep(0.05)
            assert state.get("mode") == "rpc", state
            ex._agents["localhost"]._process._proc.kill()
            result = await task
            attempts = ex.last_attempts
        finally:
            await ex.close()
        return (
            result, attempts,
            counter_value(
                "covalent_tpu_task_retries_total", reason="rpc_channel"
            ) - retries0,
            counter_value(
                "covalent_tpu_tasks_total", outcome="fallback_local"
            ) - fallbacks0,
        )

    result, attempts, retries, fallbacks = run_async(flow())
    assert result == 15
    assert attempts >= 2
    assert retries >= 1
    assert fallbacks == 0  # recovered remotely, never the local CPU re-run


def test_rpc_unavailable_runtime_falls_back_to_launch(tmp_path, run_async):
    """No resident pool runtime on the gang: the same attempt re-runs
    through the launch path (the ISSUE's missing-agent fallback)."""

    async def flow():
        from covalent_tpu_plugin import tpu as tpu_mod

        ex = make_rpc_executor(tmp_path)

        async def no_pool(*args, **kwargs):
            raise AgentError("scripted: no pool runtime")

        original = tpu_mod.start_pool_server
        tpu_mod.start_pool_server = no_pool
        try:
            result = await ex.run(
                square, [9], {}, {"dispatch_id": "fb", "node_id": 0}
            )
            mode = ex.last_dispatch_mode
        finally:
            tpu_mod.start_pool_server = original
            await ex.close()
        return result, mode

    result, mode = run_async(flow())
    assert result == 81
    assert mode == "launch"


def test_rpc_preselect_static_fallbacks(tmp_path):
    """Shapes RPC mode cannot serve route to launch before any attempt."""
    ex = make_rpc_executor(tmp_path / "base", dispatch_mode="auto")
    assert ex._rpc_preselect({}) is True
    assert ex._rpc_preselect({"dispatch_mode": "launch"}) is False
    assert ex._rpc_preselect({"pip_deps": ["torch"]}) is False

    pod = make_rpc_executor(
        tmp_path / "pod", dispatch_mode="auto", workers=["w1", "w2"]
    )
    assert pod._rpc_preselect({}) is False  # multi-worker gangs launch

    no_agent = make_rpc_executor(
        tmp_path / "na", dispatch_mode="auto", use_agent=False
    )
    assert no_agent._rpc_preselect({}) is False

    from covalent_tpu_plugin.transport import ChaosPlan

    chaotic = make_rpc_executor(
        tmp_path / "ch", dispatch_mode="auto", chaos=ChaosPlan(delay=0.01)
    )
    assert chaotic._rpc_preselect({}) is False  # auto defers under chaos
    assert chaotic._rpc_preselect({"dispatch_mode": "rpc"}) is True  # pin wins


# ---------------------------------------------------------------------------
# Leak audit (satellite): per-task state drops on every exit path
# ---------------------------------------------------------------------------


def client_books(client) -> dict:
    return {
        "started": dict(client._started),
        "exits": dict(client._exits),
        "errors": dict(client._errors),
        "results": dict(client._results),
        "telemetry_seq": dict(client._telemetry_seq),
    }


def test_agent_client_state_dropped_on_every_exit_path(tmp_path, run_async):
    """Watch state, seq-dedup maps, and result buffers for finished tasks
    must be empty after success, remote exception, AND a mid-task kill —
    a resident client serves many electrons and must not accumulate."""

    def boom():
        raise ValueError("audit-boom")

    def sleeper():
        import time

        time.sleep(30)
        return "never"

    async def flow():
        ex = make_rpc_executor(
            tmp_path, heartbeat_interval=0.2, task_timeout=60.0
        )
        try:
            # Success path (heartbeats on: telemetry seq map exercised).
            await ex.run(square, [2], {}, {"dispatch_id": "ok", "node_id": 0})
            # Remote-exception path.
            with pytest.raises(ValueError):
                await ex.run(boom, [], {}, {"dispatch_id": "ex", "node_id": 0})
            # Cancel path: a task killed mid-flight.  Capture the client
            # BEFORE cancelling — cancel tears the resident runtime down
            # (the only way to stop an in-process invocation), so the
            # executor's agent map no longer holds it afterwards.
            task = asyncio.ensure_future(ex.run(
                sleeper, [], {}, {"dispatch_id": "cancel", "node_id": 0}
            ))
            for _ in range(300):
                if ex._op_status.get("cancel_0", {}).get("stage") == "executing":
                    break
                await asyncio.sleep(0.05)
            client = ex._agents.get("localhost")
            await ex.cancel("cancel_0")
            with pytest.raises(asyncio.CancelledError):
                await asyncio.wait_for(task, 30.0)
            # The cancelled invocation's runtime was actually dropped —
            # the user function must not keep burning the shared
            # interpreter after run() returned cancelled.
            assert ex._agents.get("localhost") is not client
            # Launch path through the same client (pool run + watch).
            launch_ex = make_rpc_executor(
                tmp_path / "launch2", dispatch_mode="launch",
                heartbeat_interval=0.2,
            )
            try:
                await launch_ex.run(
                    square, [3], {}, {"dispatch_id": "lw", "node_id": 0}
                )
                launch_client = launch_ex._agents.get("localhost")
                launch_books = client_books(launch_client)
            finally:
                await launch_ex.close()
            books = client_books(client)
        finally:
            await ex.close()
        return books, launch_books

    books, launch_books = run_async(flow())
    for name, mapping in {**books, **launch_books}.items():
        assert not mapping, f"leaked {name}: {mapping}"


def test_agent_client_forget_clears_rpc_state_after_channel_death(
    tmp_path, run_async
):
    """Channel death leaves stored per-task state; forget() must drop it
    even though no waiter consumed the events."""

    async def flow():
        conn = LocalTransport()
        client = await start_pool_server(
            conn, str(tmp_path), sys.executable
        )
        try:
            payload = cloudpickle.dumps(square)
            digest = bytes_digest(payload)
            artifact = tmp_path / f"{digest}.pkl"
            artifact.write_bytes(payload)
            await client.register_fn(digest, str(artifact))
            args_b64 = base64.b64encode(
                cloudpickle.dumps(((2,), {}))
            ).decode("ascii")
            await client.invoke("dead-op", digest, args_b64=args_b64)
            # Result arrives and is buffered; nobody waits for it.
            for _ in range(100):
                if "dead-op" in client._results:
                    break
                await asyncio.sleep(0.05)
            assert "dead-op" in client._results
            client._process._proc.kill()
            for _ in range(100):
                if not client.alive:
                    break
                await asyncio.sleep(0.05)
            client.forget("dead-op")
            books = client_books(client)
        finally:
            await client.close()
            await conn.close()
        return books

    books = run_async(flow())
    for name, mapping in books.items():
        assert not mapping, f"leaked {name} after channel death: {mapping}"


# ---------------------------------------------------------------------------
# Fleet placement affinity
# ---------------------------------------------------------------------------


def test_scheduler_prefers_pool_holding_fn_digest(run_async):
    """Digest affinity beats the bin-pack most-free tiebreak: the pool
    whose gang already registered the electron's function wins placement
    even against an emptier equally-warm pool."""
    from covalent_tpu_plugin.fleet.pools import PoolRegistry, PoolSpec
    from covalent_tpu_plugin.fleet.queue import WorkItem
    from covalent_tpu_plugin.fleet.scheduler import FleetScheduler

    fn_digest = bytes_digest(cloudpickle.dumps(square))

    class HoldingStub:
        def __init__(self, holds):
            self._holds = holds
            self.is_warm = True

        def gang_state(self):
            return {"warm": True, "breakers": {}}

        def rpc_digest_count(self):
            return 1 if self._holds else 0

        def holds_fn_digest(self, digest):
            return self._holds and digest == fn_digest

        async def run(self, fn, args, kwargs, task_metadata):
            return fn(*args, **kwargs)

        async def close(self):
            pass

    registry = PoolRegistry()
    # "empty" has MORE free slots; "holder" holds the digest.
    registry.register(
        PoolSpec(name="empty", capacity=4, transport="local"),
        executor=HoldingStub(holds=False),
    )
    registry.register(
        PoolSpec(name="holder", capacity=2, transport="local"),
        executor=HoldingStub(holds=True),
    )
    scheduler = FleetScheduler(registry)
    item = WorkItem(
        fn=square, args=(2,), kwargs={},
        task_metadata={"dispatch_id": "aff", "node_id": 0},
    )
    pool, rerouted = scheduler._select_pool(item)
    assert pool.name == "holder"
    assert rerouted is False

    # Without affinity the emptier pool wins, proving the key ordering.
    other = WorkItem(
        fn=len, args=("x",), kwargs={},
        task_metadata={"dispatch_id": "no", "node_id": 0},
    )
    pool, _ = scheduler._select_pool(other)
    assert pool.name == "empty"


def test_pool_status_reports_digests_and_dispatch_modes(tmp_path, run_async):
    """The fleet ``/status`` pool view carries the RPC dispatch state:
    how many function digests the gang's resident runtimes hold, and the
    dispatch mode of each in-flight electron."""
    from covalent_tpu_plugin.fleet.pools import PoolRegistry, PoolSpec

    async def flow():
        ex = make_rpc_executor(tmp_path)
        registry = PoolRegistry()
        registry.register(
            PoolSpec(name="p", capacity=2, transport="local"), executor=ex
        )
        pool = registry.get("p")
        try:
            cold = pool.status()
            await ex.run(square, [3], {}, {"dispatch_id": "st", "node_id": 0})
            # Freeze an in-flight view mid-run by reading the live books
            # right after seeding one op status entry ourselves.
            ex._op_status["st_9"] = {"stage": "executing", "mode": "rpc"}
            warm = pool.status()
            modes = dict(ex.in_flight_modes())
        finally:
            ex._op_status.pop("st_9", None)
            await ex.close()
        return cold, warm, modes

    cold, warm, modes = run_async(flow())
    assert cold["registered_digests"] == 0
    assert warm["registered_digests"] == 1
    assert warm["in_flight_modes"] == {"st_9": "rpc"}
    assert modes == {"st_9": "rpc"}
