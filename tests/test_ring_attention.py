"""Ring attention on the 8-device CPU mesh: sequence parallelism must be
numerically transparent — identical to dense attention on the gathered
arrays — and differentiable for training."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from covalent_tpu_plugin.ops import mha_reference, ring_attention
from covalent_tpu_plugin.ops.ring_attention import sequence_parallel_attention
from covalent_tpu_plugin.parallel import MeshPlan, make_mesh


@pytest.fixture(scope="module")
def seq_mesh():
    return make_mesh(MeshPlan(seq=8))


@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_reference(seq_mesh, causal):
    q, k, v = (
        jax.random.normal(jax.random.PRNGKey(i), (2, 2, 64, 16))
        for i in range(3)
    )
    out = sequence_parallel_attention(q, k, v, seq_mesh, causal=causal)
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)


def test_ring_composes_with_data_and_tensor_axes():
    mesh = make_mesh(MeshPlan(data=2, tensor=2, seq=2))
    q, k, v = (
        jax.random.normal(jax.random.PRNGKey(10 + i), (4, 2, 32, 16))
        for i in range(3)
    )
    out = sequence_parallel_attention(q, k, v, mesh, causal=True)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)


def test_ring_gradients(seq_mesh):
    q, k, v = (
        jax.random.normal(jax.random.PRNGKey(20 + i), (1, 2, 32, 8))
        for i in range(3)
    )

    def loss_ring(q, k, v):
        return sequence_parallel_attention(q, k, v, seq_mesh, causal=True).sum()

    def loss_ref(q, k, v):
        return mha_reference(q, k, v, causal=True).sum()

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4)


def test_ring_single_device_degenerates():
    """seq=1 mesh: ring of one hop must equal plain attention."""
    mesh = make_mesh(MeshPlan(data=8))
    q, k, v = (
        jax.random.normal(jax.random.PRNGKey(30 + i), (8, 2, 16, 8))
        for i in range(3)
    )
    out = sequence_parallel_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(mha_reference(q, k, v)), atol=1e-5, rtol=1e-5
    )


def test_stripe_roundtrip_and_layout():
    from covalent_tpu_plugin.ops.ring_attention import (
        stripe_sequence,
        unstripe_sequence,
    )

    x = jnp.arange(16.0).reshape(1, 1, 16, 1)
    striped = stripe_sequence(x, n=4)
    # Device 0's shard = stripes 0 and 7: positions 0,1 and 14,15.
    assert striped[0, 0, :4, 0].tolist() == [0.0, 1.0, 14.0, 15.0]
    roundtrip = unstripe_sequence(striped, n=4)
    assert jnp.array_equal(roundtrip, x)


def test_zigzag_and_contiguous_agree(seq_mesh):
    q, k, v = (
        jax.random.normal(jax.random.PRNGKey(i), (2, 2, 64, 16))
        for i in range(3)
    )
    zz = sequence_parallel_attention(q, k, v, seq_mesh, causal=True, zigzag=True)
    contiguous = sequence_parallel_attention(
        q, k, v, seq_mesh, causal=True, zigzag=False
    )
    ref = mha_reference(q, k, v, causal=True)
    assert jnp.allclose(zz, ref, atol=2e-5)
    assert jnp.allclose(contiguous, ref, atol=2e-5)
    assert jnp.allclose(zz, contiguous, atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("zigzag", [False, True])
def test_ring_flash_matches_reference(seq_mesh, causal, zigzag):
    """impl='flash': the Pallas kernels handle each block pair (interpret
    mode on this tier), merged by log-sum-exp — must equal dense attention
    on the gathered arrays in every (causal, zigzag) combination."""
    if zigzag and not causal:
        pytest.skip("zigzag striping only applies to causal masking")
    q, k, v = (
        jax.random.normal(jax.random.PRNGKey(40 + i), (1, 2, 128, 16))
        for i in range(3)
    )
    out = sequence_parallel_attention(
        q, k, v, seq_mesh, causal=causal, zigzag=zigzag, impl="flash"
    )
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_ring_flash_gradients(seq_mesh):
    """The second ring pass (Pallas backward per block with global lse and
    delta) must reproduce dense-attention gradients."""
    q, k, v = (
        jax.random.normal(jax.random.PRNGKey(50 + i), (1, 2, 64, 8))
        for i in range(3)
    )

    def loss_flash(q, k, v):
        return (
            sequence_parallel_attention(
                q, k, v, seq_mesh, causal=True, impl="flash"
            ) * 0.1
        ).sum()

    def loss_ref(q, k, v):
        return (mha_reference(q, k, v, causal=True) * 0.1).sum()

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4)


def test_ring_flash_gradients_zigzag(seq_mesh):
    q, k, v = (
        jax.random.normal(jax.random.PRNGKey(60 + i), (1, 2, 64, 8))
        for i in range(3)
    )

    def loss(impl):
        def fn(q, k, v):
            return (
                sequence_parallel_attention(
                    q, k, v, seq_mesh, causal=True, zigzag=True, impl=impl
                ) * 0.1
            ).sum()
        return fn

    g_flash = jax.grad(loss("flash"), argnums=(0, 1, 2))(q, k, v)
    g_ein = jax.grad(loss("einsum"), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ein):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4)


def test_ring_steps_truncation():
    """The banded ring's hop count: own shard + ceil((w-1)/L) predecessors,
    never more than n; zigzag and unwindowed keep the full ring."""
    from covalent_tpu_plugin.ops.ring_attention import _ring_steps

    assert _ring_steps(8, 64, None, False) == 8       # no window: full ring
    assert _ring_steps(8, 64, 64, True) == 8          # zigzag: full ring
    assert _ring_steps(8, 64, 1, False) == 1          # w=1: own shard only
    assert _ring_steps(8, 64, 64, False) == 2         # w=L: one predecessor
    assert _ring_steps(8, 64, 65, False) == 2
    assert _ring_steps(8, 64, 128, False) == 3
    assert _ring_steps(8, 64, 10_000, False) == 8     # clamped at n


@pytest.mark.parametrize("impl", ["einsum", "flash"])
@pytest.mark.parametrize("window", [1, 16, 100, 400])
def test_windowed_ring_matches_reference(seq_mesh, impl, window):
    """Banded ring (contiguous default layout + truncated scan) must equal
    the dense windowed oracle at windows inside one shard, spanning
    shards, and wider than the sequence."""
    q, k, v = (
        jax.random.normal(jax.random.PRNGKey(70 + i), (1, 2, 128, 16))
        for i in range(3)
    )
    out = sequence_parallel_attention(
        q, k, v, seq_mesh, causal=True, impl=impl, window=window
    )
    ref = mha_reference(q, k, v, causal=True, window=window)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


@pytest.mark.parametrize("impl", ["einsum", "flash"])
def test_windowed_ring_zigzag_matches_reference(seq_mesh, impl):
    """Explicit zigzag still composes with the window (full ring, exact
    position masking)."""
    q, k, v = (
        jax.random.normal(jax.random.PRNGKey(80 + i), (1, 2, 128, 16))
        for i in range(3)
    )
    out = sequence_parallel_attention(
        q, k, v, seq_mesh, causal=True, impl=impl, window=40, zigzag=True
    )
    ref = mha_reference(q, k, v, causal=True, window=40)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


def test_windowed_ring_gradients(seq_mesh):
    """Truncated-ring backward: dk/dv partials must land back on their home
    shards (the re-homing ppermute) and match dense windowed grads."""
    q, k, v = (
        jax.random.normal(jax.random.PRNGKey(90 + i), (1, 2, 64, 8))
        for i in range(3)
    )

    for impl in ("einsum", "flash"):
        def loss_ring(q, k, v):
            return (
                sequence_parallel_attention(
                    q, k, v, seq_mesh, causal=True, impl=impl, window=20
                ) * 0.1
            ).sum()

        def loss_ref(q, k, v):
            return (mha_reference(q, k, v, causal=True, window=20) * 0.1).sum()

        g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ring, g_ref):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4
            )


def test_windowed_ring_rejects_noncausal(seq_mesh):
    q, k, v = (
        jax.random.normal(jax.random.PRNGKey(i), (1, 2, 64, 16))
        for i in range(3)
    )
    with pytest.raises(ValueError, match="requires causal"):
        sequence_parallel_attention(
            q, k, v, seq_mesh, causal=False, window=8
        )


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_matches_reference(seq_mesh, causal):
    """impl='ulysses': two all-to-alls + full-sequence local flash must
    equal dense attention on the gathered arrays."""
    q, k, v = (
        jax.random.normal(jax.random.PRNGKey(100 + i), (2, 8, 64, 16))
        for i in range(3)
    )
    out = sequence_parallel_attention(
        q, k, v, seq_mesh, causal=causal, impl="ulysses"
    )
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


def test_ulysses_windowed_with_sinks_matches_reference(seq_mesh):
    """The sinks x sequence-parallelism path the ring cannot offer."""
    q, k, v = (
        jax.random.normal(jax.random.PRNGKey(110 + i), (1, 8, 128, 16))
        for i in range(3)
    )
    out = sequence_parallel_attention(
        q, k, v, seq_mesh, causal=True, impl="ulysses", window=24, sinks=3
    )
    ref = mha_reference(q, k, v, causal=True, window=24, sinks=3)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )
    # The ring impls refuse sinks with a pointer to ulysses.
    with pytest.raises(ValueError, match="ulysses"):
        sequence_parallel_attention(
            q, k, v, seq_mesh, causal=True, impl="flash", window=24, sinks=3
        )


def test_ulysses_gqa_and_gradients(seq_mesh):
    """GQA kv repeat inside the swap + autodiff through both all-to-alls."""
    ks = jax.random.split(jax.random.PRNGKey(120), 3)
    q = jax.random.normal(ks[0], (1, 8, 64, 8))
    k = jax.random.normal(ks[1], (1, 2, 64, 8))
    v = jax.random.normal(ks[2], (1, 2, 64, 8))
    out = sequence_parallel_attention(
        q, k, v, seq_mesh, causal=True, impl="ulysses"
    )
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )

    def loss_ulysses(q, k, v):
        return (
            sequence_parallel_attention(
                q, k, v, seq_mesh, causal=True, impl="ulysses"
            ) * 0.1
        ).sum()

    def loss_ref(q, k, v):
        return (mha_reference(q, k, v, causal=True) * 0.1).sum()

    g_u = jax.grad(loss_ulysses, argnums=(0, 1, 2))(q, k, v)
    g_r = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_u, g_r):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4
        )


def test_ulysses_rejects_indivisible_heads(seq_mesh):
    q, k, v = (
        jax.random.normal(jax.random.PRNGKey(i), (1, 6, 64, 8))
        for i in range(3)
    )
    with pytest.raises(ValueError, match="divisible"):
        sequence_parallel_attention(
            q, k, v, seq_mesh, causal=True, impl="ulysses"
        )


def test_ulysses_model_forward():
    """attention='ulysses' at the model level, windowed + sinks."""
    import dataclasses

    from covalent_tpu_plugin.models import TransformerConfig, TransformerLM

    mesh = make_mesh(MeshPlan(seq=8))
    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=8, d_ff=64,
        max_seq=32, dtype=jnp.float32, attention="ulysses", mesh=mesh,
        sliding_window=6, attention_sinks=2,
    )
    model = TransformerLM(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(9), (2, 16), 0, 64)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    ref_model = TransformerLM(
        dataclasses.replace(cfg, attention="reference", mesh=None)
    )
    got = model.apply({"params": params}, tokens)
    want = ref_model.apply({"params": params}, tokens)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-4, rtol=2e-4
    )


def test_zigzag_rejects_indivisible_seq(seq_mesh):
    q, k, v = (
        jax.random.normal(jax.random.PRNGKey(i), (1, 2, 24, 16))
        for i in range(3)
    )
    with pytest.raises(ValueError, match="divisible by 2"):
        sequence_parallel_attention(q, k, v, seq_mesh, causal=True, zigzag=True)
