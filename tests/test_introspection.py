"""Performance-introspection plane: metrics exposition edges, the history
ring's downsampling + windowed queries under a fake clock, SLO burn-rate
evaluation and transition events, the failure flight recorder, the new ops
routes (/history, /slo, /tasks, POST /profile), serving stale-series reap,
and resident-mode profiling end to end (ISSUE 10 acceptance)."""

from __future__ import annotations

import json
import os
import sys
import tarfile
import urllib.request

import pytest

from covalent_tpu_plugin.obs import events as obs_events
from covalent_tpu_plugin.obs.flightrec import FlightRecorder, base_operation_id
from covalent_tpu_plugin.obs.history import MetricsHistory
from covalent_tpu_plugin.obs.metrics import Registry
from covalent_tpu_plugin.obs.slo import SLOEngine, SLOSpec, load_slo_specs


class FakeClock:
    def __init__(self, start: float = 1000.0) -> None:
        self.now = start

    def __call__(self) -> float:
        return self.now

    def tick(self, dt: float = 1.0) -> None:
        self.now += dt


# --------------------------------------------------------------------- #
# Metrics exposition edges (satellite)
# --------------------------------------------------------------------- #


def test_prometheus_label_value_escaping():
    reg = Registry()
    c = reg.counter("edges_total", "edge cases", ("path",))
    c.labels(path='a"b\\c\nd').inc()
    text = reg.prometheus_text()
    # Quote, backslash and newline must all be escaped per the text
    # format, or one weird label value corrupts the whole scrape.
    assert 'path="a\\"b\\\\c\\nd"' in text
    assert "\nd" not in text.split("edges_total{")[1].split("}")[0]


def test_prometheus_inf_bucket_is_last_and_cumulative():
    reg = Registry()
    h = reg.histogram("lat_seconds", "latency", buckets=(0.1, 1.0))
    for value in (0.05, 0.5, 5.0):
        h.observe(value)
    lines = [
        line for line in reg.prometheus_text().splitlines()
        if line.startswith("lat_seconds_bucket")
    ]
    assert [line.split(" ")[-1] for line in lines] == ["1", "2", "3"]
    assert 'le="+Inf"' in lines[-1]  # +Inf closes the family, count = total
    assert 'le="0.1"' in lines[0]


def test_remove_then_relabel_starts_fresh():
    reg = Registry()
    g = reg.gauge("depth", "queue depth", ("q",))
    g.labels(q="a").set(7)
    g.remove(q="a")
    assert 'q="a"' not in reg.prometheus_text()
    # Re-creating the same series starts at zero, not the removed value.
    assert g.labels(q="a").value == 0.0
    g.remove(q="never-existed")  # absent series: no-op, no raise
    with pytest.raises(ValueError, match="expected labels"):
        g.remove(wrong="a")


# --------------------------------------------------------------------- #
# Metrics history: ring, downsampling, windowed queries
# --------------------------------------------------------------------- #


def make_history(capacity: int = 16):
    clock = FakeClock()
    reg = Registry()
    hist = MetricsHistory(
        registry=reg, interval_s=1.0, capacity=capacity, clock=clock
    )
    return hist, reg, clock


def test_history_downsamples_and_bounds_memory():
    hist, reg, clock = make_history(capacity=16)
    reg.counter("ticks_total").inc()
    for _ in range(100):
        clock.tick(1.0)
        hist.sample()
    # Bounded forever: the ring never exceeds its capacity, the stride
    # doubles on each compaction, and the observable span keeps growing.
    assert len(hist) <= 16
    assert hist.stride > 1
    assert hist.span_s() > 16  # covers more wall-clock than capacity*1s


def test_history_counter_window_rate():
    hist, reg, clock = make_history()
    c = reg.counter("reqs_total", "", ("code",))
    c.labels(code="200").inc(5)
    hist.sample(force=True)
    for _ in range(10):
        clock.tick(1.0)
        c.labels(code="200").inc(2)
        hist.sample(force=True)
    q = hist.query("reqs_total", window_s=5.0)
    assert q["kind"] == "counter"
    stats = q["series"][json.dumps({"code": "200"})]
    assert stats["increase"] == pytest.approx(10.0)  # 5 in-window ticks x 2
    assert stats["rate_per_s"] == pytest.approx(2.0)


def test_history_series_born_mid_window_counts_from_zero():
    hist, reg, clock = make_history()
    hist.sample(force=True)  # window baseline BEFORE the series exists
    clock.tick(1.0)
    c = reg.counter("late_total")
    c.inc(16)  # all observations land between two ticks
    hist.sample(force=True)
    q = hist.query("late_total", window_s=60.0)
    # A cumulative series starts at zero when created: its first captured
    # value must count as increase, not vanish into the baseline.
    assert q["series"][""]["increase"] == pytest.approx(16.0)
    h = reg.histogram("late_seconds", buckets=(0.1, 1.0))
    for _ in range(8):
        h.observe(0.05)
    clock.tick(1.0)
    hist.sample(force=True)
    hq = hist.query("late_seconds", window_s=60.0)
    assert hq["series"][""]["count"] == 8


def test_history_histogram_window_percentiles():
    hist, reg, clock = make_history()
    h = reg.histogram("lat_seconds", buckets=(0.01, 0.1, 1.0))
    for _ in range(99):
        h.observe(0.005)  # old traffic: fast
    hist.sample(force=True)
    clock.tick(100.0)  # push the old sample out of the window
    hist.sample(force=True)
    for _ in range(10):
        h.observe(0.5)  # the window's traffic: slow
    clock.tick(1.0)
    hist.sample(force=True)
    q = hist.query("lat_seconds", window_s=10.0)
    stats = q["series"][""]
    # Windowed, not lifetime: the 99 fast lifetime observations must not
    # drown the window's 10 slow ones.
    assert stats["count"] == 10
    assert stats["p50"] == pytest.approx(1.0)  # upper-bound bucket estimate


def test_history_gauge_timeline_and_describe():
    hist, reg, clock = make_history()
    g = reg.gauge("depth")
    for value in (1, 5, 3):
        g.set(value)
        clock.tick(1.0)
        hist.sample(force=True)
    q = hist.query("depth", window_s=60.0)
    stats = q["series"][""]
    assert [point[1] for point in stats["points"]] == [1.0, 5.0, 3.0]
    assert stats["min"] == 1.0 and stats["max"] == 5.0 and stats["last"] == 3.0
    described = hist.describe()
    assert described["samples"] == 3
    assert "depth" in described["metrics"]
    assert hist.query("no_such_metric", window_s=60.0)["samples"] >= 0


def test_history_good_fraction_latency_sli():
    hist, reg, clock = make_history()
    h = reg.histogram("lat_seconds", buckets=(0.1, 1.0, 5.0))
    hist.sample(force=True)
    for _ in range(9):
        h.observe(0.05)
    h.observe(3.0)  # one slow outlier
    clock.tick(1.0)
    hist.sample(force=True)
    count, good = hist.good_fraction("lat_seconds", 0.1, window_s=60.0)
    assert count == 10
    assert good == pytest.approx(0.9)


def test_history_bad_ratio_denominatorless_is_tick_normalized():
    """An empty ``bad`` spec ("this counter should not move at all")
    normalizes by the window's elapsed sample ticks — one lone increment
    in a wide window is a small rate, not an instantly-saturated burn."""
    hist, reg, clock = make_history()
    c = reg.counter("retries_total")
    hist.sample(force=True)
    for _ in range(10):
        clock.tick(1.0)
        hist.sample(force=True)
    c.inc()  # one lone retry in the whole window
    clock.tick(1.0)
    hist.sample(force=True)
    total, frac = hist.bad_ratio("retries_total", None, window_s=60.0)
    assert total == 1.0
    assert frac == pytest.approx(1.0 / 11.0)


def test_ensure_history_tightens_interval_while_running():
    from covalent_tpu_plugin.obs import history as hist_mod

    ring = hist_mod.ensure_history(1.0)
    prev = ring.interval_s
    try:
        assert hist_mod.ensure_history(0.25) is ring
        assert ring.interval_s == 0.25  # explicit finer interval wins
        hist_mod.ensure_history(5.0)  # coarsening is ignored
        assert ring.interval_s == 0.25
    finally:
        ring.interval_s = prev


def test_history_good_fraction_threshold_above_every_bucket():
    """A threshold beyond the largest finite bound snaps to +Inf: the
    buckets cannot observe a violation there, so observations landing
    past the last finite bound must count GOOD — counting them bad pages
    on a service that is meeting its objective."""
    hist, reg, clock = make_history()
    h = reg.histogram("lat_seconds", buckets=(0.1, 1.0, 5.0))
    hist.sample(force=True)
    for _ in range(5):
        h.observe(7.0)  # past every finite bound, under the threshold
    clock.tick(1.0)
    hist.sample(force=True)
    count, good = hist.good_fraction("lat_seconds", 600.0, window_s=60.0)
    assert count == 5
    assert good == pytest.approx(1.0)


# --------------------------------------------------------------------- #
# SLO engine
# --------------------------------------------------------------------- #


def test_slo_spec_layering_and_validation(monkeypatch):
    defaults = {spec.name for spec in load_slo_specs(env="")}
    assert {"serve_p95_latency", "serve_ttft", "task_error_rate",
            "dispatch_overhead"} <= defaults
    assert load_slo_specs(env="off") == []
    overridden = load_slo_specs(env=json.dumps([
        {"name": "serve_p95_latency", "metric": "covalent_tpu_serve_request_seconds",
         "kind": "latency", "threshold_s": 0.5, "objective": 0.9},
        {"name": "serve_ttft", "disabled": True},
        {"name": "custom", "metric": "m", "kind": "ratio", "objective": 0.5},
    ]))
    by_name = {spec.name: spec for spec in overridden}
    assert by_name["serve_p95_latency"].threshold_s == 0.5
    assert "serve_ttft" not in by_name
    assert "custom" in by_name
    # A PARTIAL override tunes the same-name default field-level; a
    # whole-spec replace would drop the required fields and silently
    # delete the SLO at from_dict time.
    partial = {
        spec.name: spec for spec in load_slo_specs(
            env=json.dumps([{"name": "serve_ttft", "threshold_s": 2.0}])
        )
    }
    assert partial["serve_ttft"].threshold_s == 2.0
    assert partial["serve_ttft"].metric  # inherited from the default
    # Malformed layers are skipped, never fatal.
    assert load_slo_specs(env="not json[") and load_slo_specs(env='[{"no":1}]')
    with pytest.raises(ValueError, match="objective"):
        SLOSpec(name="bad", metric="m", kind="latency", threshold_s=1,
                objective=1.5)
    with pytest.raises(ValueError, match="unknown SLO spec field"):
        SLOSpec.from_dict({"name": "x", "metric": "m", "typo": 1})


def burn_setup(threshold_s: float = 0.1):
    hist, reg, clock = make_history()
    h = reg.histogram("lat_seconds", buckets=(0.1, 1.0, 5.0))
    spec = SLOSpec(
        name="lat_p95", metric="lat_seconds", kind="latency",
        threshold_s=threshold_s, objective=0.95, windows=(5.0, 30.0),
    )
    engine = SLOEngine(hist, specs=[spec])
    return hist, reg, clock, h, engine


def test_slo_burn_fires_and_recovers():
    hist, reg, clock, h, engine = burn_setup()
    events: list[dict] = []
    hooks: list[tuple] = []
    engine.add_alert_hook(lambda name, state, info: hooks.append((name, state)))
    listener = events.append
    obs_events.add_listener(listener)
    try:
        # Healthy traffic: under threshold, no burn.
        for _ in range(3):
            for _ in range(10):
                h.observe(0.05)
            clock.tick(1.0)
            hist.sample(force=True)
        view = engine.evaluate()
        assert view["slos"]["lat_p95"]["state"] == "ok"
        # Latency regression: everything lands over the threshold; burn
        # must exceed 1 in every window and fire ONE slo.burn.
        for _ in range(6):
            for _ in range(10):
                h.observe(0.5)
            clock.tick(1.0)
            hist.sample(force=True)
        view = engine.evaluate()
        info = view["slos"]["lat_p95"]
        assert info["state"] == "burning"
        assert info["burn_rate"] > 1.0
        engine.evaluate()  # still burning: no duplicate transition
        assert [e["slo"] for e in events if e["type"] == "slo.burn"] == [
            "lat_p95"
        ]
        assert ("lat_p95", "burning") in hooks
        from covalent_tpu_plugin.obs.slo import SLO_BURN_RATE

        assert SLO_BURN_RATE.labels(slo="lat_p95").value > 1.0
        # Recovery: good traffic pushes every window back under threshold.
        for _ in range(40):
            for _ in range(50):
                h.observe(0.05)
            clock.tick(1.0)
            hist.sample(force=True)
        view = engine.evaluate()
        assert view["slos"]["lat_p95"]["state"] == "ok"
        assert [e["slo"] for e in events if e["type"] == "slo.recovered"] == [
            "lat_p95"
        ]
    finally:
        obs_events.remove_listener(listener)


def test_slo_multiwindow_gate_needs_every_window_burning():
    hist, reg, clock, h, engine = burn_setup()
    # A long healthy history, then a 2-second blip: the short window
    # burns, the long one does not — the classic gate holds the alert.
    for _ in range(25):
        for _ in range(20):
            h.observe(0.05)
        clock.tick(1.0)
        hist.sample(force=True)
    for _ in range(2):
        for _ in range(5):
            h.observe(0.5)
        clock.tick(1.0)
        hist.sample(force=True)
    view = engine.evaluate()
    info = view["slos"]["lat_p95"]
    windows = {w["window_s"]: w for w in info["windows"]}
    assert windows[5.0]["burn"] > 1.0
    assert windows[30.0]["burn"] <= 1.0
    assert info["state"] == "ok"


def test_slo_no_data_is_not_a_recovery():
    hist, reg, clock, h, engine = burn_setup()
    for _ in range(6):
        for _ in range(10):
            h.observe(0.5)
        clock.tick(1.0)
        hist.sample(force=True)
    assert engine.evaluate()["slos"]["lat_p95"]["state"] == "burning"
    clock.tick(500.0)  # traffic stops entirely; windows go empty
    hist.sample(force=True)
    view = engine.evaluate()
    assert view["slos"]["lat_p95"]["state"] == "no_data"
    assert engine._states["lat_p95"] == "burning"  # alert NOT cleared


def test_slo_ratio_kind_over_counter_family():
    hist, reg, clock = make_history()
    c = reg.counter("tasks_total", "", ("outcome",))
    spec = SLOSpec(
        name="errors", metric="tasks_total", kind="ratio",
        bad={"outcome": ["failed"]}, objective=0.9, windows=(10.0,),
    )
    engine = SLOEngine(hist, specs=[spec])
    hist.sample(force=True)
    c.labels(outcome="completed").inc(6)
    c.labels(outcome="failed").inc(4)  # 40% bad >> 10% budget
    clock.tick(1.0)
    hist.sample(force=True)
    info = engine.evaluate()["slos"]["errors"]
    assert info["state"] == "burning"
    assert info["burn_rate"] == pytest.approx(4.0)


# --------------------------------------------------------------------- #
# Flight recorder
# --------------------------------------------------------------------- #


def test_flightrec_lineage_truncation_and_eviction():
    rec = FlightRecorder(per_task=4, max_tasks=2)
    assert base_operation_id("d_0.r2") == "d_0"
    rec.record_event({"type": "task.state", "operation_id": "d_0", "n": 1})
    rec.record_event({"type": "task.retry", "operation_id": "d_0.r1", "n": 2})
    view = rec.view("d_0.r3")  # any lineage member resolves the ring
    assert view is not None and view["count"] == 2  # one ring, whole lineage
    rec.record_event({
        "type": "task.failed", "operation_id": "d_0",
        "log_tail": "x" * 10_000,
    })
    stored = rec.view("d_0")["records"][-1]["log_tail"]
    assert len(stored) < 10_000 and stored.endswith("[truncated]")
    for i in range(5):
        rec.record_event({"type": "t", "operation_id": "d_0", "n": i})
    assert rec.view("d_0")["count"] == 4  # per-task ring bound
    rec.record_event({"type": "t", "operation_id": "other_1"})
    rec.record_event({"type": "t", "operation_id": "other_2"})
    assert rec.view("d_0") is None  # LRU across tasks: oldest evicted
    rec.record_event({"type": "t"})  # no operation_id: ignored, no raise


def test_flightrec_stage_records_and_dump(tmp_path):
    rec = FlightRecorder()
    rec.record_stage("d_0", "connecting")
    rec.record_stage("d_0.r1", "launching")
    rec.record_event({"type": "task.failed", "operation_id": "d_0.r1"})
    path = rec.dump_to_file("d_0.r1", "failed", str(tmp_path / "boxes"))
    assert path is not None
    payload = json.loads(open(path).read())
    assert payload["operation_id"] == "d_0"
    assert payload["reason"] == "failed"
    stages = [r["stage"] for r in payload["records"] if r.get("type") == "stage"]
    assert stages == ["connecting", "launching"]
    assert payload["records"][-1]["type"] == "task.failed"
    assert rec.tasks() == {"d_0": 3}
    rec.forget("d_0.r1")
    assert rec.view("d_0") is None


def test_flightrec_disable_honored_at_every_site(tmp_path, monkeypatch):
    """COVALENT_TPU_FLIGHTREC=0 must stop the executor's direct feeding
    (stage records, failure dumps) too, not just the listener wiring."""
    monkeypatch.setenv("COVALENT_TPU_FLIGHTREC", "0")
    rec = FlightRecorder()
    rec.record_stage("op_0", "connecting")
    rec.record_event({"type": "t", "operation_id": "op_0"})
    assert rec.tasks() == {}
    assert rec.dump_to_file("op_0", "failed", str(tmp_path / "boxes")) is None
    assert not (tmp_path / "boxes").exists()


def test_failed_electron_dumps_black_box(tmp_path, run_async):
    """Executor integration: a permanent failure leaves a browsable
    black-box JSON next to the cache, spanning stages and events."""
    from covalent_tpu_plugin.obs.flightrec import ensure_flight_recorder

    from .helpers import make_local_executor

    ensure_flight_recorder()
    executor = make_local_executor(
        tmp_path, run_local_on_dispatch_fail=False, max_task_retries=0
    )

    def exploding():
        raise RuntimeError("user code boom")

    async def flow():
        try:
            with pytest.raises(RuntimeError, match="user code boom"):
                await executor.run(
                    exploding, [], {},
                    {"dispatch_id": "boxed", "node_id": 0},
                )
        finally:
            await executor.close()

    run_async(flow())
    boxes = list((tmp_path / "cache" / "blackbox").glob("blackbox_*.json"))
    assert len(boxes) == 1
    payload = json.loads(boxes[0].read_text())
    assert payload["operation_id"] == "boxed_0"
    stages = [r["stage"] for r in payload["records"] if r.get("type") == "stage"]
    assert "connecting" in stages and "fetching" in stages


# --------------------------------------------------------------------- #
# Ops routes: /history, /slo, /tasks, POST /profile
# --------------------------------------------------------------------- #


@pytest.fixture()
def ops_server(monkeypatch):
    from covalent_tpu_plugin.obs import opsserver as ops_mod

    monkeypatch.setenv("COVALENT_TPU_OPS_PORT", "0")
    server = ops_mod.OpsServer(port=0)
    yield server
    server.close()


def http_get(port: int, path: str):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10
    ) as response:
        return response.status, response.read()


def test_ops_history_slo_tasks_routes(ops_server):
    from covalent_tpu_plugin.obs.flightrec import FLIGHT_RECORDER
    from covalent_tpu_plugin.obs.history import HISTORY

    HISTORY.sample(force=True)
    status, body = http_get(ops_server.port, "/history")
    assert status == 200
    described = json.loads(body)
    assert "metrics" in described and described["samples"] >= 1
    status, body = http_get(
        ops_server.port, "/history?metric=covalent_tpu_tasks_total&window=60"
    )
    assert status == 200
    assert json.loads(body)["metric"] == "covalent_tpu_tasks_total"
    status, body = http_get(ops_server.port, "/slo")
    assert status == 200
    slo_view = json.loads(body)
    assert "slos" in slo_view
    FLIGHT_RECORDER.record_stage("ops_route_op", "executing")
    status, body = http_get(ops_server.port, "/tasks")
    assert status == 200
    assert "ops_route_op" in json.loads(body)["tasks"]
    status, body = http_get(ops_server.port, "/tasks/ops_route_op")
    assert status == 200
    assert json.loads(body)["count"] >= 1
    with pytest.raises(urllib.error.HTTPError) as err:
        http_get(ops_server.port, "/tasks/never_ran")
    assert err.value.code == 404
    FLIGHT_RECORDER.forget("ops_route_op")


def http_post(port: int, path: str, payload: dict):
    request = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        method="POST",
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(request, timeout=30) as response:
            return response.status, json.loads(response.read())
    except urllib.error.HTTPError as err:
        return err.code, json.loads(err.read())


def test_ops_profile_route_providers(ops_server):
    from covalent_tpu_plugin.obs.opsserver import (
        register_profile_provider,
        unregister_profile_provider,
    )

    status, body = http_post(ops_server.port, "/profile", {})
    assert status == 503  # no provider: nothing resident to profile
    seen: list[dict] = []

    def provider(params):
        seen.append(params)
        return {"path": "/tmp/trace.tgz", "digest": "d" * 64, "bytes": 10}

    register_profile_provider("test-exec", provider)
    try:
        status, body = http_post(
            ops_server.port, "/profile", {"duration_s": 0.5}
        )
        assert status == 200
        assert body["provider"] == "test-exec"
        assert body["digest"] == "d" * 64
        assert seen[0]["duration_s"] == 0.5
        register_profile_provider("gone", lambda params: None)
        # A provider answering None (owner gone / nothing resident) is
        # skipped; the capture still lands on the live one.
        status, body = http_post(ops_server.port, "/profile", {})
        assert status == 200
    finally:
        unregister_profile_provider("test-exec")
        unregister_profile_provider("gone")


# --------------------------------------------------------------------- #
# Serving stale-series reap (satellite)
# --------------------------------------------------------------------- #


def test_serve_session_close_reaps_gauge_series(run_async):
    from covalent_tpu_plugin.obs.metrics import REGISTRY
    from covalent_tpu_plugin.serving.metrics import (
        SERVE_QUEUE_DEPTH,
        SERVE_TOKENS_PER_S,
        SERVE_WORKER_SLOTS,
    )
    from covalent_tpu_plugin.serving.supervisor import SessionSupervisor

    class StubExecutor:
        _serve_handles: dict = {}
        cache_dir = "/tmp"

    async def flow():
        # The reap lives in SessionSupervisor since the PR 11 handle/
        # supervisor split (ServeHandle and ReplicaSet replicas both
        # retire sessions through this one path).
        handle = SessionSupervisor(StubExecutor(), sid="reap-sid")
        handle.address = "w1"
        other = SessionSupervisor(StubExecutor(), sid="other-sid")
        other.address = "w1"
        StubExecutor._serve_handles = {"other-sid": other}
        SERVE_QUEUE_DEPTH.labels(session="reap-sid").set(3)
        SERVE_TOKENS_PER_S.labels(session="reap-sid").set(100.0)
        for state in ("sessions", "slots", "busy", "queued"):
            SERVE_WORKER_SLOTS.labels(worker="w1", state=state).set(1)
        def slot_lines():
            return [
                line for line in REGISTRY.prometheus_text().splitlines()
                if line.startswith("covalent_tpu_serve_worker_slots")
            ]

        handle._drop_live()
        text = REGISTRY.prometheus_text()
        # Per-session series die with the session...
        assert 'session="reap-sid"' not in text
        # ...but the worker's occupancy survives while another live
        # session still shares the worker.
        assert any('worker="w1"' in line for line in slot_lines())
        StubExecutor._serve_handles = {}
        other._drop_live()
        assert not any('worker="w1"' in line for line in slot_lines())

    run_async(flow())


# --------------------------------------------------------------------- #
# Resident-mode profiling
# --------------------------------------------------------------------- #


@pytest.fixture()
def harness_emits(monkeypatch):
    """Capture harness _emit output (the agent-channel protocol lines)."""
    from covalent_tpu_plugin import harness

    lines: list[dict] = []
    monkeypatch.setattr(harness, "_emit", lines.append)
    harness._PROFILE_ACTIVE.clear()
    yield lines
    harness._PROFILE_ACTIVE.clear()


def test_harness_profile_verbs_roundtrip(tmp_path, harness_emits):
    from covalent_tpu_plugin import harness

    trace_dir = str(tmp_path / "trace")
    harness._profile_start({"cmd": "profile_start", "id": "p1",
                            "dir": trace_dir})
    assert harness_emits[-1]["event"] == "profile_started"
    # Second start while one is active: refused busy, trace not corrupted.
    harness._profile_start({"cmd": "profile_start", "id": "p2",
                            "dir": trace_dir})
    assert harness_emits[-1] == {
        "event": "profile_error", "id": "p2", "code": "busy",
        "message": harness_emits[-1]["message"],
    }
    harness._profile_stop({"cmd": "profile_stop", "id": "p1",
                           "artifact_dir": str(tmp_path / "cas")})
    # Stop + packaging run on a daemon thread (the command loop must stay
    # responsive under multi-MB traces): wait for the threaded emit.
    import time

    deadline = time.time() + 30
    while time.time() < deadline:
        if any(e.get("event") == "profile_stopped" for e in harness_emits):
            break
        time.sleep(0.02)
    stopped = harness_emits[-1]
    assert stopped["event"] == "profile_stopped"
    assert os.path.basename(stopped["path"]) == (
        f"{stopped['digest']}.profile.tgz"
    )
    import hashlib

    assert hashlib.sha256(
        open(stopped["path"], "rb").read()
    ).hexdigest() == stopped["digest"]
    assert not os.path.exists(trace_dir)  # raw trace consumed
    # Stop with nothing active: not_running, never a crash.
    harness._profile_stop({"cmd": "profile_stop", "id": "p1"})
    assert harness_emits[-1]["code"] == "not_running"
    harness._profile_start({"cmd": "profile_start", "id": ""})
    assert harness_emits[-1]["code"] == "bad_request"


def test_harness_profile_stop_discard_skips_packaging(tmp_path, harness_emits):
    """A compensating stop (abandoned capture) must not tar+hash a trace
    nobody will fetch: the raw dir is deleted and no artifact written."""
    import time as _time

    from covalent_tpu_plugin import harness

    trace_dir = str(tmp_path / "trace")
    harness._profile_start({"cmd": "profile_start", "id": "pd",
                            "dir": trace_dir})
    assert harness_emits[-1]["event"] == "profile_started"
    harness._profile_stop({"cmd": "profile_stop", "id": "pd",
                           "discard": True})
    deadline = _time.time() + 30
    while _time.time() < deadline:
        if any(e.get("event") == "profile_stopped" for e in harness_emits):
            break
        _time.sleep(0.02)
    stopped = harness_emits[-1]
    assert stopped["event"] == "profile_stopped"
    assert stopped.get("discarded") is True and "path" not in stopped
    assert not os.path.exists(trace_dir)
    assert not list(tmp_path.rglob("*.profile.tgz"))
    assert not harness._PROFILE_ACTIVE  # slot freed for the next capture


def test_harness_profile_start_refuses_foreign_sid(tmp_path, harness_emits):
    """A sid-pinned start on a runtime NOT hosting that session must be
    refused — tracing whichever process saw the command first returns a
    digest-valid artifact of the wrong runtime."""
    from covalent_tpu_plugin import harness

    harness._profile_start({"cmd": "profile_start", "id": "p1",
                            "dir": str(tmp_path / "t"), "sid": "s-elsewhere"})
    assert harness_emits[-1]["event"] == "profile_error"
    assert harness_emits[-1]["code"] == "unknown_session"
    assert not harness._PROFILE_ACTIVE  # nothing started
    # The same start succeeds once this runtime hosts the session.
    harness._SERVE_SESSIONS["s-here"] = object()
    try:
        harness._profile_start({"cmd": "profile_start", "id": "p2",
                                "dir": str(tmp_path / "t"), "sid": "s-here"})
        assert harness_emits[-1]["event"] == "profile_started"
    finally:
        harness._SERVE_SESSIONS.pop("s-here", None)
        if harness._PROFILE_ACTIVE:
            import jax

            jax.profiler.stop_trace()
            harness._PROFILE_ACTIVE.clear()


def test_epilogue_excludes_profile_capture_from_overhead(tmp_path):
    """Trace stop + tar + fetch observes the dispatch, it is not part of
    it: charging capture seconds as wall_overhead would burn the shipped
    dispatch_overhead SLO on profiled-but-healthy traffic."""
    import time as _time

    from covalent_tpu_plugin import TPUExecutor
    from covalent_tpu_plugin.obs.trace import Span

    ex = TPUExecutor(
        transport="local", cache_dir=str(tmp_path / "c"),
        remote_cache=str(tmp_path / "r"), python_path=sys.executable,
    )
    root = Span("executor.task", activate=False)
    root.__enter__()
    root._t0 = _time.perf_counter() - 3.0  # elapsed ~3s
    root.stage_durations.update({"execute": 0.5, "profile": 2.0})
    ex._attempt_epilogue(root, "completed", "op-prof-oh", 0)
    wall = ex.last_timings["wall_overhead"]
    assert 0.3 < wall < 0.7, wall  # 3.0 - execute - profile, NOT 2.5
    assert ex.last_timings["overhead"] == pytest.approx(0.0)


def test_capture_profile_targets_pin_to_session_host(tmp_path):
    """The dispatcher side of the same contract: a sid naming a local
    ServeHandle restricts candidate agents to the one hosting it, with
    the sid translated to the current generation's remote id."""
    from covalent_tpu_plugin import TPUExecutor

    class _FakeClient:
        def __init__(self, mode):
            self.mode = mode
            self.alive = True

    class _FakeHandle:
        def __init__(self, client):
            self._sid_g = "s1.g0"
            self._client = client

    executor = TPUExecutor(
        transport="local", cache_dir=str(tmp_path / "c"),
        remote_cache=str(tmp_path / "r"), python_path=sys.executable,
    )
    pool_a, pool_b = _FakeClient("pool"), _FakeClient("pool")
    executor._agents = {"a": pool_a, "b": pool_b}
    executor._serve_handles = {"s1": _FakeHandle(pool_b)}
    sid, targets = executor._profile_targets("s1")
    assert sid == "s1.g0"
    assert targets == [("b", pool_b)]
    # No sid: every live agent is a candidate, pool servers first.
    native = _FakeClient("native")
    executor._agents["n"] = native
    _, targets = executor._profile_targets("")
    assert [t[1].mode for t in targets] == ["pool", "pool", "native"]
    # A dead pinned client falls back to the worker-side refusal road.
    pool_b.alive = False
    _, targets = executor._profile_targets("s1")
    assert pool_b not in [t[1] for t in targets] and targets


def make_rpc_profile_executor(tmp_path, **kwargs):
    from .test_rpc import make_rpc_executor

    kwargs.setdefault("profile_dir", str(tmp_path / "remote_profiles"))
    return make_rpc_executor(tmp_path, **kwargs)


def test_rpc_preselect_accepts_profiling(tmp_path):
    executor = make_rpc_profile_executor(tmp_path)
    # The PR's acceptance line: profile_dir no longer disqualifies the
    # electron from the RPC fast path.
    assert executor._rpc_preselect({}) is True


def test_rpc_electron_profiles_resident_runtime(tmp_path, run_async):
    """Acceptance: a profile_dir capture against a live RPC electron —
    no launch fallback, artifact staged back via CAS, digest-verified."""
    executor = make_rpc_profile_executor(tmp_path)

    def jaxwork(n):
        import jax.numpy as jnp

        return float(jnp.sum(jnp.arange(n)))

    async def flow():
        try:
            result = await executor.run(
                jaxwork, [10], {}, {"dispatch_id": "prof", "node_id": 0}
            )
            assert result == 45.0
            assert executor.last_dispatch_mode == "rpc"
            trace = executor.last_timings.get("profile_trace")
            assert trace and os.path.exists(trace)
            with tarfile.open(trace) as tar:
                names = tar.getnames()
            assert any("plugins/profile" in name for name in names)
            # On-demand capture against the still-warm runtime (the
            # POST /profile body) works too.
            info = await executor.capture_profile(duration_s=0.2)
            assert info is not None and os.path.exists(info["path"])
            import hashlib

            assert hashlib.sha256(
                open(info["path"], "rb").read()
            ).hexdigest() == info["digest"]
            # Neither the per-electron nor the on-demand capture may
            # leave an _profile_artifacts entry behind (the epilogue
            # pops real op ids; capture_profile pops synthetic ones).
            assert executor._profile_artifacts == {}
        finally:
            await executor.close()

    run_async(flow())


def test_launch_profile_trace_fetched_back(tmp_path, run_async):
    """Satellite: launch-mode traces are pulled back to the dispatcher
    and recorded in last_timings, and the remote trace dir is consumed."""
    executor = make_rpc_profile_executor(tmp_path, dispatch_mode="launch")

    def jaxwork(n):
        import jax.numpy as jnp

        return float(jnp.sum(jnp.arange(n)))

    async def flow():
        try:
            await executor.run(
                jaxwork, [10], {}, {"dispatch_id": "launchprof", "node_id": 0}
            )
            assert executor.last_dispatch_mode == "launch"
            trace = executor.last_timings.get("profile_trace")
            assert trace and os.path.exists(trace)
            assert not os.path.exists(
                str(tmp_path / "remote_profiles" / "launchprof_0")
            )
        finally:
            await executor.close()

    run_async(flow())


def test_capture_profile_without_runtime_returns_none(tmp_path, run_async):
    executor = make_rpc_profile_executor(tmp_path)

    async def flow():
        try:
            # No electron ever ran: no agents, nothing to profile.
            assert await executor.capture_profile(duration_s=0.1) is None
        finally:
            await executor.close()

    run_async(flow())
