"""Replica sets: session-aware routing, drain-on-death, scale-down reaps.

Two tiers:

* **Router units** — :class:`ReplicaRouter` driven with fake replica
  views and a fake clock: least-loaded choice with rotating tie-breaks,
  per-tenant DRR fairness at the configured weight ratio, sticky-sid
  pinning that survives a replica reconnect and re-pins only after a
  death, and bounded-queue shedding.
* **Set integration** — real pool servers behind 2-replica
  :class:`ReplicaSet`\\ s: streams land exactly across replicas, a
  SIGKILLed replica reconnects and replays while the survivor absorbs
  fresh load, a replica dead PAST its retry budget drains its in-flight
  callers onto the survivor with the exactly-once ``idx`` splice, and a
  scale-down releases fleet capacity pins and reaps every per-session /
  per-replica / worker-occupancy metric series through ``_drop_live``.
"""

import asyncio
import sys
import time

import pytest

from covalent_tpu_plugin import TPUExecutor
from covalent_tpu_plugin.agent import AgentError
from covalent_tpu_plugin.fleet.pools import Pool, PoolSpec
from covalent_tpu_plugin.fleet.queue import QueueFullError, WorkItem
from covalent_tpu_plugin.obs.metrics import REGISTRY
from covalent_tpu_plugin.serving import (
    ReplicaRouter,
    ReplicaView,
    ServeError,
    ServeRequest,
    open_replica_set,
)
from covalent_tpu_plugin.serving.supervisor import SessionSupervisor

from .helpers import pin_cpu_task_env
from .test_serving import gauge_value, make_factory


class FakeClock:
    def __init__(self, start: float = 100.0) -> None:
        self.now = start

    def __call__(self) -> float:
        return self.now

    def advance(self, dt: float) -> None:
        self.now += dt


def item(tenant="default", sticky="", request=None):
    return WorkItem(
        fn=None, args=(), kwargs={},
        task_metadata={"request": request, "sticky": sticky},
        tenant=tenant,
    )


def series_labels(name: str) -> list[dict]:
    metric = REGISTRY.get(name)
    if metric is None:
        return []
    return [dict(labels) for labels, _value in metric._series()]


def make_replica_executor(tmp_path, tag, **kwargs):
    kwargs.setdefault("transport", "local")
    kwargs.setdefault("cache_dir", str(tmp_path / f"cache-{tag}"))
    kwargs.setdefault("remote_cache", str(tmp_path / f"remote-{tag}"))
    kwargs.setdefault("python_path", sys.executable)
    kwargs.setdefault("poll_freq", 0.2)
    kwargs.setdefault("use_agent", "pool")
    kwargs.setdefault("heartbeat_interval", 0.0)
    kwargs.setdefault("prewarm", False)
    return TPUExecutor(**pin_cpu_task_env(kwargs))


# ---------------------------------------------------------------------------
# Router units (fake clock, fake views — no I/O)
# ---------------------------------------------------------------------------


def test_router_least_loaded_choice():
    """An unpinned request lands on the open replica with the most free
    lanes; closed (reconnecting/failed) replicas are never candidates."""
    router = ReplicaRouter(clock=FakeClock())
    views = {
        "r0": ReplicaView("r0", open=True, load=3, capacity=4),
        "r1": ReplicaView("r1", open=True, load=0, capacity=4),
        "r2": ReplicaView("r2", open=False, load=0, capacity=4),
    }
    router.submit(item())
    [(_, replica, outcome)] = router.pump(views)
    assert replica == "r1"
    assert outcome == "least_loaded"


def test_router_tie_break_rotates():
    """Exact load ties rotate across the tied replicas instead of piling
    onto one; a burst in ONE pump also spreads (headroom folds back into
    the effective load)."""
    router = ReplicaRouter(clock=FakeClock())
    views = {
        "r0": ReplicaView("r0", open=True, load=0, capacity=8),
        "r1": ReplicaView("r1", open=True, load=0, capacity=8),
    }
    for _ in range(6):
        router.submit(item())
    assigned = router.pump(views)
    counts = {"r0": 0, "r1": 0}
    for _, replica, _ in assigned:
        counts[replica] += 1
    assert counts == {"r0": 3, "r1": 3}


def test_router_drr_fairness_ratio_across_tenants():
    """Under contention (one slot trickling free), dispatch order follows
    the deficit-round-robin weights: a 3:1 weighted tenant drains 3x the
    requests over any window, and the light tenant is never starved."""
    clock = FakeClock()
    router = ReplicaRouter(
        weights={"heavy": 3.0, "light": 1.0}, clock=clock
    )
    for i in range(40):
        router.submit(item(tenant="heavy"))
        router.submit(item(tenant="light"))
        clock.advance(0.001)
    order = []
    for _ in range(32):  # one freed lane at a time
        views = {"r0": ReplicaView("r0", open=True, load=0, capacity=1)}
        assigned = router.pump(views)
        assert len(assigned) == 1
        order.append(assigned[0][0].tenant)
    heavy = order.count("heavy")
    light = order.count("light")
    assert light > 0  # no starvation
    assert 2.5 <= heavy / light <= 3.5, order


def test_router_sticky_pins_and_ttl_expiry():
    """A sticky key keeps landing on its pinned replica even when others
    are emptier; after sticky_ttl_s of silence the pin expires and the
    next request re-places least-loaded."""
    clock = FakeClock()
    router = ReplicaRouter(sticky_ttl_s=10.0, clock=clock)
    views = {
        "r0": ReplicaView("r0", open=True, load=0, capacity=8),
        "r1": ReplicaView("r1", open=True, load=0, capacity=8),
    }
    router.submit(item(sticky="user-1"))
    [(_, first, _)] = router.pump(views)
    # Make the pinned replica the WORSE choice; the pin must still win.
    views[first].load = 6
    other = "r1" if first == "r0" else "r0"
    router.submit(item(sticky="user-1"))
    [(_, second, outcome)] = router.pump(views)
    assert second == first
    assert outcome == "sticky"
    clock.advance(11.0)
    router.submit(item(sticky="user-1"))
    [(_, third, outcome)] = router.pump(views)
    assert third == other  # expired pin: fresh least-loaded placement
    assert outcome == "least_loaded"
    assert router.sticky_target("user-1") == third  # re-pinned


def test_router_sticky_waits_for_reconnecting_replica():
    """A pin to a replica that is ALIVE but mid-reconnect defers (the
    warm per-replica state is the point of the pin) instead of
    re-placing; the deferred item dispatches there once it re-opens —
    sticky pinning survives a replica reconnect."""
    clock = FakeClock()
    router = ReplicaRouter(sticky_ttl_s=300.0, clock=clock)
    open_views = {
        "r0": ReplicaView("r0", open=True, load=0, capacity=4),
        "r1": ReplicaView("r1", open=True, load=0, capacity=4),
    }
    router.submit(item(sticky="user-7"))
    [(_, pinned, _)] = router.pump(open_views)
    # The pinned replica goes into reconnect (alive, not open).
    views = dict(open_views)
    views[pinned] = ReplicaView(
        pinned, open=False, alive=True, load=0, capacity=4
    )
    router.submit(item(sticky="user-7"))
    assert router.pump(views) == []  # deferred, NOT moved to the other
    assert router.queued == 1
    # Reconnect completes: the deferred turn lands on the same replica.
    [(_, replica, outcome)] = router.pump(open_views)
    assert replica == pinned
    assert outcome == "sticky"


def test_router_sticky_repins_after_replica_death():
    """A pin to a DEAD replica (not alive) is abandoned: the request
    re-places least-loaded and the key re-pins to the survivor."""
    clock = FakeClock()
    router = ReplicaRouter(sticky_ttl_s=300.0, clock=clock)
    views = {
        "r0": ReplicaView("r0", open=True, load=0, capacity=4),
        "r1": ReplicaView("r1", open=True, load=0, capacity=4),
    }
    router.submit(item(sticky="user-9"))
    [(_, pinned, _)] = router.pump(views)
    survivor = "r1" if pinned == "r0" else "r0"
    router.forget_replica(pinned)
    views[pinned] = ReplicaView(
        pinned, open=False, alive=False, load=0, capacity=4
    )
    router.submit(item(sticky="user-9"))
    [(_, replica, _)] = router.pump(views)
    assert replica == survivor
    assert router.sticky_target("user-9") == survivor


def test_router_queue_moves_its_own_depth_gauge():
    """The router's DRR backlog must move covalent_tpu_serve_router_
    queue_depth, never the fleet scheduler's covalent_tpu_queue_depth —
    two queues on one gauge would overwrite and delete each other's
    tenant series."""
    router = ReplicaRouter(clock=FakeClock())
    router.submit(item(tenant="gsep-tenant"))
    assert not any(
        labels.get("tenant") == "gsep-tenant"
        for labels in series_labels("covalent_tpu_queue_depth")
    )
    assert any(
        labels.get("tenant") == "gsep-tenant"
        for labels in series_labels("covalent_tpu_serve_router_queue_depth")
    )
    router.drain()
    assert not any(
        labels.get("tenant") == "gsep-tenant"
        for labels in series_labels("covalent_tpu_serve_router_queue_depth")
    )


def test_router_queue_bound_sheds():
    """Past the admission bound the router refuses new work with the
    fleet queue's own QueueFullError (classified PERMANENT upstream)."""
    router = ReplicaRouter(queue_max=2, clock=FakeClock())
    router.submit(item())
    router.submit(item())
    with pytest.raises(QueueFullError):
        router.submit(item())


def test_router_defers_when_no_headroom():
    """Items stay queued (original enqueue stamp kept) while every open
    replica is at capacity, and flow the moment lanes free."""
    clock = FakeClock()
    router = ReplicaRouter(clock=clock)
    busy = {"r0": ReplicaView("r0", open=True, load=2, capacity=2)}
    router.submit(item())
    assert router.pump(busy) == []
    assert router.queued == 1
    free = {"r0": ReplicaView("r0", open=True, load=1, capacity=2)}
    [(_, replica, _)] = router.pump(free)
    assert replica == "r0"
    assert router.queued == 0


# ---------------------------------------------------------------------------
# Supervisor-level: the exactly-once splice fails loud on a gap
# ---------------------------------------------------------------------------


def test_token_stream_gap_fails_loud(run_async):
    """An idx jumping past the request's high-water mark means a chunk
    was lost: the stream must fail with the gap spelled out, never
    splice around a hole."""

    class DummyExecutor:
        _serve_handles: dict = {}

    async def flow():
        sup = SessionSupervisor(DummyExecutor(), sid="gap")
        request = ServeRequest("gap-r1", [1], None, 0.0, "")
        sup._requests["gap-r1"] = request
        sup._on_token({"rid": "gap-r1", "idx": 0, "tokens": [7, 8]})
        assert request.tokens == [7, 8]
        # Duplicate splice: replay from 0 drops the delivered prefix.
        sup._on_token({"rid": "gap-r1", "idx": 0, "tokens": [7, 8, 9]})
        assert request.tokens == [7, 8, 9]
        # Gap: idx 5 with only 3 held — fail loud.
        sup._on_token({"rid": "gap-r1", "idx": 5, "tokens": [99]})
        with pytest.raises(ServeError, match="token stream gap"):
            await request.result(timeout=1)

    run_async(flow())


# ---------------------------------------------------------------------------
# Supervisor-level: hedge arbitration (shared-request terminal ownership)
# ---------------------------------------------------------------------------


class _DummyExecutor:
    _serve_handles: dict = {}


def _hedged_pair(rid):
    """One ServeRequest held by two bare supervisors (a hedge in flight:
    primary + speculative arm), wired exactly as submit() would wire it."""
    primary = SessionSupervisor(_DummyExecutor(), sid=f"{rid}-primary")
    hedge = SessionSupervisor(_DummyExecutor(), sid=f"{rid}-hedge")
    request = ServeRequest(rid, [1], None, 0.0, "")
    request.hedged = True
    for sup in (primary, hedge):
        sup._requests[rid] = request
        request.arms[sup.sid] = time.monotonic()
    return primary, hedge, request


def test_hedge_arm_reject_releases_claim_without_failing_request(run_async):
    """The speculative arm getting shed on the side-band (likely under
    the SAME load that triggered the hedge) must not fail the shared
    request while the primary still holds it — the reject only releases
    the hedge arm's claim; the primary's stream completes normally."""
    from covalent_tpu_plugin.fleet.health import HEALTH

    async def flow():
        primary, hedge, request = _hedged_pair("hrej")
        hedge._on_reject({
            "rid": "hrej", "code": "serve_admission_shed", "message": "full",
        })
        assert not request.done
        assert "hrej" not in hedge._requests
        primary._on_token({
            "rid": "hrej", "idx": 0, "tokens": [5, 6], "done": True,
        })
        assert await request.result(timeout=1) == [5, 6]
        assert request.served_by == primary.sid
        # Both arms rejected IS terminal: nobody holds the rid anymore.
        primary2, hedge2, request2 = _hedged_pair("hrej2")
        hedge2._on_reject({"rid": "hrej2", "code": "serve_admission_shed"})
        primary2._on_reject({"rid": "hrej2", "code": "serve_admission_shed"})
        with pytest.raises(Exception, match="serve_admission_shed"):
            await request2.result(timeout=1)
        for sid in (
            primary.sid, hedge.sid, primary2.sid, hedge2.sid,
        ):
            HEALTH.drop(sid)

    run_async(flow())


def test_hedge_loser_terminal_skips_outcome_accounting(run_async):
    """A loser that completes normally before its cancel drains delivers
    a byte-equal stream, but the outcome accounting (served counter,
    health credit) belongs to the winner alone — and a loser dying with
    a non-cancel error must not fail the winner's healthy stream."""
    from covalent_tpu_plugin.fleet.health import HEALTH

    async def flow():
        primary, hedge, request = _hedged_pair("hwin")
        # The hedge arm delivers the first fresh token: it is the winner.
        hedge._on_token({"rid": "hwin", "idx": 0, "tokens": [5]})
        assert request.served_by == hedge.sid
        # The losing primary completes the FULL stream before its cancel
        # drains: the tail still splices in (byte-equal), but the loser
        # releases its claim without counting the outcome.
        primary._on_token({
            "rid": "hwin", "idx": 0, "tokens": [5, 6, 7], "done": True,
        })
        assert await request.result(timeout=1) == [5, 6, 7]
        assert primary.served == 0
        assert "hwin" not in primary._requests
        # The winner's own terminal is the one that counts.
        hedge._on_token({
            "rid": "hwin", "idx": 1, "tokens": [6, 7], "done": True,
        })
        assert hedge.served == 1
        # A loser erroring mid-drain never reaches the shared request.
        primary2, hedge2, request2 = _hedged_pair("herr")
        hedge2._on_token({"rid": "herr", "idx": 0, "tokens": [9]})
        primary2._on_token({
            "rid": "herr", "idx": 0, "tokens": [], "done": True,
            "error": "worker_died",
        })
        assert not request2.done
        hedge2._on_token({"rid": "herr", "idx": 1, "tokens": [], "done": True})
        assert await request2.result(timeout=1) == [9]
        for sid in (
            primary.sid, hedge.sid, primary2.sid, hedge2.sid,
        ):
            HEALTH.drop(sid)

    run_async(flow())


def test_hedge_winner_health_latency_uses_own_dispatch(run_async):
    """The winner's differential health sample is measured from ITS OWN
    dispatch, not the original submit: charging the healthy winner the
    primary's stall plus the hedge threshold wait would pollute the very
    EWMA-vs-median signal that routed around the straggler."""
    from covalent_tpu_plugin.fleet.health import HEALTH

    async def flow():
        sup = SessionSupervisor(_DummyExecutor(), sid="hlat-winner")
        request = ServeRequest("hlat", [1], None, 0.0, "")
        request.hedged = True
        # The request was submitted 30s ago; the hedge arm dispatched it
        # only 10ms ago (the primary spent the difference stalling).
        request.t_submit = time.monotonic() - 30.0
        sup._requests["hlat"] = request
        request.arms[sup.sid] = time.monotonic() - 0.01
        sup._on_token({"rid": "hlat", "idx": 0, "tokens": [1], "done": True})
        snap = HEALTH.snapshot()["hlat-winner"]
        assert snap["lat_samples"] == 1
        assert snap["lat_ewma_s"] < 1.0, snap
        HEALTH.drop("hlat-winner")

    run_async(flow())


def test_replica_set_streams_across_replicas(tmp_path, run_async):
    """Eight requests through a 2-replica set: every stream exact, BOTH
    replicas served traffic (least-loaded spread), per-replica sessions
    visible on each executor's serving view, router decisions cheap."""

    async def flow():
        ex1 = make_replica_executor(tmp_path, "a")
        ex2 = make_replica_executor(tmp_path, "b")
        try:
            rset = await open_replica_set(
                [ex1, ex2], make_factory(), name="spread",
                stats_interval_s=0.1,
            )
            requests = [
                await rset.request(
                    [10 * i], params={"max_new_tokens": 4},
                    tenant=f"t{i % 2}",
                )
                for i in range(8)
            ]
            results = [await r.result(timeout=30) for r in requests]
            status = rset.status()
            views1 = dict(ex1.serve_sessions())
            views2 = dict(ex2.serve_sessions())
            closed = await rset.close()
        finally:
            await ex1.close()
            await ex2.close()
        return results, status, views1, views2, closed

    results, status, views1, views2, closed = run_async(flow())
    for i, tokens in enumerate(results):
        assert tokens == [10 * i + j + 1 for j in range(4)]
    assert status["state"] == "open"
    per_replica = {
        rid: view["served"] for rid, view in status["replicas"].items()
    }
    assert set(per_replica) == {"r0", "r1"}
    assert all(served > 0 for served in per_replica.values()), per_replica
    assert closed["served"] == 8
    # Each executor's /status serving section carries its replica session,
    # tagged with the set identity.
    assert "spread:r0" in views1
    assert views1["spread:r0"]["replica_set"] == "spread"
    assert "spread:r1" in views2


def test_single_replica_set_degenerates(tmp_path, run_async):
    """replicas=1 is exactly today's one-session behavior: pass-through
    router, one supervised session, same stream semantics."""

    async def flow():
        ex = make_replica_executor(tmp_path, "solo")
        try:
            rset = await open_replica_set(
                ex, make_factory(), name="solo",
            )
            request = await rset.request(
                [100], params={"max_new_tokens": 4}
            )
            tokens = await request.result(timeout=30)
            state = rset.state
            closed = await rset.close()
        finally:
            await ex.close()
        return tokens, state, closed

    tokens, state, closed = run_async(flow())
    assert tokens == [101, 102, 103, 104]
    assert state == "open"
    assert closed["served"] == 1


def test_replica_kill_mid_stream_drains_onto_survivor(tmp_path, run_async):
    """SIGKILL one replica's resident server mid-traffic: its supervisor
    reconnects and replays (exactly-once splice), fresh requests keep
    flowing through the survivor the whole time, and every stream —
    killed replica's included — completes byte-exact."""

    async def flow():
        ex1 = make_replica_executor(
            tmp_path, "k1", retry_base_delay=0.05, retry_max_delay=0.2
        )
        ex2 = make_replica_executor(
            tmp_path, "k2", retry_base_delay=0.05, retry_max_delay=0.2
        )
        try:
            rset = await open_replica_set(
                [ex1, ex2],
                make_factory(step_delay=0.1, default_cap=12),
                name="chaos", retries=2,
            )
            requests = [await rset.request([100 * i]) for i in range(6)]
            for _ in range(200):
                if all(len(r.tokens) >= 4 for r in requests):
                    break
                await asyncio.sleep(0.05)
            assert all(len(r.tokens) >= 4 for r in requests)
            ex1._agents["localhost"]._process._proc.kill()
            # Fresh load lands on the survivor while r0 reconnects.
            late = await rset.request(
                [9000], params={"max_new_tokens": 3}
            )
            results = [await r.result(timeout=60) for r in requests]
            late_result = await late.result(timeout=30)
            reconnects = rset.reconnects
            state = rset.state
            await rset.close()
        finally:
            await ex1.close()
            await ex2.close()
        return results, late_result, reconnects, state

    results, late_result, reconnects, state = run_async(flow())
    for i, tokens in enumerate(results):
        assert tokens == [100 * i + j + 1 for j in range(12)], (i, tokens)
    assert late_result == [9001, 9002, 9003]
    assert reconnects >= 1
    assert state == "open"


def test_replica_past_retry_budget_reroutes_in_flight(tmp_path, run_async):
    """A replica dead PAST its retry budget hands its in-flight requests
    to the set, which re-routes them onto the survivor: streams complete
    byte-exact with no duplicate (the cross-replica splice), the dead
    replica reports failed, the set stays open, and the failover
    decision is counted."""

    def counter_value(name: str, **labels) -> float:
        metric = REGISTRY.get(name)
        if metric is None:
            return 0.0
        return sum(
            value.value for lbls, value in metric._series()
            if all(lbls.get(k) == v for k, v in labels.items())
        )

    async def flow():
        ex1 = make_replica_executor(
            tmp_path, "p1", retry_base_delay=0.05, retry_max_delay=0.2
        )
        ex2 = make_replica_executor(
            tmp_path, "p2", retry_base_delay=0.05, retry_max_delay=0.2
        )
        failover0 = counter_value(
            "covalent_tpu_serve_router_decisions_total",
            outcome="failover",
        )
        try:
            # 24-token streams on 4 engine slots: every request streams
            # CONCURRENTLY and is still in flight when the kill lands (a
            # request COMPLETED on the dead replica correctly loses its
            # pin — only in-flight ones re-route and re-pin).
            rset = await open_replica_set(
                [ex1, ex2],
                make_factory(step_delay=0.1, default_cap=24, slots=4),
                name="drain", retries=1,
            )
            requests = [
                await rset.request([100 * i], sticky=f"u{i}")
                for i in range(6)
            ]
            for _ in range(200):
                if all(len(r.tokens) >= 4 for r in requests):
                    break
                await asyncio.sleep(0.05)
            assert all(len(r.tokens) >= 4 for r in requests)
            # Doom r0's reconnect: every re-open attempt refuses, so the
            # retry budget spends and the permanent path drains.
            victim = rset.supervisors["r0"]

            async def refuse():
                raise AgentError("re-open refused (test)")

            victim._open_generation = refuse
            victim.retries = 0
            ex1._agents["localhost"]._process._proc.kill()
            results = [await r.result(timeout=60) for r in requests]
            victim_state = victim.state
            set_state = rset.state
            failover = counter_value(
                "covalent_tpu_serve_router_decisions_total",
                outcome="failover",
            ) - failover0
            # Drain-on-death keeps the callers' pins: every sticky key
            # now targets the SURVIVOR, so follow-up turns land where
            # the re-routed streams did.
            pins = {
                rset.router.sticky_target(f"u{i}") for i in range(6)
            }
            await rset.close()
        finally:
            await ex1.close()
            await ex2.close()
        return results, victim_state, set_state, failover, pins

    results, victim_state, set_state, failover, pins = run_async(flow())
    for i, tokens in enumerate(results):
        assert tokens == [100 * i + j + 1 for j in range(24)], (i, tokens)
    assert victim_state == "failed"
    assert set_state == "open"
    assert failover >= 1
    assert pins == {"r1"}, pins


def test_scale_down_releases_capacity_and_reaps_gauges(
    tmp_path, run_async
):
    """The N-replica teardown satellite: scaling 2 -> 1 releases the
    retired replica's fleet capacity pin and drops its per-session AND
    per-replica series through ``_drop_live`` — including the worker
    occupancy series once no live session shares that executor's worker
    — and a full close leaves NO covalent_tpu_serve_* series behind."""

    async def flow():
        ex1 = make_replica_executor(tmp_path, "s1")
        ex2 = make_replica_executor(tmp_path, "s2")
        pool1 = Pool(
            PoolSpec(name="sp1", capacity=2, transport="local"),
            executor=ex1,
        )
        pool2 = Pool(
            PoolSpec(name="sp2", capacity=2, transport="local"),
            executor=ex2,
        )
        try:
            rset = await open_replica_set(
                [pool1, pool2], make_factory(), name="shrink",
                stats_interval_s=0.05,
            )
            in_use_open = (pool1.in_use, pool2.in_use)
            # A request + a stats tick so the per-session gauges exist.
            request = await rset.request(
                [5], params={"max_new_tokens": 2}
            )
            await request.result(timeout=30)
            await asyncio.sleep(0.2)
            # Worker-occupancy series as the heartbeat backhaul would
            # set them (heartbeats are disabled in tests).
            for ex in (ex1, ex2):
                ex._record_heartbeat(
                    "op-x", "localhost",
                    {"type": "worker.heartbeat", "seq": 1, "pid": 1,
                     "ts": 1.0,
                     "serve": {"sessions": 1, "slots": 2, "busy": 0,
                               "queued": 0}},
                )
            assert gauge_value(
                "covalent_tpu_serve_worker_slots",
                worker="localhost", state="slots",
            ) == 2.0
            live = await rset.scale_to(1)
            in_use_scaled = (pool1.in_use, pool2.in_use)
            replica_series_after_scale = series_labels(
                "covalent_tpu_serve_replica_in_flight"
            )
            session_series_after_scale = [
                labels["session"]
                for labels in series_labels(
                    "covalent_tpu_serve_queue_depth"
                )
                if labels["session"].startswith("shrink:")
            ]
            await rset.close()
            in_use_closed = (pool1.in_use, pool2.in_use)
        finally:
            await ex1.close()
            await ex2.close()
        return (
            live, in_use_open, in_use_scaled, in_use_closed,
            replica_series_after_scale, session_series_after_scale,
        )

    (live, in_use_open, in_use_scaled, in_use_closed,
     replica_series, session_series) = run_async(flow())
    assert live == 1
    assert in_use_open == (1, 1)
    assert sum(in_use_scaled) == 1  # the retired replica's pin released
    assert in_use_closed == (0, 0)
    # Exactly one replica's series survive the scale-down.
    shrink_series = [
        labels for labels in replica_series if labels["set"] == "shrink"
    ]
    assert len(shrink_series) == 1, replica_series
    assert len(session_series) == 1, session_series
    # Full close: nothing left.
    assert not [
        labels
        for labels in series_labels("covalent_tpu_serve_replica_in_flight")
        if labels["set"] == "shrink"
    ]
    assert not [
        labels
        for labels in series_labels("covalent_tpu_serve_replicas")
        if labels["set"] == "shrink"
    ]
    assert not [
        labels
        for labels in series_labels("covalent_tpu_serve_queue_depth")
        if labels["session"].startswith("shrink:")
    ]
    assert not [
        labels
        for labels in series_labels("covalent_tpu_serve_worker_slots")
        if labels["worker"] == "localhost"
    ]


def test_sticky_requests_land_on_one_replica(tmp_path, run_async):
    """Every turn of a sticky session serves on the SAME replica."""

    async def flow():
        ex1 = make_replica_executor(tmp_path, "st1")
        ex2 = make_replica_executor(tmp_path, "st2")
        try:
            rset = await open_replica_set(
                [ex1, ex2], make_factory(), name="pin",
            )
            for turn in range(6):
                request = await rset.request(
                    [10 * turn], params={"max_new_tokens": 2},
                    sticky="chat-1",
                )
                await request.result(timeout=30)
            served = {
                rid: sup.served
                for rid, sup in rset.supervisors.items()
            }
            await rset.close()
        finally:
            await ex1.close()
            await ex2.close()
        return served

    served = run_async(flow())
    assert sorted(served.values()) == [0, 6], served


def test_rank_targets_prefers_digest_affinity():
    """Replica placement order: spread first, then targets already
    holding the factory's CAS digest, then warm gangs, then free slots —
    the serving analog of the scheduler's fn-digest affinity."""
    from covalent_tpu_plugin.serving.replicas import ReplicaSet

    class StubExecutor:
        def __init__(self, holds=False, warm=False):
            self._holds = holds
            self.is_warm = warm

        def holds_serve_digest(self, digest):
            return self._holds

    class StubPool:
        def __init__(self, holds=False, free=0):
            self._holds = holds
            self.free_slots = free

        def holds_serve_digest(self, digest):
            return self._holds

    cold = StubExecutor()
    holder = StubExecutor(holds=True)
    warm = StubExecutor(warm=True)
    rset = ReplicaSet.__new__(ReplicaSet)
    rset._targets = [(cold, None), (holder, None), (warm, None)]
    rset._placements = {}
    rset._digest = "d" * 64
    ranked = rset._rank_targets()
    assert ranked[0][0] is holder
    assert ranked[1][0] is warm
    assert ranked[2][0] is cold
    # Spread beats affinity: once the holder hosts a replica, the next
    # one goes elsewhere.
    rset._placements["r0"] = (holder, None)
    assert rset._rank_targets()[0][0] is warm
    # Pool targets are probed through the Pool's own wrapper (it guards
    # cold/stub executors), not the executor attribute directly.
    pool_holder = StubPool(holds=True, free=1)
    rset._targets = [(cold, StubPool()), (cold, pool_holder)]
    rset._placements = {}
    assert rset._rank_targets()[0][1] is pool_holder


# ---------------------------------------------------------------------------
# gray-failure health routing + tail-latency hedging


def test_router_degraded_replica_is_last_resort():
    """A gray-degraded replica receives work only when every healthy
    replica is out of headroom — least-loaded must never steer traffic
    onto the browned-out replica just because it drained (slowly)."""
    router = ReplicaRouter(clock=FakeClock())
    views = {
        "gray": ReplicaView(
            "gray", open=True, load=0, capacity=4, degraded=True
        ),
        "busy": ReplicaView("busy", open=True, load=3, capacity=4),
    }
    router.submit(item())
    [(_, replica, _)] = router.pump(views)
    assert replica == "busy"
    # Healthy capacity exhausted: the degraded replica is still better
    # than shedding.
    views["busy"] = ReplicaView("busy", open=True, load=4, capacity=4)
    router.submit(item())
    [(_, replica, _)] = router.pump(views)
    assert replica == "gray"


def test_router_quarantined_gets_no_traffic():
    """Quarantined replicas are excluded from headroom entirely; with no
    other lane the item defers rather than landing on one."""
    router = ReplicaRouter(clock=FakeClock())
    views = {
        "q": ReplicaView(
            "q", open=True, load=0, capacity=4, quarantined=True
        ),
    }
    router.submit(item())
    assert router.pump(views) == []
    # The item survived the deferral and places once a healthy lane opens.
    views["ok"] = ReplicaView("ok", open=True, load=0, capacity=4)
    [(_, replica, _)] = router.pump(views)
    assert replica == "ok"


def test_router_sticky_drains_off_quarantined_replica():
    """A sticky pin to a quarantined replica does NOT wait out the
    quarantine (a reconnect that never comes): the request re-places on
    a healthy replica and the pin moves with it."""
    router = ReplicaRouter(clock=FakeClock())
    router.pin("sess", "q")
    views = {
        "q": ReplicaView(
            "q", open=True, load=0, capacity=4, alive=True,
            quarantined=True,
        ),
        "ok": ReplicaView("ok", open=True, load=2, capacity=4),
    }
    router.submit(item(sticky="sess"))
    [(_, replica, outcome)] = router.pump(views)
    assert replica == "ok"
    assert outcome == "least_loaded"
    assert router.sticky_target("sess") == "ok"
    # Merely-reconnecting (alive, not quarantined) still waits — the
    # drain is a health verdict, not a liveness one.
    router.pin("sess2", "down")
    views["down"] = ReplicaView(
        "down", open=False, load=0, capacity=4, alive=True
    )
    router.submit(item(sticky="sess2"))
    assert router.pump(views) == []
    assert router.sticky_target("sess2") == "down"


def test_hedge_threshold_adapts_to_ttft_ring(monkeypatch):
    """Below 8 samples the trigger is a conservative 1s; with a warm ring
    it tracks the configured percentile, floored at HEDGE_MIN_S."""
    from covalent_tpu_plugin.serving.replicas import ReplicaSet

    monkeypatch.setenv("COVALENT_TPU_HEDGE_PERCENTILE", "90")
    monkeypatch.setenv("COVALENT_TPU_HEDGE_MIN_S", "0.05")
    rset = ReplicaSet.__new__(ReplicaSet)
    rset._hedge_enabled = True
    rset._hedge_percentile = 90.0
    rset._hedge_min_s = 0.05
    rset._ttft_ring = __import__("collections").deque(maxlen=512)
    assert rset._hedge_threshold_s() == 1.0
    for ttft in [0.1] * 18 + [0.9, 0.95]:
        rset._ttft_ring.append(ttft)
    # p90 over [0.1 x18, 0.9, 0.95] = 0.9.
    assert rset._hedge_threshold_s() == pytest.approx(0.9)
    # The floor wins when the fleet is uniformly fast.
    rset._ttft_ring.clear()
    rset._ttft_ring.extend([0.001] * 20)
    assert rset._hedge_threshold_s() == pytest.approx(0.05)


def test_hedge_exactly_once_byte_equal_loser_cancelled(
    tmp_path, run_async, monkeypatch
):
    """End-to-end tail-latency hedge through real pool servers: one
    replica browned out (first token delayed far past the 1s cold
    threshold), its request speculatively re-issued on the healthy
    replica, first token wins, the loser is abandoned mid-generation —
    and the merged stream is byte-equal to the expected tokens, exactly
    once, for EVERY request."""
    from covalent_tpu_plugin.fleet.health import HEALTH
    from covalent_tpu_plugin.serving.metrics import SERVE_HEDGES_TOTAL

    monkeypatch.setenv("COVALENT_TPU_HEDGE", "on")
    monkeypatch.setenv("COVALENT_TPU_HEDGE_BUDGET_PCT", "100")

    def factory():
        import os as os_mod
        import time as time_mod

        class Engine:
            """Deterministic streams; under TEST_GRAY_SLOW the FIRST
            chunk of every lane is held back 3s and later chunks trickle
            — a brownout, not a crash."""

            def __init__(self):
                self.slots = 4
                self.lanes = {}
                self.ready_at = {}
                self.slow = bool(os_mod.environ.get("TEST_GRAY_SLOW"))

            def admit(self, rid, prompt, params):
                base = int(prompt[-1])
                cap = int((params or {}).get("max_new_tokens", 6))
                self.lanes[rid] = [base + j + 1 for j in range(cap)]
                self.ready_at[rid] = (
                    time_mod.monotonic() + 3.0 if self.slow else 0.0
                )

            def step(self):
                time_mod.sleep(0.03)
                events = []
                now = time_mod.monotonic()
                for rid in list(self.lanes):
                    if now < self.ready_at.get(rid, 0.0):
                        continue
                    chunk = self.lanes[rid][:2]
                    self.lanes[rid] = self.lanes[rid][2:]
                    if self.slow:  # trickle: stay mid-stream when losing
                        self.ready_at[rid] = now + 0.4
                    done = not self.lanes[rid]
                    if done:
                        del self.lanes[rid]
                    events.append(
                        {"rid": rid, "tokens": chunk, "done": done}
                    )
                return events

            def cancel(self, rid):
                self.lanes.pop(rid, None)
                self.ready_at.pop(rid, None)

        return Engine()

    async def flow():
        HEALTH.reset()
        ex_slow = make_replica_executor(
            tmp_path, "hslow", task_env={"TEST_GRAY_SLOW": "1"}
        )
        ex_fast = make_replica_executor(tmp_path, "hfast")
        try:
            rset = await open_replica_set(
                [ex_slow, ex_fast], factory, name="hedge",
                stats_interval_s=0.1,
            )
            # Two concurrent requests: the tie-break spread lands one on
            # each replica, so exactly one stalls and hedges.
            requests = [
                await rset.request([100 * (i + 1)],
                                   params={"max_new_tokens": 6})
                for i in range(2)
            ]
            results = await asyncio.gather(
                *(r.result(timeout=60) for r in requests)
            )
            status = rset.status()
            hedged = [r for r in requests if r.hedged]
            await rset.close()
        finally:
            await ex_slow.close()
            await ex_fast.close()
        return results, status, hedged

    def won() -> float:
        return sum(
            c.value for labels, c in SERVE_HEDGES_TOTAL._series()
            if labels.get("outcome") == "won"
        )

    before = won()
    results, status, hedged = run_async(flow())
    # Byte-equal, exactly once: the splice dropped every duplicate chunk
    # the losing replica may have emitted before its cancel landed.
    assert list(results) == [
        [100 * (i + 1) + j + 1 for j in range(6)] for i in range(2)
    ], results
    assert len(hedged) == 1, [r.rid for r in hedged]
    assert won() == before + 1
    assert status["hedge"]["enabled"] is True
    assert status["hedge"]["issued"] >= 1
    assert status["hedge"]["wins"] >= 1
    HEALTH.reset()
