"""Chaos-driven integration tests: real dispatches, injected faults.

Each test runs the FULL executor lifecycle over the local transport (real
subprocess gangs, real staged files) with a scripted :class:`ChaosPlan`
injecting exactly the fault under test, and asserts the resilience layer's
recovery contract: transient faults are retried to success with zero local
fallbacks, timeouts kill the whole remote process group (no orphan pids),
and a quarantined connect path heals through the circuit's half-open probe.
"""

from __future__ import annotations

import asyncio
import os
import sys
import time

import pytest

from covalent_tpu_plugin.agent import AGENT_RESTARTS_TOTAL, AgentError
from covalent_tpu_plugin.obs.metrics import REGISTRY
from covalent_tpu_plugin.resilience import TASK_RETRIES_TOTAL
from covalent_tpu_plugin.transport import ChaosPlan

from .helpers import make_local_executor

METADATA = {"dispatch_id": "chaos", "node_id": 0}


def counter_value(name: str, **labels) -> float:
    metric = REGISTRY.get(name)
    if metric is None:
        return 0.0
    child = metric.labels(**labels) if labels else metric
    return child.value


def retries_total() -> float:
    metric = REGISTRY.get("covalent_tpu_task_retries_total")
    if metric is None:
        return 0.0
    return sum(child.value for _, child in metric._series())


def make_resilient_executor(tmp_path, **kwargs):
    kwargs.setdefault("max_task_retries", 2)
    kwargs.setdefault("retry_base_delay", 0.05)
    kwargs.setdefault("retry_max_delay", 0.1)
    # Prove retries (not the CPU fallback) did the recovering: the
    # fallback is ON, and the tests assert its counter never moves.
    kwargs.setdefault("run_local_on_dispatch_fail", True)
    kwargs.setdefault("poll_freq", 0.1)
    return make_local_executor(tmp_path, **kwargs)


def pid_running(pid: int) -> bool:
    """True for a live process; zombies count as dead (a killed child is a
    zombie until its reparented parent reaps it — ``os.kill(pid, 0)`` alone
    would misread that as an orphan)."""
    try:
        with open(f"/proc/{pid}/stat") as f:
            state = f.read().rsplit(") ", 1)[1].split()[0]
    except (FileNotFoundError, ProcessLookupError, IndexError):
        return False
    return state not in ("Z", "X", "x")


def assert_pid_gone(pid: int, within_s: float = 8.0) -> None:
    deadline = time.monotonic() + within_s
    while time.monotonic() < deadline:
        if not pid_running(pid):
            return
        time.sleep(0.1)
    raise AssertionError(f"pid {pid} still alive after {within_s}s")


def test_mid_run_channel_death_retried_to_success(tmp_path, run_async):
    """A channel that dies mid-poll (after submit) is retried end to end:
    gang torn down, workers redialed, artifacts re-staged via CAS, and the
    electron completes with ZERO local fallbacks."""
    plan = ChaosPlan(drop_match="if test -f", max_faults=1)
    ex = make_resilient_executor(tmp_path, chaos=plan)
    fallbacks_before = counter_value(
        "covalent_tpu_tasks_total", outcome="fallback_local"
    )
    retries_before = counter_value(
        "covalent_tpu_task_retries_total", reason="channel"
    )

    async def flow():
        try:
            return await ex.run(lambda a, b: a + b, [20, 22], {}, METADATA)
        finally:
            await ex.close()

    assert run_async(flow()) == 42
    assert plan.faults_injected == 1          # the death actually happened
    assert ex.last_attempts == 2              # one retry, then success
    assert counter_value(
        "covalent_tpu_task_retries_total", reason="channel"
    ) == retries_before + 1
    assert counter_value(
        "covalent_tpu_tasks_total", outcome="fallback_local"
    ) == fallbacks_before  # recovery came from the retry, not the fallback


def test_connect_fault_retried_through_fresh_dial(tmp_path, run_async):
    """A refused dial burns the (single-attempt) connect envelope, the
    retry driver backs off and redials, and the electron completes."""
    plan = ChaosPlan(connect_errors=1, max_faults=1)
    ex = make_resilient_executor(
        tmp_path, chaos=plan, max_connection_attempts=1
    )
    before = counter_value(
        "covalent_tpu_task_retries_total", reason="connect"
    )

    async def flow():
        try:
            return await ex.run(lambda: "ok", [], {}, METADATA)
        finally:
            await ex.close()

    assert run_async(flow()) == "ok"
    assert plan.faults_injected == 1
    assert ex.last_attempts == 2
    assert counter_value(
        "covalent_tpu_task_retries_total", reason="connect"
    ) == before + 1
    # The dial failure and the healed redial were both recorded.
    assert ex._breakers.get("localhost").state.value == "closed"


def test_truncated_upload_caught_by_digest_and_retried(tmp_path, run_async):
    """An upload truncated in flight fails the worker's CAS digest check
    (a remote exception -> permanent), but the spec re-upload on retry is
    clean.  The fault lands on the *function pickle* upload; the harness
    detects the torn artifact before unpickling."""
    plan = ChaosPlan(truncate_uploads=1, max_faults=1)
    ex = make_resilient_executor(tmp_path, chaos=plan, max_task_retries=2)
    before = retries_total()

    async def flow():
        try:
            return await ex.run(lambda: "intact", [], {}, METADATA)
        finally:
            await ex.close()

    # The torn artifact surfaces as a remote RuntimeError (digest
    # mismatch) — by design a PERMANENT fault (re-raised, not retried,
    # not fallback-swallowed): content errors must fail loud.
    with pytest.raises(RuntimeError, match="digest"):
        run_async(flow())
    assert plan.faults_injected == 1
    assert retries_total() == before  # permanent: no retry burned


def test_timeout_escalation_kills_gang_no_orphans_then_retry(
    tmp_path, run_async
):
    """task_timeout expiry kills the remote process group — harness AND the
    user function's own child — and the timeout is classified transient:
    the retried attempt completes."""
    marker = str(tmp_path / "attempted")
    child_pid_file = str(tmp_path / "child.pid")

    def sleepy_once(marker_path, pid_path):
        import os
        import subprocess
        import sys
        import time

        if os.path.exists(marker_path):
            return "second-attempt"
        with open(marker_path, "w") as f:
            f.write("x")
        child = subprocess.Popen(
            [sys.executable, "-c", "import time; time.sleep(120)"]
        )
        with open(pid_path, "w") as f:
            f.write(str(child.pid))
        time.sleep(120)

    ex = make_resilient_executor(
        tmp_path, task_timeout=4.0, max_task_retries=2
    )
    ex.TIMEOUT_KILL_GRACE_S = 0.3
    before = counter_value(
        "covalent_tpu_task_retries_total", reason="timeout"
    )

    async def flow():
        try:
            return await ex.run(
                sleepy_once, [marker, child_pid_file], {}, METADATA
            )
        finally:
            await ex.close()

    result = run_async(flow())
    assert result == "second-attempt"
    assert counter_value(
        "covalent_tpu_task_retries_total", reason="timeout"
    ) == before + 1
    # No orphans: the harness pid (pid file of attempt 1) and the user
    # function's own child are both gone.
    pid_file = tmp_path / "remote" / "pid_chaos_0.0"
    assert pid_file.exists(), "first attempt never wrote its pid file"
    assert_pid_gone(int(pid_file.read_text().strip()))
    assert os.path.exists(child_pid_file), "first attempt never spawned"
    assert_pid_gone(int(open(child_pid_file).read().strip()))


def test_cancelled_op_is_not_retried(tmp_path, run_async):
    """cancel() during a gang's run surfaces CancelledError — never a
    retry, never the local fallback re-running the body."""
    ex = make_resilient_executor(tmp_path, max_task_retries=3)
    before = retries_total()

    async def flow():
        task = asyncio.ensure_future(
            ex.run(lambda: __import__("time").sleep(60), [], {}, METADATA)
        )
        # Wait for the gang to actually launch, then cancel by base id.
        for _ in range(200):
            if ex._active:
                break
            await asyncio.sleep(0.05)
        await ex.cancel("chaos_0")
        try:
            with pytest.raises(asyncio.CancelledError):
                await task
        finally:
            await ex.close()

    run_async(flow())
    assert retries_total() == before


def test_user_cancel_racing_transient_failure_not_retried(
    tmp_path, run_async
):
    """A user cancel() landing DURING a transient failure's gang teardown
    must not be erased by it: the retry driver sees the mark and surfaces
    CancelledError instead of relaunching a cancelled electron."""
    plan = ChaosPlan(drop_match="if test -f", max_faults=1)
    ex = make_resilient_executor(tmp_path, chaos=plan, max_task_retries=3)
    real_discard = ex._discard_workers

    async def discard_then_user_cancel(conns=None):
        await real_discard(conns)
        # The user's cancel arrives while the failure handler is mid-
        # teardown, before the retry is raised.
        await ex.cancel("chaos_0")

    ex._discard_workers = discard_then_user_cancel
    before = retries_total()

    async def flow():
        try:
            with pytest.raises(asyncio.CancelledError):
                await ex.run(lambda: 42, [], {}, METADATA)
        finally:
            ex._discard_workers = real_discard
            await ex.close()

    run_async(flow())
    assert plan.faults_injected == 1
    # The retry was *counted* (the failure preceded the cancel) but never
    # executed: the driver bailed at the post-backoff cancellation check.
    assert retries_total() == before + 1
    assert "chaos_0" not in ex._cancelled_ops  # run()'s finally cleaned up


def test_four_node_fanout_survives_one_channel_death(tmp_path, run_async):
    """Acceptance: a 4-electron fan-out with exactly ONE injected channel
    death completes every node successfully with zero fallback_local
    outcomes and the retry recorded."""
    plan = ChaosPlan(drop_match="if test -f", max_faults=1)
    ex = make_resilient_executor(tmp_path, chaos=plan)
    fallbacks_before = counter_value(
        "covalent_tpu_tasks_total", outcome="fallback_local"
    )
    retries_before = retries_total()

    async def flow():
        try:
            return await asyncio.gather(
                *(
                    ex.run(
                        lambda i=i: i * 10, [],
                        {},
                        {"dispatch_id": "fan", "node_id": i},
                    )
                    for i in range(4)
                )
            )
        finally:
            await ex.close()

    results = run_async(flow())
    assert results == [0, 10, 20, 30]
    assert plan.faults_injected == 1
    assert retries_total() >= retries_before + 1
    assert counter_value(
        "covalent_tpu_tasks_total", outcome="fallback_local"
    ) == fallbacks_before


def test_cached_agent_failed_ping_restarts_agent(tmp_path, run_async):
    """Satellite: a cached agent whose channel no longer answers ping is
    discarded and restarted (counter bumped) instead of surfacing the RPC
    error to the electron."""
    ex = make_local_executor(
        tmp_path, use_agent="pool", pool_preload="cloudpickle"
    )
    restarts_before = AGENT_RESTARTS_TOTAL.value

    async def flow():
        first = await ex.run(lambda: 1, [], {}, METADATA)
        stale = ex._agents.get("localhost")
        assert stale is not None, "pool agent did not start"

        async def failing_ping(timeout=None):
            raise AgentError("agent@localhost: no event within 0.1s")

        stale.ping = failing_ping  # hung server: alive-looking, no pongs
        second = await ex.run(lambda: 2, [], {}, METADATA)
        fresh = ex._agents.get("localhost")
        await ex.close()
        return first, second, stale, fresh

    first, second, stale, fresh = run_async(flow())
    assert (first, second) == (1, 2)
    assert fresh is not None and fresh is not stale  # genuinely restarted
    assert AGENT_RESTARTS_TOTAL.value == restarts_before + 1


def test_preempt_after_sigterm_then_grace_then_drop(tmp_path, run_async):
    """The ``preempt_after`` primitive models a TPU spot reclaim: SIGTERM
    reaches the registered worker's process group on the Nth op, ops keep
    working inside the grace window (the cooperative-checkpoint window),
    then the channel drops — counted under ``chaos_faults_total``."""
    import signal
    import subprocess

    from covalent_tpu_plugin.transport import ChaosTransport, LocalTransport
    from covalent_tpu_plugin.transport.base import TransportError
    from covalent_tpu_plugin.transport.chaos import plan_from_spec

    plan = plan_from_spec(
        "preempt_after=2,preempt_grace=0.4,max_faults=1"
    )
    assert plan is not None and plan.active
    faults_before = counter_value(
        "covalent_tpu_chaos_faults_total", kind="preempt"
    )
    worker = subprocess.Popen(
        [sys.executable, "-c", "import time; time.sleep(60)"],
        start_new_session=True,
    )
    conn = ChaosTransport(LocalTransport(), plan)
    conn.chaos_notify_pid(worker.pid)

    async def flow():
        await conn.run("echo one")  # op 1
        await conn.run("echo two")  # op 2
        await conn.run("echo notice")  # op 3: fault fires, channel alive
        inside_grace = await conn.run("echo still-here")  # grace window
        await asyncio.sleep(0.5)  # grace elapses
        with pytest.raises(TransportError):
            await conn.run("echo gone")
        return inside_grace

    try:
        inside_grace = run_async(flow())
        assert inside_grace.exit_status == 0
        worker.wait(timeout=10)
        # SIGTERM (not KILL): the notice the harness's handler can act on.
        assert worker.returncode == -signal.SIGTERM
        assert counter_value(
            "covalent_tpu_chaos_faults_total", kind="preempt"
        ) == faults_before + 1
    finally:
        if worker.poll() is None:
            worker.kill()
            worker.wait()


# ---------------------------------------------------------------------------
# gray modes: alive-but-degraded fault injection


def test_gray_spec_parses_and_is_seed_deterministic():
    """The gray keys ride the same spec grammar, and two plans with the
    same seed replay the identical probabilistic fault sequence — a
    flaky brownout is worthless as a regression fixture."""
    from covalent_tpu_plugin.transport.chaos import plan_from_spec

    plan = plan_from_spec(
        "seed=11,jitter=0.02,p_slow=0.5,slow_factor=3,p_drop_op=0.1"
    )
    assert plan.seed == 11
    assert plan.jitter == pytest.approx(0.02)
    assert plan.p_slow == pytest.approx(0.5)
    assert plan.slow_factor == pytest.approx(3.0)
    assert plan.p_drop_op == pytest.approx(0.1)
    assert plan.active
    # slow tail = slow_factor x max(delay, jitter, 0.01).
    assert plan.slow_tail_s() == pytest.approx(3 * 0.02)
    twin = plan_from_spec(
        "seed=11,jitter=0.02,p_slow=0.5,slow_factor=3,p_drop_op=0.1"
    )
    assert [plan.rng.random() for _ in range(16)] == [
        twin.rng.random() for _ in range(16)
    ]
    with pytest.raises(ValueError):
        plan_from_spec("jittery=0.02")  # typos fail loudly, not silently


def test_gray_p_drop_op_fails_op_but_channel_survives(run_async):
    """Lossy-but-alive: a dropped op raises, the NEXT op on the same
    transport works — no channel death, no breaker trip by itself."""
    from covalent_tpu_plugin.transport.base import TransportError
    from covalent_tpu_plugin.transport.chaos import ChaosPlan, ChaosTransport

    class Inner:
        address = "fake-host"

    plan = ChaosPlan(seed=3, p_drop_op=1.0, max_faults=1)
    chaos = ChaosTransport(Inner(), plan)
    faults_before = counter_value(
        "covalent_tpu_chaos_faults_total", kind="drop_op"
    )

    async def flow():
        with pytest.raises(TransportError):
            await chaos._gate("run", "echo a")
        assert not chaos.dead
        # Budget spent: the channel keeps working from here on.
        await chaos._gate("run", "echo b")
        await chaos._gate("run", "echo c")

    run_async(flow())
    assert counter_value(
        "covalent_tpu_chaos_faults_total", kind="drop_op"
    ) == faults_before + 1


def test_gray_p_slow_sleeps_heavy_tail_and_completes(run_async):
    """The p_slow heavy tail delays the op (slow_factor x jitter floor)
    without failing it — the brownout a binary breaker never sees."""
    from covalent_tpu_plugin.transport.chaos import ChaosPlan, ChaosTransport

    class Inner:
        address = "fake-host"

    plan = ChaosPlan(seed=5, p_slow=1.0, slow_factor=5, max_faults=1)
    chaos = ChaosTransport(Inner(), plan)
    assert plan.slow_tail_s() == pytest.approx(0.05)  # 5 x 0.01 floor

    async def flow():
        t0 = time.monotonic()
        await chaos._gate("run", "echo slow")
        return time.monotonic() - t0

    elapsed = run_async(flow())
    assert elapsed >= 0.05
    assert not chaos.dead


def test_worker_side_gray_plan_parses_only_gray_keys(monkeypatch):
    """The harness's decode-loop brownout reads the SAME env spec but
    only its gray keys: transport-only keys are ignored (not rejected —
    they are the transport's to validate), and a spec with no gray mode
    yields no plan at all."""
    from covalent_tpu_plugin.harness import _gray_chaos_from_env

    monkeypatch.setenv(
        "COVALENT_TPU_CHAOS",
        "seed=7,jitter=0.02,p_slow=0.6,slow_factor=40,drop_match=if test",
    )
    gray = _gray_chaos_from_env()
    assert gray is not None
    assert gray["jitter"] == pytest.approx(0.02)
    assert gray["p_slow"] == pytest.approx(0.6)
    assert gray["slow_s"] == pytest.approx(40 * 0.02)
    # Seeded: two parses replay the same sequence.
    twin = _gray_chaos_from_env()
    assert [gray["rng"].random() for _ in range(8)] == [
        twin["rng"].random() for _ in range(8)
    ]
    monkeypatch.setenv("COVALENT_TPU_CHAOS", "drop_match=if test -f")
    assert _gray_chaos_from_env() is None
    monkeypatch.delenv("COVALENT_TPU_CHAOS")
    assert _gray_chaos_from_env() is None
