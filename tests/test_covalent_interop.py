"""Covalent-present interop tier (VERDICT r1 next-round #7).

The reference CI's gate is importing the plugin through a live Covalent
server's loader (``/root/reference/.github/workflows/tests.yml:80-84``).
Covalent cannot be installed in this sandbox, so a shared stub ``covalent``
package (``tests/covalent_stub.py`` — the same pattern as the stub-asyncssh
transport tier) stands in, consumed two ways: an in-process fixture that
reloads ``executor_base``/``utils.config`` with their covalent-present
branches live, and a subprocess that installs the stub before first import
and runs one electron end-to-end with ``TPUExecutor`` subclassing the
*Covalent* template class.
"""

from __future__ import annotations

import importlib
import os
import sys
import types

import pytest

from .covalent_stub import FakeRemoteExecutor, build_modules


@pytest.fixture()
def covalent_stub(monkeypatch):
    """Install the fake `covalent` package and reload the interop modules."""
    store: dict[str, object] = {"executors.tpu.remote_workdir": "from-covalent-config"}
    modules = build_modules(store)
    for name, module in modules.items():
        monkeypatch.setitem(sys.modules, name, module)

    import covalent_tpu_plugin.executor_base as eb
    import covalent_tpu_plugin.utils.config as cfg

    importlib.reload(eb)
    importlib.reload(cfg)
    try:
        yield types.SimpleNamespace(store=store, eb=eb, cfg=cfg)
    finally:
        for name in modules:
            sys.modules.pop(name, None)
        importlib.reload(eb)
        importlib.reload(cfg)
        assert not eb.HAVE_COVALENT  # sandbox ground state restored


def test_executor_base_uses_covalent_template(covalent_stub):
    assert covalent_stub.eb.HAVE_COVALENT
    assert covalent_stub.eb.RemoteExecutor is FakeRemoteExecutor


def test_config_delegates_to_covalent(covalent_stub):
    cfg = covalent_stub.cfg
    assert cfg._HAVE_COVALENT
    assert cfg.get_config("executors.tpu.remote_workdir") == "from-covalent-config"
    assert cfg.get_config("executors.tpu.missing", "fallback") == "fallback"
    cfg.set_config("executors.tpu.poll_freq", 0.25)
    assert covalent_stub.store["executors.tpu.poll_freq"] == 0.25
    cfg.update_config({"new_key": "v"}, section="executors.tpu")
    assert covalent_stub.store["executors.tpu.new_key"] == "v"


_E2E_SCRIPT = r"""
import asyncio, sys

from tests.covalent_stub import FakeRemoteExecutor, install

store = {"executors.tpu.remote_workdir": "from-covalent-config"}
install(store)

# Imported AFTER the stub is in place: the covalent-present branches load.
from covalent_tpu_plugin import TPUExecutor  # noqa: E402

assert issubclass(TPUExecutor, FakeRemoteExecutor), TPUExecutor.__mro__
# Plugin-loader contract: defaults were merged into covalent's config.
assert store["executors.tpu.poll_freq"] == 0.5, store

tmp = sys.argv[1]
ex = TPUExecutor(
    transport="local",
    cache_dir=f"{tmp}/cache",
    remote_cache=f"{tmp}/remote",
    python_path=sys.executable,
    poll_freq=0.1,
    use_agent=False,
    task_env={"JAX_PLATFORMS": "cpu"},
)
assert ex.template_init_ran  # Covalent template __init__ really ran
# Config chain: unset ctor arg -> covalent's get_config wins.
assert ex.remote_workdir == "from-covalent-config", ex.remote_workdir


async def flow():
    result = await ex.run(
        lambda a, b: a * b, [6, 7], {}, {"dispatch_id": "cov", "node_id": 0}
    )
    await ex.close()
    return result


assert asyncio.run(flow()) == 42
print("INTEROP-E2E-OK")
"""


def test_electron_end_to_end_on_covalent_template(tmp_path):
    """TPUExecutor subclassing Covalent's own RemoteExecutor runs a full
    electron — what a live dispatcher would drive.  Runs in a subprocess:
    installing the stub before first import flips every covalent-present
    branch without reloading modules under an in-flight test session."""
    import pathlib
    import subprocess

    repo_root = str(pathlib.Path(__file__).resolve().parents[1])
    env = dict(os.environ)
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("COVALENT_TPU_CONFIG", str(tmp_path / "unused.toml"))
    proc = subprocess.run(
        [sys.executable, "-c", _E2E_SCRIPT, str(tmp_path)],
        capture_output=True,
        text=True,
        timeout=240,
        env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "INTEROP-E2E-OK" in proc.stdout


def test_entry_point_declared_for_covalent_loader():
    """setup.py must register the plugin in Covalent's entry-point group
    (reference setup.py:36, 74-76)."""
    import re
    from pathlib import Path

    setup_src = Path(__file__).resolve().parents[1].joinpath("setup.py").read_text()
    assert "covalent.executor.executor_plugins" in setup_src
    assert re.search(r"tpu\s*=\s*covalent_tpu_plugin\.tpu", setup_src)


def test_plugin_identity_globals():
    """The loader keys on EXECUTOR_PLUGIN_NAME + defaults dict (ssh.py:34-50)."""
    import covalent_tpu_plugin.tpu as tpu_mod

    assert tpu_mod.EXECUTOR_PLUGIN_NAME == "TPUExecutor"
    assert isinstance(tpu_mod._EXECUTOR_PLUGIN_DEFAULTS, dict)
    assert "remote_workdir" in tpu_mod._EXECUTOR_PLUGIN_DEFAULTS
