"""Persistent serving tier: session protocol, streaming, resilience.

Protocol-level coverage of the ``serve_open``/``serve_request``/
``serve_close`` verbs on the Python pool server (the native C++ agent's
analog lives in ``test_agent.py``), plus the dispatcher-side
:class:`ServeHandle` lifecycle: concurrent callers multiplexed onto one
session, incremental token streams with real TTFT, bounded-queue
backpressure classified PERMANENT, per-request deadlines, the kill-mid-
stream reconnect with exactly-once token delivery, fleet capacity
pinning, and the oversized streamed-result staging policy.

The engines here are closure-local stubs implementing the harness's
duck-typed serving surface (``slots``/``admit``/``step``/``cancel``) —
the real LM engine (``models/serve.ContinuousEngine``) is covered
against the decode oracle in ``test_continuous.py``.
"""

import asyncio
import sys
import time

import cloudpickle
import pytest

from covalent_tpu_plugin import TPUExecutor
from covalent_tpu_plugin.agent import AgentError, start_pool_server
from covalent_tpu_plugin.cache import bytes_digest
from covalent_tpu_plugin.fleet.pools import Pool, PoolSpec
from covalent_tpu_plugin.obs import events as obs_events
from covalent_tpu_plugin.obs.metrics import REGISTRY
from covalent_tpu_plugin.resilience import FaultClass, classify_error
from covalent_tpu_plugin.serving import (
    ServeError,
    ServeRequestRejected,
    open_session,
)
from covalent_tpu_plugin.transport import LocalTransport

from .helpers import pin_cpu_task_env


def make_serve_executor(tmp_path, **kwargs):
    kwargs.setdefault("transport", "local")
    kwargs.setdefault("cache_dir", str(tmp_path / "cache"))
    kwargs.setdefault("remote_cache", str(tmp_path / "remote"))
    kwargs.setdefault("python_path", sys.executable)
    kwargs.setdefault("poll_freq", 0.2)
    kwargs.setdefault("use_agent", "pool")
    kwargs.setdefault("heartbeat_interval", 0.0)
    kwargs.setdefault("prewarm", False)
    return TPUExecutor(**pin_cpu_task_env(kwargs))


def make_factory(step_delay=0.0, slots=2, chunk=2, default_cap=6):
    """A stub serving engine, cloudpickled BY VALUE (closure-local class:
    the resident worker cannot import the tests package).  Deterministic
    streams — prompt ``[..., base]`` yields ``base+1, base+2, ...`` — so
    replay splices are byte-checkable."""

    def factory():
        import time as time_mod

        class Engine:
            def __init__(self):
                self.slots = slots
                self.lanes = {}

            def admit(self, rid, prompt, params):
                cap = int((params or {}).get("max_new_tokens", default_cap))
                base = int(prompt[-1])
                self.lanes[rid] = [base + i + 1 for i in range(cap)]

            def step(self):
                if step_delay:
                    time_mod.sleep(step_delay)
                events = []
                for rid in list(self.lanes):
                    taken = self.lanes[rid][:chunk]
                    self.lanes[rid] = self.lanes[rid][chunk:]
                    done = not self.lanes[rid]
                    if done:
                        del self.lanes[rid]
                    events.append(
                        {"rid": rid, "tokens": taken, "done": done}
                    )
                return events

            def cancel(self, rid):
                self.lanes.pop(rid, None)

        return Engine()

    return factory


def make_unsupported_factory():
    """A factory refusing its model shape with the duck-typed permanence
    tag — the shape ``models/serve.RollingCacheUnsupported`` carries."""

    def factory():
        class ModelUnsupported(ValueError):
            fault_label = "serve_model_unsupported"
            fault_transient = False

        raise ModelUnsupported("rolling_cache models are not servable")

    return factory


def stage_factory(tmp_path, factory):
    payload = cloudpickle.dumps(factory)
    digest = bytes_digest(payload)
    path = tmp_path / f"{digest}.pkl"
    path.write_bytes(payload)
    return digest, str(path)


def gauge_value(name: str, **labels) -> float:
    metric = REGISTRY.get(name)
    if metric is None:
        return 0.0
    for series_labels, gauge in metric._series():
        if all(series_labels.get(k) == v for k, v in labels.items()):
            return gauge.value
    return 0.0


async def drain_until(records, predicate, timeout=15.0):
    """Await the first side-band record satisfying ``predicate``."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        for record in records:
            if predicate(record):
                return record
        await asyncio.sleep(0.02)
    raise AssertionError(f"no matching record in {records}")


# ---------------------------------------------------------------------------
# Protocol level: the pool server's session verbs over a real channel
# ---------------------------------------------------------------------------


def test_pool_serve_open_request_close_roundtrip(tmp_path, run_async):
    """The whole session protocol against the real forkserver: open by
    digest, stream one request's chunks (cumulative ``idx`` contract),
    drain-close with the served count."""

    async def flow():
        client = await start_pool_server(
            LocalTransport(), str(tmp_path / "remote"), sys.executable
        )
        records: list = []
        try:
            digest, path = stage_factory(tmp_path, make_factory())
            client.watch_serve("s1", lambda sid, data: records.append(data))
            opened = await client.serve_open(
                "s1", digest, path,
                options={"stats_interval_s": 0.1}, timeout=30.0,
            )
            await client.serve_request(
                "s1", "r1", [5], params={"max_new_tokens": 4}
            )
            final = await drain_until(
                records,
                lambda r: r.get("type") == "serve.token" and r.get("done"),
            )
            stats = await drain_until(
                records, lambda r: r.get("type") == "serve.stats"
            )
            closed = await client.serve_close("s1", timeout=15.0)
        finally:
            await client.close()
        return opened, records, final, stats, closed

    opened, records, final, stats, closed = run_async(flow())
    assert opened["slots"] == 2 and opened["pid"] > 0
    chunks = [r for r in records if r.get("type") == "serve.token"]
    streamed: list = []
    for chunk in chunks:
        assert chunk["rid"] == "r1"
        assert chunk["idx"] == len(streamed)  # cumulative-before-chunk
        streamed.extend(chunk["tokens"])
    assert streamed == [6, 7, 8, 9]
    assert final["done"] is True
    assert stats["slots"] == 2 and stats["served"] in (0, 1)
    assert closed["served"] == 1


def test_pool_serve_unknown_session_and_duplicate(tmp_path, run_async):
    """Requests against a sid that was never opened fail fast as streamed
    rejects; closing one errors; double-open is refused PERMANENT."""

    async def flow():
        client = await start_pool_server(
            LocalTransport(), str(tmp_path / "remote"), sys.executable
        )
        records: list = []
        try:
            client.watch_serve(
                "ghost", lambda sid, data: records.append(data)
            )
            await client.serve_request("ghost", "r0", [1])
            reject = await drain_until(
                records, lambda r: r.get("type") == "serve.reject"
            )
            with pytest.raises(AgentError, match="unknown_session"):
                await client.serve_close("ghost", timeout=10.0)
            digest, path = stage_factory(tmp_path, make_factory())
            await client.serve_open("dup", digest, path, timeout=30.0)
            with pytest.raises(AgentError, match="duplicate") as dup:
                await client.serve_open("dup", digest, path, timeout=30.0)
            await client.serve_close("dup", timeout=15.0)
        finally:
            await client.close()
        return reject, dup.value

    reject, dup_error = run_async(flow())
    assert reject["code"] == "unknown_session"
    assert reject["rid"] == "r0"
    fault, _ = classify_error(dup_error)
    assert fault is FaultClass.PERMANENT


def test_pool_serve_session_survives_unrelated_forget(tmp_path, run_async):
    """``forget()`` of an unrelated electron's state must not disturb an
    open session's sink, seq-dedup, or streams."""

    async def flow():
        client = await start_pool_server(
            LocalTransport(), str(tmp_path / "remote"), sys.executable
        )
        records: list = []
        try:
            digest, path = stage_factory(tmp_path, make_factory())
            client.watch_serve("s1", lambda sid, data: records.append(data))
            await client.serve_open("s1", digest, path, timeout=30.0)
            await client.serve_request(
                "s1", "r1", [10], params={"max_new_tokens": 2}
            )
            await drain_until(
                records,
                lambda r: r.get("type") == "serve.token" and r.get("done"),
            )
            # An unrelated electron leaving the executor's books.
            client.forget("some-finished-electron")
            client.unwatch_serve("some-other-session")
            await client.serve_request(
                "s1", "r2", [20], params={"max_new_tokens": 2}
            )
            await drain_until(
                records,
                lambda r: r.get("type") == "serve.token"
                and r.get("rid") == "r2" and r.get("done"),
            )
            closed = await client.serve_close("s1", timeout=15.0)
        finally:
            await client.close()
        return records, closed

    records, closed = run_async(flow())
    tokens = {
        r["rid"]: r for r in records
        if r.get("type") == "serve.token" and r.get("done")
    }
    assert set(tokens) == {"r1", "r2"}
    assert closed["served"] == 2


def test_pool_serve_open_digest_mismatch_permanent(tmp_path, run_async):
    """A factory artifact that fails its sha256 check is refused before
    unpickling and classifies PERMANENT — no gang retries."""

    async def flow():
        client = await start_pool_server(
            LocalTransport(), str(tmp_path / "remote"), sys.executable
        )
        try:
            _digest, path = stage_factory(tmp_path, make_factory())
            wrong = bytes_digest(b"entirely different bytes")
            with pytest.raises(AgentError, match="digest_mismatch") as info:
                await client.serve_open("bad", wrong, path, timeout=30.0)
        finally:
            await client.close()
        return info.value

    error = run_async(flow())
    fault, label = classify_error(error)
    assert fault is FaultClass.PERMANENT
    assert label == "serve_digest_mismatch"


def test_pool_serve_factory_fault_label_is_permanent(tmp_path, run_async):
    """A factory refusing its model shape (RollingCacheUnsupported's
    duck tag) surfaces through the RPC as a PERMANENT fault with the
    factory's own label — a misconfigured session is refused once."""

    async def flow():
        client = await start_pool_server(
            LocalTransport(), str(tmp_path / "remote"), sys.executable
        )
        try:
            digest, path = stage_factory(
                tmp_path, make_unsupported_factory()
            )
            with pytest.raises(AgentError, match="factory_failed") as info:
                await client.serve_open("unsup", digest, path, timeout=30.0)
        finally:
            await client.close()
        return info.value

    error = run_async(flow())
    fault, label = classify_error(error)
    assert fault is FaultClass.PERMANENT
    assert label == "serve_model_unsupported"


# ---------------------------------------------------------------------------
# Handle level: ServeHandle through the executor
# ---------------------------------------------------------------------------


def test_serve_handle_streams_concurrent_requests(tmp_path, run_async):
    """Five concurrent callers through one session: every stream lands
    deterministically, TTFT <= full latency, the live session shows on
    the executor's status view, close reports the served count."""

    async def flow():
        ex = make_serve_executor(tmp_path)
        try:
            handle = await open_session(
                ex, make_factory(), stats_interval_s=0.1
            )
            requests = [
                await handle.request([10 * i], params={"max_new_tokens": 4})
                for i in range(5)
            ]
            results = [await r.result(timeout=30) for r in requests]
            ttfts = [r.ttft_s for r in requests]
            latencies = [r.latency_s for r in requests]
            view = dict(ex.serve_sessions())
            state = handle.state
            closed = await handle.close()
            post_view = dict(ex.serve_sessions())
        finally:
            await ex.close()
        return (
            handle.sid, requests, results, ttfts, latencies, view, state,
            closed, post_view,
        )

    sid, requests, results, ttfts, latencies, view, state, closed, post = (
        run_async(flow())
    )
    for i, tokens in enumerate(results):
        assert tokens == [10 * i + j + 1 for j in range(4)]
    assert all(t is not None for t in ttfts)
    assert all(t <= lat for t, lat in zip(ttfts, latencies))
    assert state == "open"
    assert view[sid]["state"] == "open" and view[sid]["slots"] == 2
    assert closed["served"] == 5
    assert sid not in post


def test_serve_handle_stream_iterator_yields_chunks(tmp_path, run_async):
    """``stream()`` delivers the chunks incrementally, in order."""

    async def flow():
        ex = make_serve_executor(tmp_path)
        try:
            handle = await open_session(ex, make_factory(chunk=2))
            request = await handle.request(
                [100], params={"max_new_tokens": 6}
            )
            chunks = [chunk async for chunk in request.stream()]
            await handle.close()
        finally:
            await ex.close()
        return chunks

    chunks = run_async(flow())
    assert [t for chunk in chunks for t in chunk] == [
        101, 102, 103, 104, 105, 106
    ]
    assert all(len(chunk) <= 2 for chunk in chunks)
    assert len(chunks) >= 3


def test_serve_kill_mid_stream_reconnects_exactly_once(tmp_path, run_async):
    """The chaos contract: SIGKILL the resident server mid-stream; the
    supervisor classifies transient, re-opens on a fresh gang, replays
    in-flight requests, and the idx splice hands every caller each token
    EXACTLY once — no duplicates, none lost.  The handle stays usable."""

    async def flow():
        ex = make_serve_executor(
            tmp_path, retry_base_delay=0.05, retry_max_delay=0.2
        )
        try:
            handle = await open_session(
                ex,
                make_factory(step_delay=0.1, default_cap=12),
                retries=2,
            )
            requests = [await handle.request([100 * i]) for i in range(3)]
            for _ in range(200):
                if all(len(r.tokens) >= 4 for r in requests):
                    break
                await asyncio.sleep(0.05)
            assert all(len(r.tokens) >= 4 for r in requests), (
                [r.tokens for r in requests])
            ex._agents["localhost"]._process._proc.kill()
            results = [await r.result(timeout=60) for r in requests]
            reconnects = handle.reconnects
            state = handle.state
            late = await handle.request([7], params={"max_new_tokens": 3})
            late_result = await late.result(timeout=30)
            await handle.close()
        finally:
            await ex.close()
        return results, reconnects, state, late_result

    results, reconnects, state, late_result = run_async(flow())
    for i, tokens in enumerate(results):
        assert tokens == [100 * i + j + 1 for j in range(12)], tokens
    assert reconnects == 1
    assert state == "open"
    assert late_result == [8, 9, 10]


def test_serve_admission_shed_is_permanent(tmp_path, run_async):
    """A bounded queue refusing work sheds it immediately; the rejection
    classifies PERMANENT under ``serve_admission_shed`` (a gang retry
    would amplify exactly the overload that shed the work)."""

    async def flow():
        ex = make_serve_executor(tmp_path)
        try:
            handle = await open_session(
                ex,
                make_factory(step_delay=0.2, slots=1, default_cap=6),
                queue_max=1,
            )
            requests = [await handle.request([10 * i]) for i in range(6)]
            outcomes = await asyncio.gather(
                *(r.result(timeout=60) for r in requests),
                return_exceptions=True,
            )
            await handle.close()
        finally:
            await ex.close()
        return outcomes

    outcomes = run_async(flow())
    sheds = [o for o in outcomes if isinstance(o, ServeRequestRejected)]
    completions = [o for o in outcomes if isinstance(o, list)]
    assert sheds, outcomes
    assert completions, outcomes
    for shed in sheds:
        assert shed.code == "serve_admission_shed"
        fault, label = classify_error(shed)
        assert fault is FaultClass.PERMANENT
        assert label == "serve_admission_shed"


def test_serve_request_deadline_reclaims_lane(tmp_path, run_async):
    """A request past its deadline mid-generation completes with the
    partial stream and the ``deadline_exceeded`` marker — the lane is
    reclaimed, not wedged."""

    async def flow():
        ex = make_serve_executor(tmp_path)
        try:
            handle = await open_session(
                ex,
                make_factory(step_delay=0.15, slots=1, chunk=1,
                             default_cap=40),
            )
            request = await handle.request([0], deadline_s=0.5)
            tokens = await request.result(timeout=30)
            error = request.error
            # The freed lane must admit the next request.
            follow = await handle.request(
                [50], params={"max_new_tokens": 2}, deadline_s=30.0
            )
            follow_tokens = await follow.result(timeout=30)
            await handle.close()
        finally:
            await ex.close()
        return tokens, error, follow_tokens

    tokens, error, follow_tokens = run_async(flow())
    assert error == "deadline_exceeded"
    assert 0 < len(tokens) < 40
    assert tokens == [i + 1 for i in range(len(tokens))]
    assert follow_tokens == [51, 52]


def test_serve_session_pins_fleet_capacity(tmp_path, run_async):
    """Opened through a fleet pool, a session occupies one capacity slot
    for its lifetime (placement bin-packs around it) and its live view
    rides ``pool.status()``; close releases the slot."""

    async def flow():
        ex = make_serve_executor(tmp_path)
        pool = Pool(
            PoolSpec(name="srv", capacity=2, transport="local"),
            executor=ex,
        )
        try:
            handle = await pool.open_session(make_factory())
            in_use_open = pool.in_use
            status = pool.status()
            await handle.close()
            in_use_closed = pool.in_use
        finally:
            await ex.close()
        return handle.sid, in_use_open, status, in_use_closed

    sid, in_use_open, status, in_use_closed = run_async(flow())
    assert in_use_open == 1
    assert in_use_closed == 0
    assert status["in_use"] == 1
    assert status["serve_sessions"][sid]["state"] == "open"


def test_serve_failed_open_does_not_leak_capacity(tmp_path, run_async):
    """A refused open (permanent factory fault) must release nothing it
    never pinned: pool slots and the live-session gauge stay level."""

    async def flow():
        ex = make_serve_executor(tmp_path)
        pool = Pool(
            PoolSpec(name="srv", capacity=2, transport="local"),
            executor=ex,
        )
        sessions0 = gauge_value("covalent_tpu_serve_sessions")
        try:
            with pytest.raises(AgentError):
                await pool.open_session(make_unsupported_factory())
            in_use = pool.in_use
            sessions1 = gauge_value("covalent_tpu_serve_sessions")
            views = dict(ex.serve_sessions())
        finally:
            await ex.close()
        return in_use, sessions0, sessions1, views

    in_use, sessions0, sessions1, views = run_async(flow())
    assert in_use == 0
    assert sessions1 == sessions0
    assert views == {}


# ---------------------------------------------------------------------------
# Satellite: the inline-vs-CAS size policy applies to streamed results
# ---------------------------------------------------------------------------


def test_oversized_rpc_result_stages_instead_of_inlining(
    tmp_path, run_async
):
    """A result pickle over ``rpc_inline_args_max`` takes the staged road
    (remote file + sha256 announce) instead of one multi-MB base64 write
    on the channel — and still arrives intact."""

    staged_events: list = []

    def listener(event: dict) -> None:
        if event.get("type") == "task.rpc_result_staged":
            staged_events.append(event)

    def big_result(n):
        return bytes(range(256)) * n

    async def flow():
        ex = make_serve_executor(
            tmp_path, dispatch_mode="rpc", rpc_inline_args_max=1024
        )
        try:
            big = await ex.run(
                big_result, [2048], {},
                {"dispatch_id": "stage", "node_id": 0},
            )
            small = await ex.run(
                big_result, [1], {},
                {"dispatch_id": "inline", "node_id": 1},
            )
            mode = ex.last_dispatch_mode
        finally:
            await ex.close()
        return big, small, mode

    obs_events.add_listener(listener)
    try:
        big, small, mode = run_async(flow())
    finally:
        obs_events.remove_listener(listener)
    assert mode == "rpc"
    assert big == bytes(range(256)) * 2048
    assert small == bytes(range(256))
    # Exactly the oversized result staged; the small one rode inline.
    assert len(staged_events) == 1
    assert staged_events[0]["bytes"] > 1024


# ---------------------------------------------------------------------------
# Satellite: heartbeat backhaul carries serving slot occupancy
# ---------------------------------------------------------------------------


def test_serve_occupancy_rides_heartbeats():
    """Worker side: live sessions fold into every beat's ``serve`` block;
    dispatcher side: a fresh beat moves the per-worker occupancy gauges."""
    from covalent_tpu_plugin import harness

    class FakeQueue:
        def qsize(self):
            return 3

    class FakeSession:
        slots = 4
        running = {"r1": {}, "r2": {}}
        queue = FakeQueue()

    harness._SERVE_SESSIONS["fake"] = FakeSession()
    try:
        occupancy = harness._serve_occupancy()
    finally:
        harness._SERVE_SESSIONS.pop("fake", None)
    assert occupancy == {
        "sessions": 1, "slots": 4, "busy": 2, "queued": 3,
    }
    assert harness._serve_occupancy() == {}  # no sessions -> no block

    ex = TPUExecutor.__new__(TPUExecutor)  # gauge path needs no init
    ex._record_heartbeat(
        "op-serve", "worker9",
        {"type": "worker.heartbeat", "seq": 1, "pid": 1, "ts": 1.0,
         "serve": {"sessions": 1, "slots": 4, "busy": 2, "queued": 3}},
    )
    assert gauge_value(
        "covalent_tpu_serve_worker_slots", worker="worker9", state="busy"
    ) == 2.0
    assert gauge_value(
        "covalent_tpu_serve_worker_slots", worker="worker9", state="queued"
    ) == 3.0


def test_serve_metrics_move_with_traffic(tmp_path, run_async):
    """The obs registry's serving series move with real traffic: request
    outcomes, streamed tokens, TTFT observations, session gauge."""

    def counter_value(name: str, **labels) -> float:
        metric = REGISTRY.get(name)
        if metric is None:
            return 0.0
        total = 0.0
        for series_labels, counter in metric._series():
            if all(series_labels.get(k) == v for k, v in labels.items()):
                total += counter.value
        return total

    async def flow():
        ex = make_serve_executor(tmp_path)
        ok0 = counter_value(
            "covalent_tpu_serve_requests_total", outcome="ok"
        )
        tokens0 = counter_value("covalent_tpu_serve_tokens_total")
        try:
            handle = await open_session(
                ex, make_factory(), stats_interval_s=0.1
            )
            live_during = gauge_value("covalent_tpu_serve_sessions")
            requests = [
                await handle.request([0], params={"max_new_tokens": 4})
                for _ in range(2)
            ]
            for request in requests:
                await request.result(timeout=30)
            await asyncio.sleep(0.3)  # let a stats record land
            queue_depth = gauge_value(
                "covalent_tpu_serve_queue_depth", session=handle.sid
            )
            await handle.close()
        finally:
            await ex.close()
        return (
            counter_value(
                "covalent_tpu_serve_requests_total", outcome="ok"
            ) - ok0,
            counter_value("covalent_tpu_serve_tokens_total") - tokens0,
            live_during,
            queue_depth,
        )

    ok_delta, tokens_delta, live_during, queue_depth = run_async(flow())
    assert ok_delta == 2
    assert tokens_delta == 8
    assert live_during >= 1
    assert queue_depth == 0


def test_serve_error_when_agent_disabled(tmp_path, run_async):
    """Serving needs the resident runtime: a no-agent executor refuses
    the open with a clear error instead of wedging."""

    async def flow():
        ex = make_serve_executor(tmp_path, use_agent=False)
        try:
            with pytest.raises((AgentError, ServeError)):
                await open_session(ex, make_factory())
        finally:
            await ex.close()

    run_async(flow())


def test_pool_serve_failed_open_sid_is_reopenable(tmp_path, run_async):
    """A session whose factory failed leaves no tombstone: re-opening the
    SAME sid on the same live pool server must succeed — the reconnect
    path retries sid.gN verbatim, and a stale dead entry refusing it as
    'duplicate' (PERMANENT) would abort the whole retry loop."""

    async def flow():
        client = await start_pool_server(
            LocalTransport(), str(tmp_path / "remote"), sys.executable
        )
        records: list = []
        try:
            bad_digest, bad_path = stage_factory(
                tmp_path, make_unsupported_factory()
            )
            with pytest.raises(AgentError, match="factory_failed"):
                await client.serve_open("s1", bad_digest, bad_path,
                                        timeout=30.0)
            digest, path = stage_factory(tmp_path, make_factory())
            client.watch_serve("s1", lambda sid, data: records.append(data))
            opened = await client.serve_open("s1", digest, path, timeout=30.0)
            await client.serve_request(
                "s1", "r1", [3], params={"max_new_tokens": 2}
            )
            await drain_until(
                records,
                lambda r: r.get("type") == "serve.token" and r.get("done"),
            )
            closed = await client.serve_close("s1", timeout=15.0)
        finally:
            await client.close()
        return opened, closed

    opened, closed = run_async(flow())
    assert opened["slots"] == 2
    assert closed["served"] == 1


def test_serve_warm_handoff_zero_dropped_tokens(tmp_path, run_async):
    """Planned churn: handoff() opens the replacement session BEFORE
    retiring the old one and splices in-flight streams on the idx replay —
    byte-equal results, exactly-once, no reconnect event, handle usable
    on the new generation."""

    async def flow():
        ex = make_serve_executor(tmp_path)
        try:
            handle = await open_session(
                ex, make_factory(step_delay=0.1, default_cap=12)
            )
            requests = [await handle.request([100 * i]) for i in range(3)]
            for _ in range(200):
                if all(len(r.tokens) >= 4 for r in requests):
                    break
                await asyncio.sleep(0.05)
            assert all(len(r.tokens) >= 4 for r in requests)
            moved = await handle.handoff(reason="test")
            results = [await r.result(timeout=60) for r in requests]
            stats = (
                moved, handle.handoffs, handle.generation,
                handle.reconnects, handle.state,
            )
            late = await handle.request([7], params={"max_new_tokens": 3})
            late_result = await late.result(timeout=30)
            await handle.close()
        finally:
            await ex.close()
        return results, stats, late_result

    results, stats, late_result = run_async(flow())
    moved, handoffs, generation, reconnects, state = stats
    assert moved is True
    for i, tokens in enumerate(results):
        assert tokens == [100 * i + j + 1 for j in range(12)], tokens
    assert handoffs == 1
    assert generation == 2  # the replacement generation took over
    assert reconnects == 0  # warm path, not the death path
    assert state == "open"
    assert late_result == [8, 9, 10]


def test_serve_preempt_notice_triggers_auto_handoff(tmp_path, run_async):
    """SIGTERM on the serving runtime (the spot preemption notice): the
    worker announces ``serve.preempt`` on the side-band and KEEPS serving;
    the supervisor warm-hands the session off inside the grace window —
    streams stay byte-equal and exactly-once."""
    import os as os_mod
    import signal

    async def flow():
        ex = make_serve_executor(tmp_path)
        try:
            handle = await open_session(
                ex, make_factory(step_delay=0.1, default_cap=12)
            )
            requests = [await handle.request([100 * i]) for i in range(3)]
            for _ in range(200):
                if all(len(r.tokens) >= 4 for r in requests):
                    break
                await asyncio.sleep(0.05)
            server_pid = ex._agents["localhost"]._process._proc.pid
            os_mod.kill(server_pid, signal.SIGTERM)  # the preemption notice
            for _ in range(200):
                if handle.handoffs:
                    break
                await asyncio.sleep(0.05)
            results = [await r.result(timeout=60) for r in requests]
            stats = (handle.handoffs, handle.state)
            await handle.close()
        finally:
            await ex.close()
        return results, stats

    results, (handoffs, state) = run_async(flow())
    for i, tokens in enumerate(results):
        assert tokens == [100 * i + j + 1 for j in range(12)], tokens
    assert handoffs == 1
    assert state == "open"


def test_serve_session_spec_greedy_bit_equal_to_fp(tmp_path, run_async):
    """Greedy spec-decode through a REAL open_session: a tiny LM served
    with a self-draft (full acceptance) streams token-for-token what the
    same model's fp session streams, and the supervisor's stats records
    carry the spec accept-rate feed the metrics plane exports."""
    import os

    import jax
    import jax.numpy as jnp

    from covalent_tpu_plugin.models import TransformerConfig, TransformerLM
    from covalent_tpu_plugin.models.serve import lm_engine_factory

    cfg = TransformerConfig(
        vocab_size=32, d_model=16, n_layers=1, n_heads=2, d_ff=32,
        max_seq=32, dtype=jnp.float32, attention="reference",
    )
    model = TransformerLM(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )["params"]
    prompts = [[3, 9, 1], [7, 2], [5, 5, 5, 5]]

    async def flow():
        cloudpickle.register_pickle_by_value(
            sys.modules["covalent_tpu_plugin.models.serve"]
        )
        repo_root = os.path.dirname(os.path.dirname(__file__))
        ex = make_serve_executor(
            tmp_path,
            task_env={
                "PYTHONPATH": repo_root + os.pathsep
                + os.environ.get("PYTHONPATH", ""),
            },
        )
        results, spec_stats = {}, None
        try:
            for tag, extra in (
                ("fp", {}),
                ("spec", dict(
                    draft_model=model, draft_params=params, draft_len=2,
                )),
            ):
                factory = lm_engine_factory(
                    model, params, max_batch=2, sync_steps=3,
                    max_new_tokens=6, length=24, **extra,
                )
                handle = await open_session(
                    ex, factory, name=f"lm-{tag}",
                    stats_interval_s=0.1, open_timeout_s=180.0,
                )
                requests = [
                    await handle.request(p, params={"max_new_tokens": 5})
                    for p in prompts
                ]
                results[tag] = [
                    await r.result(timeout=120.0) for r in requests
                ]
                if tag == "spec":
                    # The 0.1s stats cadence must surface the engine's
                    # accept counters before close.
                    for _ in range(100):
                        if handle.supervisor.stats.get("spec_accepted"):
                            break
                        await asyncio.sleep(0.05)
                    spec_stats = dict(handle.supervisor.stats)
                await handle.close()
        finally:
            await ex.close()
        return results, spec_stats

    results, spec_stats = run_async(flow())
    assert len(results["fp"]) == len(prompts)
    assert all(len(t) == 5 for t in results["fp"])
    # The oracle: spec streams ARE the fp streams, bit for bit.
    assert results["spec"] == results["fp"]
    assert spec_stats is not None
    assert spec_stats.get("spec_proposed", 0) > 0
    # Self-draft: every proposal agrees, accept rate exactly 1.0.
    assert spec_stats["spec_accepted"] == spec_stats["spec_proposed"]
    assert float(spec_stats.get("spec_accept_rate") or 0.0) == 1.0
    assert spec_stats.get("spec_refusals", 0) == 0
