"""Flash-attention kernel vs the dense oracle (interpret mode on the CPU
tier; the same kernel compiles via Mosaic on TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from covalent_tpu_plugin.ops import flash_attention, mha_reference


def random_qkv(key, shape, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    return (
        jax.random.normal(kq, shape, dtype),
        jax.random.normal(kk, shape, dtype),
        jax.random.normal(kv, shape, dtype),
    )


@pytest.mark.parametrize("causal", [True, False])
def test_flash_matches_reference(causal):
    q, k, v = random_qkv(jax.random.PRNGKey(0), (2, 3, 256, 64))
    out_flash = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
    out_ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(out_flash, out_ref, atol=2e-5, rtol=2e-5)


def test_flash_bf16():
    q, k, v = random_qkv(jax.random.PRNGKey(1), (1, 2, 128, 64), jnp.bfloat16)
    out = flash_attention(q, k, v, causal=True, block_q=64, block_k=64)
    ref = mha_reference(q, k, v, causal=True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        out.astype(np.float32), ref.astype(np.float32), atol=3e-2, rtol=3e-2
    )


def test_flash_gradients_match_reference():
    q, k, v = random_qkv(jax.random.PRNGKey(2), (1, 2, 128, 32))

    def loss_flash(q, k, v):
        return flash_attention(q, k, v, causal=True, block_q=64, block_k=64).sum()

    def loss_ref(q, k, v):
        return mha_reference(q, k, v, causal=True).sum()

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(a, b, atol=2e-4, rtol=2e-4)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_gradients_noncausal_and_odd_seq(causal):
    """Pallas backward (dq/dk/dv recompute kernels) across mask modes and a
    length the default tiles must shrink for (768 -> 256)."""
    q, k, v = random_qkv(jax.random.PRNGKey(5), (1, 2, 768, 32))

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, causal=causal) * 0.01).sum()

    def loss_ref(q, k, v):
        return (mha_reference(q, k, v, causal=causal) * 0.01).sum()

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(a, b, atol=2e-5, rtol=2e-4)


def test_flash_gradients_bf16():
    q, k, v = random_qkv(jax.random.PRNGKey(6), (1, 2, 256, 64), jnp.bfloat16)

    def loss(fn):
        return lambda q, k, v: fn(q, k, v, causal=True).astype(jnp.float32).sum()

    g_flash = jax.grad(loss(flash_attention), argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss(mha_reference), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        assert a.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            a.astype(np.float32), b.astype(np.float32), atol=1e-1, rtol=1e-1
        )


def test_flash_backward_no_dense_scores():
    """The backward jaxpr must not materialise an (S, S) probability array —
    the whole point of the flash recompute (VERDICT r1 weak #3)."""
    s = 256
    q, k, v = random_qkv(jax.random.PRNGKey(7), (1, 1, s, 32))
    jaxpr = jax.make_jaxpr(
        jax.grad(lambda q, k, v: flash_attention(q, k, v).sum(), argnums=(0, 1, 2))
    )(q, k, v)
    dense = [
        eqn for eqn in jaxpr.jaxpr.eqns
        for var in eqn.outvars
        if getattr(var.aval, "shape", ())[-2:] == (s, s)
    ]
    assert not dense, f"backward materialises dense S x S values: {dense}"


@pytest.mark.parametrize("causal", [True, False])
def test_flash_gqa_matches_repeated_kv(causal):
    """GQA: 8 query heads over 2 kv heads == dense attention with kv heads
    explicitly repeated; gradients land on the true kv shapes."""
    key = jax.random.PRNGKey(8)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (2, 8, 256, 32))
    k = jax.random.normal(kk, (2, 2, 256, 32))
    v = jax.random.normal(kv, (2, 2, 256, 32))

    out = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
    ref = mha_reference(q, k, v, causal=causal)  # repeats kv internally
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, causal=causal) * 0.01).sum()

    def loss_ref(q, k, v):
        return (mha_reference(q, k, v, causal=causal) * 0.01).sum()

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    assert g_flash[1].shape == k.shape  # true kv shape, not repeated
    assert g_flash[2].shape == v.shape
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(a, b, atol=2e-5, rtol=2e-4)


def test_flash_gqa_rejects_indivisible_heads():
    q, k, v = random_qkv(jax.random.PRNGKey(9), (1, 6, 128, 32))
    with pytest.raises(ValueError, match="divisible"):
        flash_attention(q, k[:, :4], v[:, :4])


def test_flash_position_vectors_mask_arbitrary_layouts():
    """q/k_positions drive the causal mask: a permuted (zigzag-style)
    layout through flash must equal attending in natural order and
    permuting the result — forward and gradients."""
    s = 256
    rng = np.random.default_rng(0)
    perm = jnp.asarray(rng.permutation(s).astype(np.int32))
    q, k, v = random_qkv(jax.random.PRNGKey(15), (1, 2, s, 32))

    qp = jnp.take(q, perm, axis=2)
    kp = jnp.take(k, perm, axis=2)
    vp = jnp.take(v, perm, axis=2)

    def loss_pos(qp, kp, vp):
        out = flash_attention(
            qp, kp, vp, causal=True, q_positions=perm, k_positions=perm
        )
        return (out * 0.01).sum()

    def loss_ref(q, k, v):
        return (mha_reference(q, k, v, causal=True) * 0.01).sum()

    out_pos = flash_attention(
        qp, kp, vp, causal=True, q_positions=perm, k_positions=perm
    )
    out_ref = jnp.take(mha_reference(q, k, v, causal=True), perm, axis=2)
    np.testing.assert_allclose(out_pos, out_ref, atol=2e-5, rtol=2e-5)

    g_pos = jax.grad(loss_pos, argnums=(0, 1, 2))(qp, kp, vp)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_pos, g_ref):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(jnp.take(b, perm, axis=2)),
            atol=2e-5, rtol=2e-4,
        )


def test_flash_cross_lengths_with_positions():
    """K/V shorter than Q (a ring K/V shard): positions select which keys
    each query may see."""
    q, _, _ = random_qkv(jax.random.PRNGKey(16), (1, 2, 256, 32))
    k = jax.random.normal(jax.random.PRNGKey(17), (1, 2, 128, 32))
    v = jax.random.normal(jax.random.PRNGKey(18), (1, 2, 128, 32))
    q_pos = jnp.arange(256, dtype=jnp.int32)
    k_pos = jnp.arange(128, dtype=jnp.int32) + 64  # keys live at 64..191

    out = flash_attention(
        q, k, v, causal=True, q_positions=q_pos, k_positions=k_pos,
        block_q=64, block_k=64,
    )
    # dense oracle on the same mask
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * (32**-0.5)
    mask = q_pos[:, None] >= k_pos[None, :]
    scores = jnp.where(mask, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    # rows with no visible key (q_pos < 64) are undefined in the oracle;
    # compare only fully-defined rows
    ref = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
    np.testing.assert_allclose(
        out[:, :, 64:], ref[:, :, 64:], atol=2e-5, rtol=2e-5
    )
    # flash defines fully-masked rows as zero output
    np.testing.assert_allclose(out[:, :, :64], 0.0, atol=1e-6)


class TestShardedFlash:
    """flash_attention_sharded: the shard_map wrapper that keeps the Pallas
    kernel collective-free under a sharded jit (a bare pallas_call forces
    Q/K/V all-gathers — 27 in one call's HLO on a 2×4 mesh)."""

    @pytest.fixture()
    def mesh(self):
        from covalent_tpu_plugin.parallel import MeshPlan, make_mesh

        return make_mesh(MeshPlan(data=2, tensor=4))

    def _sharded(self, mesh, x, heads_axis):
        from jax.sharding import NamedSharding, PartitionSpec as P

        return jax.device_put(
            x, NamedSharding(mesh, P(("data", "fsdp"), heads_axis, None, None))
        )

    def test_mha_no_collectives(self, mesh):
        from covalent_tpu_plugin.ops.attention import flash_attention_sharded

        q, k, v = random_qkv(jax.random.PRNGKey(10), (4, 8, 256, 32))
        qs, ks, vs = (self._sharded(mesh, t, "tensor") for t in (q, k, v))
        f = jax.jit(lambda q, k, v: flash_attention_sharded(q, k, v, mesh))
        out = f(qs, ks, vs)
        np.testing.assert_allclose(
            np.asarray(out), mha_reference(q, k, v), atol=2e-5, rtol=2e-5
        )
        hlo = f.lower(qs, ks, vs).compile().as_text()
        assert hlo.count("all-gather") == 0
        assert hlo.count("all-reduce") == 0

    def test_gqa_more_shards_than_kv_heads(self, mesh):
        """tensor=4 > kv_heads=2: kv replicated, each shard slices its one
        kv head; kv cotangents psum across the head axis in backward."""
        from covalent_tpu_plugin.ops.attention import flash_attention_sharded

        key = jax.random.PRNGKey(11)
        kq, kk, kv_ = jax.random.split(key, 3)
        q = jax.random.normal(kq, (4, 8, 256, 32))
        k = jax.random.normal(kk, (4, 2, 256, 32))
        v = jax.random.normal(kv_, (4, 2, 256, 32))
        qs = self._sharded(mesh, q, "tensor")
        ks = self._sharded(mesh, k, None)
        vs = self._sharded(mesh, v, None)

        def loss_s(q, k, v):
            return (flash_attention_sharded(q, k, v, mesh) * 0.01).sum()

        def loss_r(q, k, v):
            return (mha_reference(q, k, v) * 0.01).sum()

        out = jax.jit(lambda q, k, v: flash_attention_sharded(q, k, v, mesh))(
            qs, ks, vs
        )
        np.testing.assert_allclose(
            np.asarray(out), mha_reference(q, k, v), atol=2e-5, rtol=2e-5
        )
        g_s = jax.jit(jax.grad(loss_s, argnums=(0, 1, 2)))(qs, ks, vs)
        g_r = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_s, g_r):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=2e-5, rtol=2e-4
            )

    def test_mesh_without_head_axis_falls_back_to_batch_sharding(self):
        """A hand-built data-only mesh must work (heads whole per shard)."""
        from jax.sharding import Mesh

        from covalent_tpu_plugin.ops.attention import flash_attention_sharded

        mesh = Mesh(np.array(jax.devices()[:4]).reshape(4), ("data",))
        q, k, v = random_qkv(jax.random.PRNGKey(14), (4, 8, 256, 32))
        out = jax.jit(lambda q, k, v: flash_attention_sharded(q, k, v, mesh))(
            q, k, v
        )
        np.testing.assert_allclose(
            np.asarray(out), mha_reference(q, k, v), atol=2e-5, rtol=2e-5
        )

    def test_rejects_unsplittable_heads(self, mesh):
        from covalent_tpu_plugin.ops.attention import flash_attention_sharded

        # 24 q heads over 3 kv heads: valid GQA, but kv=3 and tensor=4
        # divide neither way.
        q, _, _ = random_qkv(jax.random.PRNGKey(12), (4, 24, 128, 32))
        k = jax.random.normal(jax.random.PRNGKey(13), (4, 3, 128, 32))
        with pytest.raises(ValueError, match="divide one way"):
            flash_attention_sharded(q, k, k, mesh)


def test_flash_rejects_indivisible_seq():
    q, k, v = random_qkv(jax.random.PRNGKey(3), (1, 1, 100, 32))
    with pytest.raises(ValueError, match="divisible"):
        flash_attention(q, k, v, block_q=64, block_k=64)


def test_flash_under_jit():
    q, k, v = random_qkv(jax.random.PRNGKey(4), (1, 2, 128, 32))
    jitted = jax.jit(lambda q, k, v: flash_attention(q, k, v, block_q=64, block_k=64))
    np.testing.assert_allclose(
        jitted(q, k, v), mha_reference(q, k, v, causal=True), atol=2e-5, rtol=2e-5
    )


def test_default_blocks_adapt_to_odd_seq_lengths():
    """Default (None) blocks must auto-fit lengths like 768 that the tuned
    512/1024 tiles don't divide; explicit non-dividing blocks still raise."""
    import jax
    import jax.numpy as jnp

    from covalent_tpu_plugin.ops.attention import flash_attention, mha_reference

    q = jax.random.normal(jax.random.PRNGKey(0), (1, 2, 768, 32), jnp.float32)
    out = flash_attention(q, q, q, causal=True)
    ref = mha_reference(q, q, q, causal=True)
    assert jnp.allclose(out, ref, atol=2e-5)
    with pytest.raises(ValueError, match="divisible"):
        flash_attention(q, q, q, causal=True, block_q=512)
    # Lengths with large odd factors must fail loudly, not degrade to
    # 2-wide tiles (4098 = 2*3*683).
    q_bad = jax.random.normal(jax.random.PRNGKey(1), (1, 2, 4098, 32), jnp.float32)
    with pytest.raises(ValueError, match="pad the sequence"):
        flash_attention(q_bad, q_bad, q_bad, causal=True)
