"""Weight-only int8 serving: conversion correctness + end-to-end decode.

The decisive properties: per-channel symmetric quantization round-trips
within its step size, the quantized model's logits track the float
model's, and the whole generate() path runs on the converted tree.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from covalent_tpu_plugin.models import (
    TransformerConfig,
    TransformerLM,
    generate,
    quantize_lm,
)
from covalent_tpu_plugin.models.quant import (
    SERVING_MODES,
    mode_variant,
    quantize_array,
)

BASE = TransformerConfig(
    vocab_size=64,
    d_model=32,
    n_layers=2,
    n_heads=4,
    d_ff=64,
    max_seq=32,
    dtype=jnp.float32,
    attention="reference",
    scan_layers=False,
)


def test_quantize_array_roundtrip_within_step():
    w = jax.random.normal(jax.random.PRNGKey(0), (16, 8), jnp.float32)
    q, scale = quantize_array(w, n_feature_dims=1)
    assert q.dtype == jnp.int8 and scale.shape == (8,)
    assert int(jnp.max(jnp.abs(q.astype(jnp.int32)))) <= 127
    # Dequantized error is bounded by half a quantization step per entry.
    err = np.abs(np.asarray(q, np.float32) * np.asarray(scale) - np.asarray(w))
    assert (err <= np.asarray(scale)[None, :] * 0.5 + 1e-7).all()


def test_quantize_array_multi_feature_dims():
    w = jax.random.normal(jax.random.PRNGKey(1), (32, 4, 8), jnp.float32)
    q, scale = quantize_array(w, n_feature_dims=2)
    assert scale.shape == (4, 8)
    # Per-channel max maps to exactly +/-127.
    assert int(jnp.max(jnp.abs(q.astype(jnp.int32)))) == 127


def test_quantized_model_tracks_float_logits():
    model = TransformerLM(BASE)
    tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 8), 0, BASE.vocab_size)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    qmodel, qparams = quantize_lm(model, params)

    # Every dense kernel really is int8 in the converted tree.
    kernels = [
        leaf
        for path, leaf in jax.tree_util.tree_flatten_with_path(qparams)[0]
        if any(getattr(e, "key", None) == "kernel" for e in path)
    ]
    assert kernels and all(k.dtype == jnp.int8 for k in kernels)

    full = np.asarray(model.apply({"params": params}, tokens), np.float32)
    quant = np.asarray(qmodel.apply({"params": qparams}, tokens), np.float32)
    cos = (full * quant).sum() / (
        np.linalg.norm(full) * np.linalg.norm(quant) + 1e-9
    )
    assert cos > 0.999, cos


def test_quantized_generate_end_to_end():
    model = TransformerLM(BASE)
    prompt = jax.random.randint(jax.random.PRNGKey(3), (2, 5), 0, BASE.vocab_size)
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]
    qmodel, qparams = quantize_lm(model, params)
    out = jax.jit(lambda p, t: generate(qmodel, p, t, max_new_tokens=6))(
        qparams, prompt
    )
    assert out.shape == (2, 11)
    np.testing.assert_array_equal(np.asarray(out[:, :5]), np.asarray(prompt))
    assert 0 <= int(jnp.min(out)) and int(jnp.max(out)) < BASE.vocab_size
    # At int8 fidelity the greedy continuations should mostly agree with
    # the float model's on a tiny model.
    want = generate(model, params, prompt, max_new_tokens=6)
    agreement = (np.asarray(out) == np.asarray(want)).mean()
    assert agreement >= 0.75, agreement


def test_quantize_lm_rejects_scanned_and_moe():
    scan_model = TransformerLM(dataclasses.replace(BASE, scan_layers=True))
    tokens = jnp.zeros((1, 4), jnp.int32)
    params = scan_model.init(jax.random.PRNGKey(0), tokens)["params"]
    with pytest.raises(ValueError, match="scan_layers"):
        quantize_lm(scan_model, params)
    moe_model = TransformerLM(dataclasses.replace(BASE, moe_experts=2))
    with pytest.raises(ValueError, match="MoE"):
        quantize_lm(moe_model, {})


def test_quantize_lm_copies_non_dense_leaves_verbatim():
    # Round-trip structure: embeddings and norm scales must cross the
    # conversion untouched — only dense kernels change representation.
    model = TransformerLM(BASE)
    tokens = jnp.zeros((1, 4), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    from covalent_tpu_plugin.parallel.sharding import unbox

    params = unbox(params)
    _, qparams = quantize_lm(model, params)
    np.testing.assert_array_equal(
        np.asarray(qparams["embedding"]), np.asarray(params["embedding"])
    )
    np.testing.assert_array_equal(
        np.asarray(qparams["ln_final"]["scale"]),
        np.asarray(params["ln_final"]["scale"]),
    )


def test_mode_variant_fp_is_identity():
    model = TransformerLM(BASE)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32))[
        "params"
    ]
    vmodel, vparams = mode_variant(model, params, "fp")
    assert vmodel is model and vparams is params


def test_mode_variant_kv_quant_shares_weights():
    model = TransformerLM(BASE)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32))[
        "params"
    ]
    vmodel, vparams = mode_variant(model, params, "kv_quant")
    # Same weight tree by identity — kv_quant only changes the cache.
    assert vparams is params
    assert vmodel.config.quantized_kv_cache and not vmodel.config.quantized


def test_mode_variant_int8_and_full_quant():
    model = TransformerLM(BASE)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32))[
        "params"
    ]
    i8_model, i8_params = mode_variant(model, params, "int8")
    assert i8_model.config.quantized and not i8_model.config.quantized_kv_cache
    fq_model, fq_params = mode_variant(model, params, "full_quant")
    assert fq_model.config.quantized and fq_model.config.quantized_kv_cache
    for qparams in (i8_params, fq_params):
        kernels = [
            leaf
            for path, leaf in jax.tree_util.tree_flatten_with_path(qparams)[0]
            if any(getattr(e, "key", None) == "kernel" for e in path)
        ]
        assert kernels and all(k.dtype == jnp.int8 for k in kernels)


def test_mode_variant_rejects_unknown_and_propagates_refusal():
    model = TransformerLM(BASE)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32))[
        "params"
    ]
    with pytest.raises(ValueError, match="unknown decode mode"):
        mode_variant(model, params, "int4")
    assert "fp" in SERVING_MODES and len(SERVING_MODES) == 4
    # quantize_lm's scan_layers refusal surfaces through mode_variant —
    # the engine catches it and falls back to the fp lane.
    scan_model = TransformerLM(dataclasses.replace(BASE, scan_layers=True))
    scan_params = scan_model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )["params"]
    with pytest.raises(ValueError, match="scan_layers"):
        mode_variant(scan_model, scan_params, "int8")


def test_quantized_gqa_attention_shapes():
    cfg = dataclasses.replace(BASE, n_kv_heads=2)
    model = TransformerLM(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(4), (1, 6), 0, cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    qmodel, qparams = quantize_lm(model, params)
    out = qmodel.apply({"params": qparams}, tokens)
    assert out.shape == (1, 6, cfg.vocab_size)
    assert np.isfinite(np.asarray(out, np.float32)).all()
