"""Weight-only int8 serving: conversion correctness + end-to-end decode.

The decisive properties: per-channel symmetric quantization round-trips
within its step size, the quantized model's logits track the float
model's, and the whole generate() path runs on the converted tree.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from covalent_tpu_plugin.models import (
    TransformerConfig,
    TransformerLM,
    generate,
    quantize_lm,
)
from covalent_tpu_plugin.models.quant import quantize_array

BASE = TransformerConfig(
    vocab_size=64,
    d_model=32,
    n_layers=2,
    n_heads=4,
    d_ff=64,
    max_seq=32,
    dtype=jnp.float32,
    attention="reference",
    scan_layers=False,
)


def test_quantize_array_roundtrip_within_step():
    w = jax.random.normal(jax.random.PRNGKey(0), (16, 8), jnp.float32)
    q, scale = quantize_array(w, n_feature_dims=1)
    assert q.dtype == jnp.int8 and scale.shape == (8,)
    assert int(jnp.max(jnp.abs(q.astype(jnp.int32)))) <= 127
    # Dequantized error is bounded by half a quantization step per entry.
    err = np.abs(np.asarray(q, np.float32) * np.asarray(scale) - np.asarray(w))
    assert (err <= np.asarray(scale)[None, :] * 0.5 + 1e-7).all()


def test_quantize_array_multi_feature_dims():
    w = jax.random.normal(jax.random.PRNGKey(1), (32, 4, 8), jnp.float32)
    q, scale = quantize_array(w, n_feature_dims=2)
    assert scale.shape == (4, 8)
    # Per-channel max maps to exactly +/-127.
    assert int(jnp.max(jnp.abs(q.astype(jnp.int32)))) == 127


def test_quantized_model_tracks_float_logits():
    model = TransformerLM(BASE)
    tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 8), 0, BASE.vocab_size)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    qmodel, qparams = quantize_lm(model, params)

    # Every dense kernel really is int8 in the converted tree.
    kernels = [
        leaf
        for path, leaf in jax.tree_util.tree_flatten_with_path(qparams)[0]
        if any(getattr(e, "key", None) == "kernel" for e in path)
    ]
    assert kernels and all(k.dtype == jnp.int8 for k in kernels)

    full = np.asarray(model.apply({"params": params}, tokens), np.float32)
    quant = np.asarray(qmodel.apply({"params": qparams}, tokens), np.float32)
    cos = (full * quant).sum() / (
        np.linalg.norm(full) * np.linalg.norm(quant) + 1e-9
    )
    assert cos > 0.999, cos


def test_quantized_generate_end_to_end():
    model = TransformerLM(BASE)
    prompt = jax.random.randint(jax.random.PRNGKey(3), (2, 5), 0, BASE.vocab_size)
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]
    qmodel, qparams = quantize_lm(model, params)
    out = jax.jit(lambda p, t: generate(qmodel, p, t, max_new_tokens=6))(
        qparams, prompt
    )
    assert out.shape == (2, 11)
    np.testing.assert_array_equal(np.asarray(out[:, :5]), np.asarray(prompt))
    assert 0 <= int(jnp.min(out)) and int(jnp.max(out)) < BASE.vocab_size
    # At int8 fidelity the greedy continuations should mostly agree with
    # the float model's on a tiny model.
    want = generate(model, params, prompt, max_new_tokens=6)
    agreement = (np.asarray(out) == np.asarray(want)).mean()
    assert agreement >= 0.75, agreement


def test_quantize_lm_rejects_scanned_and_moe():
    scan_model = TransformerLM(dataclasses.replace(BASE, scan_layers=True))
    tokens = jnp.zeros((1, 4), jnp.int32)
    params = scan_model.init(jax.random.PRNGKey(0), tokens)["params"]
    with pytest.raises(ValueError, match="scan_layers"):
        quantize_lm(scan_model, params)
    moe_model = TransformerLM(dataclasses.replace(BASE, moe_experts=2))
    with pytest.raises(ValueError, match="MoE"):
        quantize_lm(moe_model, {})


def test_quantized_gqa_attention_shapes():
    cfg = dataclasses.replace(BASE, n_kv_heads=2)
    model = TransformerLM(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(4), (1, 6), 0, cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    qmodel, qparams = quantize_lm(model, params)
    out = qmodel.apply({"params": qparams}, tokens)
    assert out.shape == (1, 6, cfg.vocab_size)
    assert np.isfinite(np.asarray(out, np.float32)).all()
