"""Shared fake `covalent` package for the interop tier.

One definition serves both consumers in ``test_covalent_interop.py``: the
in-process fixture (branch tests on reloaded modules) and the subprocess
end-to-end script (stub installed before first import) — so the stubbed
RemoteExecutor/config contract cannot silently diverge between tiers.
"""

from __future__ import annotations

import sys
import types


class FakeRemoteExecutor:
    """Covalent's async RemoteExecutor template, shape-compatible
    (covalent.executor.executor_plugins.remote_executor)."""

    def __init__(self, poll_freq=15, remote_cache="", credentials_file=""):
        self.poll_freq = poll_freq
        self.remote_cache = remote_cache
        self.credentials_file = credentials_file
        self.template_init_ran = True


def build_modules(store: dict) -> dict[str, types.ModuleType]:
    """Fake covalent module tree backed by ``store`` for config state."""

    def get_config(key):
        if key not in store:
            raise KeyError(key)
        return store[key]

    def set_config(mapping):
        store.update(mapping)

    def package(name, **attrs):
        module = types.ModuleType(name)
        module.__path__ = []  # mark as package
        for key, value in attrs.items():
            setattr(module, key, value)
        return module

    return {
        "covalent": package("covalent"),
        "covalent.executor": package("covalent.executor"),
        "covalent.executor.executor_plugins": package(
            "covalent.executor.executor_plugins"
        ),
        "covalent.executor.executor_plugins.remote_executor": package(
            "covalent.executor.executor_plugins.remote_executor",
            RemoteExecutor=FakeRemoteExecutor,
        ),
        "covalent._shared_files": package("covalent._shared_files"),
        "covalent._shared_files.config": package(
            "covalent._shared_files.config",
            get_config=get_config,
            set_config=set_config,
            store=store,
        ),
    }


def install(store: dict) -> dict[str, types.ModuleType]:
    """Install the stub into sys.modules (subprocess usage)."""
    modules = build_modules(store)
    sys.modules.update(modules)
    return modules
