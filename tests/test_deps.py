"""Per-electron dependency tests: DepsPip, call_before/call_after hooks.

Reference capability: upstream Covalent's ``ct.DepsPip`` attached to an
electron (``tests/functional_tests/svm_workflow.py:6,19``).  The install
command is redirected through ``COVALENT_TPU_PIP_CMD`` so no test touches
the network or mutates the environment.
"""

import json
import shlex
import sys

import pytest

import covalent_tpu_plugin.workflow as ct
from covalent_tpu_plugin.harness import run_task
from covalent_tpu_plugin.utils.serialize import dump_task
from covalent_tpu_plugin.workflow.deps import wrap_task

from .helpers import make_local_executor


# -------------------------------------------------------------------- #
# DepsPip construction                                                 #
# -------------------------------------------------------------------- #


def test_deps_pip_from_list_and_string():
    assert ct.DepsPip(packages=["numpy==1.23.2", "scikit-learn"]).packages == [
        "numpy==1.23.2",
        "scikit-learn",
    ]
    assert ct.DepsPip(packages="einops").packages == ["einops"]
    assert ct.DepsPip().packages == []


def test_deps_pip_from_requirements_file(tmp_path):
    reqs = tmp_path / "requirements.txt"
    reqs.write_text("# comment\nnumpy==1.23.2\n\nscikit-learn==1.1.2\n")
    deps = ct.DepsPip(reqs_path=str(reqs))
    assert deps.packages == ["numpy==1.23.2", "scikit-learn==1.1.2"]


# -------------------------------------------------------------------- #
# Call hooks                                                           #
# -------------------------------------------------------------------- #


def test_call_hooks_run_in_order_for_bare_electron_call():
    events = []

    @ct.electron(
        call_before=[lambda: events.append("before")],
        call_after=[lambda: events.append("after")],
    )
    def task(x):
        events.append("body")
        return x + 1

    assert task(1) == 2
    assert events == ["before", "body", "after"]


def test_call_after_runs_even_when_body_raises():
    events = []

    fn = wrap_task(
        lambda: (_ for _ in ()).throw(ValueError("boom")),
        call_before=[ct.DepsCall(events.append, ("before",))],
        call_after=[ct.DepsCall(events.append, ("after",))],
    )
    with pytest.raises(ValueError):
        fn()
    assert events == ["before", "after"]


def test_hooked_task_survives_pickle_roundtrip(tmp_path):
    """The wrapper must serialise by value — workers lack this package."""
    import cloudpickle

    marker = tmp_path / "hook_ran"
    fn = wrap_task(
        lambda x: x * 2,
        call_before=[ct.DepsCall(lambda p: open(p, "w").close(), (str(marker),))],
        call_after=[],
    )
    restored = cloudpickle.loads(cloudpickle.dumps(fn))
    assert restored(21) == 42
    assert marker.exists()


# -------------------------------------------------------------------- #
# Harness pip install path                                             #
# -------------------------------------------------------------------- #


def _recorder_cmd(record_file) -> str:
    """A fake pip: records its arguments as JSON and exits 0."""
    return (
        f"{shlex.quote(sys.executable)} -c "
        + shlex.quote(
            "import json,sys; json.dump(sys.argv[1:], open("
            + repr(str(record_file))
            + ", 'w'))"
        )
    )


def test_harness_installs_pip_deps_before_unpickle(tmp_path, monkeypatch):
    record = tmp_path / "pip_args.json"
    monkeypatch.setenv("COVALENT_TPU_PIP_CMD", _recorder_cmd(record))

    function_file = tmp_path / "function.pkl"
    result_file = tmp_path / "result.pkl"
    dump_task(lambda: "ok", (), {}, str(function_file))

    rc = run_task(
        {
            "function_file": str(function_file),
            "result_file": str(result_file),
            "pip_deps": ["scikit-learn==1.1.2", "numpy"],
        }
    )
    assert rc == 0
    assert json.loads(record.read_text()) == ["scikit-learn==1.1.2", "numpy"]
    import pickle

    result, exception = pickle.load(open(result_file, "rb"))
    assert exception is None and result == "ok"


def test_harness_reports_pip_failure_as_task_error(tmp_path, monkeypatch):
    monkeypatch.setenv(
        "COVALENT_TPU_PIP_CMD",
        f"{shlex.quote(sys.executable)} -c "
        + shlex.quote("import sys; print('no index', file=sys.stderr); sys.exit(1)"),
    )
    function_file = tmp_path / "function.pkl"
    result_file = tmp_path / "result.pkl"
    dump_task(lambda: "ok", (), {}, str(function_file))

    rc = run_task(
        {
            "function_file": str(function_file),
            "result_file": str(result_file),
            "pip_deps": ["definitely-not-a-package"],
        }
    )
    assert rc == 1
    import pickle

    result, exception = pickle.load(open(result_file, "rb"))
    assert result is None
    assert "pip dependency install failed" in str(exception)


# -------------------------------------------------------------------- #
# End-to-end through the engine                                        #
# -------------------------------------------------------------------- #


def test_lattice_with_deps_and_hooks_through_tpu_executor(tmp_path, monkeypatch):
    record = tmp_path / "pip_args.json"
    monkeypatch.setenv("COVALENT_TPU_PIP_CMD", _recorder_cmd(record))
    marker = tmp_path / "before_marker"

    executor = make_local_executor(tmp_path)

    @ct.electron(
        executor=executor,
        deps_pip=ct.DepsPip(packages=["cloudpickle"]),
        call_before=[ct.DepsCall(lambda p: open(p, "w").close(), (str(marker),))],
    )
    def remote_task(x):
        return x * 10

    @ct.lattice
    def flow(x):
        return remote_task(x)

    result = ct.dispatch_sync(flow)(4)
    assert result.status is ct.Status.COMPLETED, result.error
    assert result.result == 40
    assert json.loads(record.read_text()) == ["cloudpickle"]
    assert marker.exists()  # hook ran on the worker (same fs: local transport)


def test_local_executor_honours_pip_deps(tmp_path, monkeypatch):
    record = tmp_path / "pip_args.json"
    monkeypatch.setenv("COVALENT_TPU_PIP_CMD", _recorder_cmd(record))

    @ct.electron(deps_pip=["einops"])  # bare list accepted like upstream
    def task():
        return "done"

    @ct.lattice
    def flow():
        return task()

    result = ct.dispatch_sync(flow)()
    assert result.status is ct.Status.COMPLETED, result.error
    assert json.loads(record.read_text()) == ["einops"]
