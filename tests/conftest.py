"""Test harness configuration.

Mirrors the reference's decoupling of unit tests from live services
(``COVALENT_PLUGIN_LOAD=false``, ``tests.yml:87-89``) and adds the CPU
simulated-mesh tier from SURVEY §4.2c: an 8-device virtual CPU mesh via
``--xla_force_host_platform_device_count`` so all pjit/shard_map fan-out
logic is tested without TPUs.  Environment must be set before jax first
initializes its backends, hence module level, before any test imports jax.
"""

import asyncio
import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The test tier always runs on the virtual CPU mesh, even in sandboxes whose
# sitecustomize force-registers a TPU platform: the env var alone can be
# overridden by that registration, so pin the platform via jax.config too
# (must happen before first backend use).
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

# Isolate the config system from any real user config file.
os.environ.setdefault("COVALENT_TPU_CONFIG", "/tmp/covalent-tpu-test-config.toml")

import pytest

#: jax-heavy modules (interpret-mode Pallas kernels, model forwards,
#: virtual-mesh shard_map) — minutes each on one core.  The fast tier
#: (``pytest -m "not slow"``) is the executor/transport/workflow/config
#: stack, mirroring the reference's seconds-fast mocked unit tier
#: (reference tests/ssh_test.py); CI runs both tiers.
SLOW_MODULES = {
    "test_attention",
    "test_attention_sinks",
    "test_continuous",
    "test_distributed_pod",
    "test_beam",
    "test_decode",
    "test_kv_cache_quant",
    "test_lora",
    "test_models",
    "test_moe",
    "test_parallel",
    "test_pipeline",
    "test_quant",
    "test_ring_attention",
    "test_serving_sharded",
    "test_sliding_window",
    "test_speculative",
}


def pytest_collection_modifyitems(items):
    for item in items:
        module = item.module.__name__.rsplit(".", 1)[-1]
        if module in SLOW_MODULES:
            item.add_marker(pytest.mark.slow)


@pytest.fixture(autouse=True, scope="module")
def _bound_jax_compile_state():
    """Clear jax's in-process caches after each test module.

    A full-suite run accumulates hundreds of compiled executables in one
    process; at ~85% through, XLA:CPU's compiler segfaulted inside
    backend_compile (reproduced twice at the same test, while the same
    test passes in isolation and in whole-file runs).  Per-module
    clearing bounds the growth; cross-module cache reuse is ~nil anyway
    (modules compile their own model/kernel shapes)."""
    yield
    jax.clear_caches()


@pytest.fixture()
def run_async():
    """Drive a coroutine to completion (no pytest-asyncio in this image)."""

    def runner(coro):
        return asyncio.run(coro)

    return runner


@pytest.fixture()
def tmp_config(tmp_path, monkeypatch):
    """Point the config system at a fresh file and reset its cache."""
    from covalent_tpu_plugin.utils import config as config_mod

    path = tmp_path / "config.toml"
    monkeypatch.setenv("COVALENT_TPU_CONFIG", str(path))
    config_mod._reset_cache_for_tests()
    yield path
    config_mod._reset_cache_for_tests()
