"""Transport-layer tests: local backend, retry envelope, pooling.

The retry tests replicate the reference's scripted flaky-network simulation
(``tests/ssh_test.py:199-257``): a connect that fails a set number of times
with classified-retryable errors, asserting immediate success, eventual
success, immediate failure with ``retry_connect=False``, and exhausted
retries.
"""

import os

import pytest

from covalent_tpu_plugin.transport import (
    LocalTransport,
    TransportError,
    TransportPool,
    connect_with_retries,
)
from covalent_tpu_plugin.transport.base import Transport


class FlakyTransport(Transport):
    """Raises retryable errors until the Nth open attempt succeeds."""

    def __init__(self, succeed_after: int):
        self.address = "flaky"
        self.succeed_after = succeed_after
        self.attempts = 0

    async def _open(self):
        self.attempts += 1
        if self.attempts < self.succeed_after:
            # Alternate the two retryable classes like ssh_test.py:199-219.
            raise (ConnectionRefusedError if self.attempts % 2 else OSError)("boom")

    async def run(self, command, timeout=None):
        raise NotImplementedError

    async def put(self, a, b):
        raise NotImplementedError

    async def get(self, a, b):
        raise NotImplementedError

    async def close(self):
        pass


def test_local_run_captures_output(run_async):
    t = LocalTransport()
    result = run_async(t.run("echo hello && echo err >&2"))
    assert result.exit_status == 0
    assert result.stdout.strip() == "hello"
    assert result.stderr.strip() == "err"


def test_local_run_nonzero_exit(run_async):
    result = run_async(LocalTransport().run("exit 7"))
    assert result.exit_status == 7


def test_local_run_timeout(run_async):
    with pytest.raises(TransportError):
        run_async(LocalTransport().run("sleep 5", timeout=0.1))


def test_local_put_get_roundtrip(run_async, tmp_path):
    src = tmp_path / "a.txt"
    src.write_text("payload")
    dst = tmp_path / "b.txt"
    fetched = tmp_path / "c.txt"

    async def flow():
        t = LocalTransport()
        await t.put(str(src), str(dst))
        await t.get(str(dst), str(fetched))

    run_async(flow())
    assert fetched.read_text() == "payload"


def test_closed_transport_rejects_commands(run_async):
    async def flow():
        t = LocalTransport()
        await t.close()
        await t.run("echo hi")

    with pytest.raises(TransportError):
        run_async(flow())


def test_connect_immediate_success(run_async):
    t = FlakyTransport(succeed_after=1)
    run_async(connect_with_retries(t, max_attempts=5, retry_wait_time=0))
    assert t.attempts == 1


def test_connect_eventual_success(run_async):
    t = FlakyTransport(succeed_after=3)
    run_async(connect_with_retries(t, max_attempts=5, retry_wait_time=0))
    assert t.attempts == 3


def test_connect_no_retry_reraises_immediately(run_async):
    t = FlakyTransport(succeed_after=4)
    with pytest.raises(ConnectionRefusedError):
        run_async(
            connect_with_retries(t, max_attempts=5, retry_wait_time=0, retry_connect=False)
        )
    assert t.attempts == 1


def test_connect_exhausted_retries(run_async):
    t = FlakyTransport(succeed_after=100)
    with pytest.raises(TransportError):
        run_async(connect_with_retries(t, max_attempts=4, retry_wait_time=0))
    assert t.attempts == 4


def test_pool_reuses_transport_and_single_flight(run_async):
    pool = TransportPool()
    created = []

    async def factory():
        t = LocalTransport()
        created.append(t)
        return t

    async def flow():
        import asyncio

        results = await asyncio.gather(
            *(pool.acquire("k", factory) for _ in range(8))
        )
        assert all(r is results[0] for r in results)
        other = await pool.acquire("k2", factory)
        assert other is not results[0]
        await pool.close_all()

    run_async(flow())
    assert len(created) == 2


def test_pool_discard_forces_redial(run_async):
    pool = TransportPool()
    created = []

    async def factory():
        t = LocalTransport()
        created.append(t)
        return t

    async def flow():
        first = await pool.acquire("k", factory)
        await pool.discard("k")
        second = await pool.acquire("k", factory)
        assert first is not second

    run_async(flow())
    assert len(created) == 2


def test_local_remove_unlinks_without_shell(tmp_path, run_async):
    t = LocalTransport()
    paths = [str(tmp_path / f"f{i}") for i in range(3)]
    for p in paths[:2]:
        open(p, "w").close()
    # Third path doesn't exist: remove must stay best-effort quiet.
    result = run_async(t.remove(paths))
    assert result.exit_status == 0
    assert not any(os.path.exists(p) for p in paths)


def test_base_remove_rides_run(run_async):
    class Recorder(LocalTransport):
        def __init__(self):
            super().__init__()
            self.commands = []

        async def run(self, command, timeout=None):
            self.commands.append(command)
            return await super().run(command, timeout)

    t = Recorder()
    # Skip the subclass override to exercise the ABC's rm -f default.
    run_async(Transport.remove(t, ["/tmp/does-not-exist-xyz", "a b.txt"]))
    assert t.commands and t.commands[0].startswith("rm -f ")
    assert "'a b.txt'" in t.commands[0]  # quoting
