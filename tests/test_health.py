"""Gray-failure health scoring: the fleet's continuous sense organ.

Unit tier over :class:`~covalent_tpu_plugin.fleet.health.HealthMonitor`
with an injected fake clock: differential (vs-group-median) latency
scoring, heartbeat-jitter penalties, the four-state machine's full
HEALTHY -> PROBATION -> DEGRADED -> QUARANTINED walk, canary readmission
(single-flight, exponential dwell, probation-not-healthy on success),
the crash-recovery neutral reset (the "no stale quarantines" regression),
metric-series reaping, and the gang straggler differential detector on
the executor.
"""

from __future__ import annotations

import pytest

from covalent_tpu_plugin.fleet.health import (
    DEGRADED,
    HEALTHY,
    PROBATION,
    PROBING,
    QUARANTINED,
    HealthMonitor,
)
from covalent_tpu_plugin.obs.metrics import REGISTRY


class FakeClock:
    def __init__(self, start: float = 1000.0) -> None:
        self.now = start

    def __call__(self) -> float:
        return self.now

    def advance(self, dt: float) -> None:
        self.now += dt


def make_monitor(clock=None, min_samples=3, cooldown_s=10.0):
    monitor = HealthMonitor(clock=clock or FakeClock())
    monitor.min_samples = min_samples
    monitor.cooldown_s = cooldown_s
    return monitor


def counter_value(name: str, **labels) -> float:
    metric = REGISTRY.get(name)
    if metric is None:
        return 0.0
    return sum(
        c.value for lbls, c in metric._series()
        if all(lbls.get(k) == v for k, v in labels.items())
    )


def gauge_series(name: str) -> dict[str, float]:
    metric = REGISTRY.get(name)
    if metric is None:
        return {}
    return {
        dict(labels).get("target", ""): g.value
        for labels, g in metric._series()
    }


# ---------------------------------------------------------------------------
# scoring


def test_differential_latency_scores_relative_to_group_median():
    """A target 10x slower than its peer median scores low; the peers —
    equally 'slow' in absolute terms on a slow pool — stay near 1.0.
    Absolute latency is meaningless across heterogeneous fleets."""
    monitor = make_monitor()
    for _ in range(4):
        monitor.record_latency("a", 0.1, group="g")
        monitor.record_latency("b", 0.1, group="g")
        monitor.record_latency("slow", 1.0, group="g")
    assert monitor.score("a") == pytest.approx(1.0)
    assert monitor.score("b") == pytest.approx(1.0)
    # lat component = median(0.1) / ewma(1.0) = 0.1 -> heavily penalized.
    assert monitor.score("slow") < 0.65
    assert monitor.score("slow") == pytest.approx(
        0.45 * 0.1 + 0.15 + 0.30 + 0.10, abs=0.02
    )


def test_ungrouped_target_is_not_latency_penalized():
    """Without a peer group there is no median to differ from: latency
    alone never dings a lone target (faults/jitter still can)."""
    monitor = make_monitor()
    for _ in range(6):
        monitor.record_latency("lonely", 30.0)
    assert monitor.score("lonely") == pytest.approx(1.0)
    assert monitor.state("lonely") == HEALTHY


def test_min_samples_gates_the_latency_judgment():
    """Below min_samples the differential term stays neutral — one cold
    first op must not probation a fresh replica."""
    monitor = make_monitor(min_samples=5)
    for _ in range(4):
        monitor.record_latency("peer", 0.1, group="g")
    monitor.record_latency("cold", 5.0, group="g")  # 1 sample < 5
    assert monitor.score("cold") == pytest.approx(1.0)
    assert monitor.state("cold") == HEALTHY


def test_heartbeat_jitter_lowers_score():
    """Erratic inter-arrival gaps (cv ~ 1) cost the jitter weight; a
    steady beat costs nothing."""
    clock = FakeClock()
    monitor = make_monitor(clock=clock)
    for _ in range(10):
        clock.advance(1.0)
        monitor.record_heartbeat("steady")
    gaps = [0.1, 3.0, 0.1, 2.5, 0.2, 3.5, 0.1, 2.8, 0.15, 3.2]
    for gap in gaps:
        clock.advance(gap)
        monitor.record_heartbeat("erratic")
    snap = monitor.snapshot()
    assert snap["steady"]["hb_jitter_cv"] == pytest.approx(0.0, abs=0.01)
    assert snap["erratic"]["hb_jitter_cv"] > 0.5
    assert monitor.score("steady") > monitor.score("erratic")


def test_faults_decay_and_successes_heal():
    monitor = make_monitor()
    monitor.record_fault("w", label="rpc_channel")
    after_one = monitor.score("w")
    assert after_one == pytest.approx(1.0 - 0.30 * 0.34, abs=0.01)
    for _ in range(5):
        monitor.record_success("w")
    assert monitor.score("w") == pytest.approx(1.0)


# ---------------------------------------------------------------------------
# state machine


def brown_out(monitor, clock, key="bad", peers=("a", "b")):
    """Drive one target through the full gray decline: differential
    latency -> PROBATION, sustained -> DEGRADED, faults on top ->
    QUARANTINED.  Returns after quarantine."""
    for _ in range(4):
        for peer in peers:
            monitor.record_latency(peer, 0.1, group="g")
        monitor.record_latency(key, 1.0, group="g")
    assert monitor.state(key) == PROBATION
    # Probation graduates to degraded only when the low score SUSTAINS
    # past cooldown/2 — a single spike never escalates.
    clock.advance(monitor.cooldown_s / 2 + 0.1)
    monitor.record_latency(key, 1.0, group="g")
    assert monitor.state(key) == DEGRADED
    for _ in range(3):
        monitor.record_fault(key, label="worker_stalled")
    assert monitor.state(key) == QUARANTINED


def test_state_machine_walks_probation_degraded_quarantined():
    clock = FakeClock()
    monitor = make_monitor(clock=clock)
    brown_out(monitor, clock)
    assert monitor.rank("bad") == 3
    assert monitor.quarantined("bad")
    assert monitor.degraded("bad")
    assert monitor.rank("a") == 0


def test_probation_recovers_to_healthy_without_escalating():
    """A transient dip that recovers before cooldown/2 goes straight
    back to HEALTHY — no degraded detour, no quarantine."""
    clock = FakeClock()
    monitor = make_monitor(clock=clock)
    for _ in range(4):
        monitor.record_latency("a", 0.1, group="g")
        monitor.record_latency("b", 0.1, group="g")
        monitor.record_latency("dip", 1.0, group="g")
    assert monitor.state("dip") == PROBATION
    # Latency recovers: EWMA converges back toward the peer median.
    for _ in range(20):
        monitor.record_latency("dip", 0.1, group="g")
    assert monitor.state("dip") == HEALTHY


def test_quarantine_exits_only_through_the_canary():
    """No passive signal readmits a quarantined target: successes and
    fast latencies are ignored until a canary probe passes."""
    clock = FakeClock()
    monitor = make_monitor(clock=clock)
    brown_out(monitor, clock)
    for _ in range(10):
        monitor.record_success("bad")
        monitor.record_latency("bad", 0.05, group="g")
    assert monitor.state("bad") == QUARANTINED


def test_canary_single_flight_and_probation_readmission():
    clock = FakeClock()
    monitor = make_monitor(clock=clock)
    brown_out(monitor, clock)
    # Inside the dwell window: no probe yet.
    assert not monitor.allow_probe("bad")
    clock.advance(monitor.cooldown_s + 0.1)
    assert monitor.allow_probe("bad")
    assert monitor.state("bad") == PROBING
    # Single-flight: a second prober in the same window is refused.
    assert not monitor.allow_probe("bad")
    monitor.record_probe("bad", ok=True)
    # Canary ok readmits to PROBATION, not HEALTHY — the score must be
    # re-earned by real traffic (signals were reset to neutral).
    assert monitor.state("bad") == PROBATION
    assert monitor.score("bad") == pytest.approx(1.0)
    monitor.record_success("bad")
    assert monitor.state("bad") == HEALTHY


def test_probing_ranks_with_degraded_until_the_verdict():
    """A canary in flight is not a verdict: the instant allow_probe flips
    QUARANTINED -> PROBING the target must NOT become fully routable at
    top priority — it ranks with DEGRADED (last-resort) for the whole
    probe window, and only readmission to PROBATION restores priority."""
    clock = FakeClock()
    monitor = make_monitor(clock=clock)
    brown_out(monitor, clock)
    clock.advance(monitor.cooldown_s + 0.1)
    assert monitor.allow_probe("bad")
    assert monitor.state("bad") == PROBING
    assert monitor.rank("bad") == 2
    assert monitor.degraded("bad")
    monitor.record_probe("bad", ok=True)
    assert monitor.state("bad") == PROBATION
    assert monitor.rank("bad") == 1
    assert not monitor.degraded("bad")


def test_release_probe_is_verdict_free():
    """A probe slot released because the canary never RAN (no event loop
    on a sync status path) must not count as a failed canary: the target
    returns to QUARANTINED with its original dwell clock and round — the
    next tick retries immediately instead of waiting out an exponentially
    lengthened back-off the target never earned."""
    clock = FakeClock()
    monitor = make_monitor(clock=clock)
    brown_out(monitor, clock)
    clock.advance(monitor.cooldown_s + 0.1)
    assert monitor.allow_probe("bad")
    assert monitor.state("bad") == PROBING
    monitor.release_probe("bad")
    assert monitor.state("bad") == QUARANTINED
    # Dwell clock untouched (already elapsed): the retry is immediate.
    assert monitor.allow_probe("bad")
    # A REAL failed canary still doubles the dwell from here (round 2).
    monitor.record_probe("bad", ok=False)
    clock.advance(monitor.cooldown_s + 0.1)
    assert not monitor.allow_probe("bad")
    clock.advance(monitor.cooldown_s)
    assert monitor.allow_probe("bad")


def test_failed_canary_requarantines_with_exponential_dwell():
    clock = FakeClock()
    monitor = make_monitor(clock=clock)
    brown_out(monitor, clock)
    clock.advance(monitor.cooldown_s + 0.1)
    assert monitor.allow_probe("bad")
    monitor.record_probe("bad", ok=False)
    assert monitor.state("bad") == QUARANTINED
    # Round 2: the dwell doubled — one cooldown is no longer enough.
    clock.advance(monitor.cooldown_s + 0.1)
    assert not monitor.allow_probe("bad")
    clock.advance(monitor.cooldown_s)
    assert monitor.allow_probe("bad")


def test_neutral_clears_stale_quarantine():
    """The crash-recovery regression: a re-adopted session / re-dialed
    worker starts NEUTRAL — the restarted control plane must never
    inherit the dead incarnation's quarantine verdicts."""
    clock = FakeClock()
    monitor = make_monitor(clock=clock)
    brown_out(monitor, clock)
    assert monitor.state("bad") == QUARANTINED
    monitor.neutral("bad")
    assert monitor.state("bad") == HEALTHY
    assert monitor.score("bad") == pytest.approx(1.0)
    assert monitor.rank("bad") == 0
    # And the group memory is kept so differential scoring resumes.
    assert monitor.snapshot()["bad"]["group"] == "g"


def test_disabled_env_freezes_the_state_machine(monkeypatch):
    monkeypatch.setenv("COVALENT_TPU_HEALTH", "off")
    clock = FakeClock()
    monitor = make_monitor(clock=clock)
    for _ in range(4):
        monitor.record_latency("a", 0.1, group="g")
        monitor.record_latency("b", 0.1, group="g")
        monitor.record_latency("bad", 5.0, group="g")
    for _ in range(5):
        monitor.record_fault("bad")
    assert monitor.state("bad") == HEALTHY


def test_transition_counter_and_state_gauge_move():
    clock = FakeClock()
    monitor = make_monitor(clock=clock)
    before = counter_value(
        "covalent_tpu_health_transitions_total", to="quarantined"
    )
    brown_out(monitor, clock, key="metricbad", peers=("ma", "mb"))
    after = counter_value(
        "covalent_tpu_health_transitions_total", to="quarantined"
    )
    assert after == before + 1
    assert gauge_series("covalent_tpu_health_state")["metricbad"] == 3
    monitor.reset()


def test_drop_reaps_metric_series():
    """A released target's score/state series must not haunt /metrics."""
    monitor = make_monitor()
    monitor.record_fault("ghost")
    assert "ghost" in gauge_series("covalent_tpu_health_score")
    monitor.drop("ghost")
    assert "ghost" not in gauge_series("covalent_tpu_health_score")
    assert "ghost" not in gauge_series("covalent_tpu_health_state")
    assert monitor.state("ghost") == HEALTHY  # forgotten, not quarantined


# ---------------------------------------------------------------------------
# gang straggler detection (executor-side differential)


def test_gang_straggler_flagged_and_fault_charged(monkeypatch):
    from covalent_tpu_plugin.fleet.health import HEALTH
    from covalent_tpu_plugin.tpu import TPUExecutor

    monkeypatch.delenv("COVALENT_TPU_STRAGGLER_BUDGET_S", raising=False)
    monkeypatch.delenv("COVALENT_TPU_STRAGGLER_REDIAL", raising=False)
    HEALTH.drop("w2")
    ex = TPUExecutor.__new__(TPUExecutor)  # detector needs no dial state
    before = counter_value("covalent_tpu_stragglers_total", worker="w2")
    ex._note_gang_stragglers(
        "op-1", ["w0", "w1", "w2"], {0: 10.0, 1: 10.2, 2: 18.0}
    )
    # w2 exited 7.8s past the gang median (10.2) — over the 5s budget.
    assert counter_value(
        "covalent_tpu_stragglers_total", worker="w2"
    ) == before + 1
    assert HEALTH.snapshot()["w2"]["fault_score"] < 1.0
    HEALTH.drop("w2")


def test_gang_straggler_within_budget_not_flagged(monkeypatch):
    from covalent_tpu_plugin.tpu import TPUExecutor

    monkeypatch.setenv("COVALENT_TPU_STRAGGLER_BUDGET_S", "5")
    ex = TPUExecutor.__new__(TPUExecutor)
    before = counter_value("covalent_tpu_stragglers_total")
    ex._note_gang_stragglers(
        "op-2", ["w0", "w1"], {0: 10.0, 1: 14.0}  # 4s < 5s budget
    )
    assert counter_value("covalent_tpu_stragglers_total") == before


def test_gang_straggler_budget_zero_disables(monkeypatch):
    from covalent_tpu_plugin.tpu import TPUExecutor

    monkeypatch.setenv("COVALENT_TPU_STRAGGLER_BUDGET_S", "0")
    ex = TPUExecutor.__new__(TPUExecutor)
    before = counter_value("covalent_tpu_stragglers_total")
    ex._note_gang_stragglers(
        "op-3", ["w0", "w1"], {0: 1.0, 1: 500.0}
    )
    assert counter_value("covalent_tpu_stragglers_total") == before
