"""KV-cache decoding: incremental generation must match full recompute.

The decisive property: feeding tokens one at a time through the decode
cache produces the same next-token choices as re-running the full prefix
through the training-mode model at every step (the O(S^2) naive loop).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from covalent_tpu_plugin.models import TransformerConfig, TransformerLM, generate

BASE = TransformerConfig(
    vocab_size=64,
    d_model=32,
    n_layers=2,
    n_heads=4,
    d_ff=64,
    max_seq=32,
    dtype=jnp.float32,
    attention="reference",
)


def naive_greedy(model, params, prompt, max_new):
    """O(S^2) oracle: full forward over the growing prefix each step."""
    tokens = prompt
    for _ in range(max_new):
        logits = model.apply({"params": params}, tokens)
        nxt = jnp.argmax(logits[:, -1].astype(jnp.float32), axis=-1)
        tokens = jnp.concatenate([tokens, nxt[:, None].astype(jnp.int32)], axis=1)
    return tokens


@pytest.mark.parametrize("scan_layers", [True, False], ids=["scan", "unrolled"])
@pytest.mark.parametrize("n_kv_heads", [None, 2], ids=["mha", "gqa"])
def test_cached_decode_matches_full_recompute(scan_layers, n_kv_heads):
    cfg = dataclasses.replace(BASE, scan_layers=scan_layers, n_kv_heads=n_kv_heads)
    model = TransformerLM(cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 5), 0, cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]

    got = generate(model, params, prompt, max_new_tokens=6)
    want = naive_greedy(model, params, prompt, 6)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_cached_decode_logits_match_bf16():
    """bf16 (the default training dtype): cached-decode step logits must
    track the full-recompute forward within bf16 tolerance — guards the
    f32-accumulation of the probs x cached_V contraction."""
    from covalent_tpu_plugin.models.decode import _decode_model, init_cache

    cfg = dataclasses.replace(BASE, dtype=jnp.bfloat16)
    model = TransformerLM(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(4), (2, 8), 0, cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]

    full_logits = model.apply({"params": params}, tokens)  # (B, 8, V)
    decoder = _decode_model(model)
    cache = init_cache(model, 2)
    for t in range(tokens.shape[1]):
        step_logits, mutated = decoder.apply(
            {"params": params, "cache": cache}, tokens[:, t:t + 1],
            mutable=["cache"],
        )
        cache = mutated["cache"]
        np.testing.assert_allclose(
            np.asarray(step_logits[:, 0], np.float32),
            np.asarray(full_logits[:, t], np.float32),
            atol=0.15, rtol=0.05,
        )


def test_chunked_prefill_keeps_cached_context():
    """Feeding the prompt in two multi-token chunks must equal one full
    forward — the second chunk's queries attend the first chunk's cache."""
    from covalent_tpu_plugin.models.decode import _decode_model, init_cache

    model = TransformerLM(BASE)
    tokens = jax.random.randint(jax.random.PRNGKey(5), (2, 12), 0, BASE.vocab_size)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    full = model.apply({"params": params}, tokens)

    decoder = _decode_model(model)
    cache = init_cache(model, 2)
    first, mutated = decoder.apply(
        {"params": params, "cache": cache}, tokens[:, :7], mutable=["cache"]
    )
    second, _ = decoder.apply(
        {"params": params, "cache": mutated["cache"]}, tokens[:, 7:],
        mutable=["cache"],
    )
    got = jnp.concatenate([first, second], axis=1)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(full), atol=2e-4, rtol=2e-4
    )


def test_generate_zero_new_tokens_is_identity():
    model = TransformerLM(BASE)
    prompt = jax.random.randint(jax.random.PRNGKey(6), (2, 5), 0, BASE.vocab_size)
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]
    out = generate(model, params, prompt, max_new_tokens=0)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(prompt))


def test_generate_is_jittable_and_prompt_preserved():
    model = TransformerLM(BASE)
    prompt = jax.random.randint(jax.random.PRNGKey(2), (3, 4), 0, BASE.vocab_size)
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]
    jitted = jax.jit(
        lambda p, t: generate(model, p, t, max_new_tokens=5)
    )
    out = jitted(params, prompt)
    assert out.shape == (3, 9)
    np.testing.assert_array_equal(np.asarray(out[:, :4]), np.asarray(prompt))
    np.testing.assert_array_equal(
        np.asarray(out), np.asarray(jitted(params, prompt))
    )


def test_sampled_generation_seeds_and_bounds():
    model = TransformerLM(BASE)
    prompt = jnp.zeros((2, 3), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]
    a = generate(model, params, prompt, 8, temperature=1.0,
                 rng=jax.random.PRNGKey(7))
    b = generate(model, params, prompt, 8, temperature=1.0,
                 rng=jax.random.PRNGKey(7))
    c = generate(model, params, prompt, 8, temperature=1.0,
                 rng=jax.random.PRNGKey(8))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert not np.array_equal(np.asarray(a), np.asarray(c))
    assert int(jnp.max(a)) < BASE.vocab_size and int(jnp.min(a)) >= 0


def test_generate_rejects_overlong_and_missing_rng():
    model = TransformerLM(BASE)
    prompt = jnp.zeros((1, 30), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]
    with pytest.raises(ValueError, match="max_seq"):
        generate(model, params, prompt, 10)
    with pytest.raises(ValueError, match="rng"):
        generate(model, params, prompt[:, :4], 2, temperature=0.5)


def test_top_k_filter_masks_exactly_k():
    from covalent_tpu_plugin.models.decode import _filter_top_k
    from covalent_tpu_plugin.ops.attention import NEG_INF

    logits = jnp.asarray([[1.0, 5.0, 3.0, 2.0], [0.0, -1.0, 4.0, 4.0]])
    out = np.asarray(_filter_top_k(logits, 2))
    neg = np.float32(NEG_INF)
    np.testing.assert_array_equal(
        out[0], np.asarray([neg, 5.0, 3.0, neg], np.float32)
    )
    # Row 2 has a tie at the kth value: both 4.0s survive the >= threshold.
    np.testing.assert_array_equal(
        out[1], np.asarray([neg, neg, 4.0, 4.0], np.float32)
    )


def test_top_p_filter_keeps_nucleus():
    from covalent_tpu_plugin.models.decode import _filter_top_p

    # softmax([2, 1, 0, -3]) ~ [0.662, 0.244, 0.090, 0.004]: top_p=0.6 keeps
    # the first token only, 0.9 keeps two, 1.0 keeps everything.
    logits = jnp.asarray([[2.0, 1.0, 0.0, -3.0]])
    keep = lambda p: (np.asarray(_filter_top_p(logits, p)) > -1e29)[0]
    np.testing.assert_array_equal(keep(0.6), [True, False, False, False])
    np.testing.assert_array_equal(keep(0.9), [True, True, False, False])
    np.testing.assert_array_equal(keep(1.0), [True, True, True, True])


def test_top_k1_sampling_equals_greedy():
    """top_k=1 collapses sampling to argmax whatever the temperature."""
    model = TransformerLM(BASE)
    prompt = jax.random.randint(jax.random.PRNGKey(9), (2, 4), 0, BASE.vocab_size)
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]
    greedy = generate(model, params, prompt, 6)
    sampled = generate(
        model, params, prompt, 6, temperature=2.0,
        rng=jax.random.PRNGKey(3), top_k=1,
    )
    np.testing.assert_array_equal(np.asarray(greedy), np.asarray(sampled))


def test_top_filters_are_jittable_and_validated():
    model = TransformerLM(BASE)
    prompt = jnp.zeros((1, 3), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]
    jitted = jax.jit(
        lambda p, t, r: generate(
            model, p, t, 5, temperature=0.8, rng=r, top_k=8, top_p=0.9
        )
    )
    out = jitted(params, prompt, jax.random.PRNGKey(0))
    assert out.shape == (1, 8)
    assert 0 <= int(jnp.min(out)) and int(jnp.max(out)) < BASE.vocab_size
    with pytest.raises(ValueError, match="top_k/top_p/min_p require"):
        generate(model, params, prompt, 2, top_k=4)
    with pytest.raises(ValueError, match="top_k must be"):
        generate(model, params, prompt, 2, temperature=1.0,
                 rng=jax.random.PRNGKey(0), top_k=0)
    with pytest.raises(ValueError, match="top_p must be"):
        generate(model, params, prompt, 2, temperature=1.0,
                 rng=jax.random.PRNGKey(0), top_p=1.5)


def test_inference_params_casts_only_f32():
    from covalent_tpu_plugin.models import inference_params

    model = TransformerLM(BASE)
    prompt = jnp.zeros((1, 4), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]
    cast = inference_params({"w": params, "step": jnp.zeros((), jnp.int32)})
    leaves = jax.tree_util.tree_leaves(cast["w"])
    assert all(leaf.dtype == jnp.bfloat16 for leaf in leaves)
    assert cast["step"].dtype == jnp.int32  # non-f32 passthrough
    # Generation still runs end to end on the serving copy.
    out = generate(model, cast["w"], prompt, 4)
    assert out.shape == (1, 8)
    assert 0 <= int(jnp.min(out)) and int(jnp.max(out)) < BASE.vocab_size


def test_eos_stops_row_and_pads():
    """Force EOS: a row that emits eos_token_id freezes to pad tokens and
    the non-eos path is unchanged."""
    model = TransformerLM(BASE)
    prompt = jax.random.randint(jax.random.PRNGKey(11), (2, 4), 0, BASE.vocab_size)
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]
    plain = np.asarray(generate(model, params, prompt, 8))
    # Pick the token the model actually emits first as "EOS" for row 0.
    eos = int(plain[0, 4])
    out = np.asarray(
        generate(model, params, prompt, 8, eos_token_id=eos, pad_token_id=63)
    )
    # Row 0 hit EOS immediately: the rest of the row is pad.
    assert out[0, 4] == eos
    assert (out[0, 5:] == 63).all()
    # Other rows keep generating until their own EOS (if any); prefixes
    # before any EOS match plain generation.
    for b in range(2):
        row = plain[b]
        hits = np.where(row[4:] == eos)[0]
        n_valid = (hits[0] + 1) if hits.size else 8
        np.testing.assert_array_equal(out[b, : 4 + n_valid], row[: 4 + n_valid])


def test_eos_all_rows_early_exit_matches_prefix():
    """When every row finishes early the loop exits; emitted prefixes are
    identical to the non-eos run, tails are pad."""
    model = TransformerLM(BASE)
    prompt = jnp.zeros((2, 3), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]
    plain = np.asarray(generate(model, params, prompt, 10))
    eos = int(plain[0, 3])  # both rows identical (same prompt): instant EOS
    out = np.asarray(generate(model, params, prompt, 10, eos_token_id=eos))
    assert (out[:, 3] == eos).all()
    assert (out[:, 4:] == eos).all()  # pad defaults to the eos id


def test_eos_is_jittable():
    model = TransformerLM(BASE)
    prompt = jnp.zeros((1, 3), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]
    jitted = jax.jit(
        lambda p, t: generate(model, p, t, 6, eos_token_id=0, pad_token_id=1)
    )
    out = jitted(params, prompt)
    assert out.shape == (1, 9)


def test_pad_without_eos_rejected():
    model = TransformerLM(BASE)
    prompt = jnp.zeros((1, 3), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]
    with pytest.raises(ValueError, match="pad_token_id requires"):
        generate(model, params, prompt, 4, pad_token_id=0)


def test_rope_base_changes_positions_but_keeps_cache_consistency():
    """A non-default rope_base must (a) change logits vs the default
    (the knob is live) and (b) keep cached decode == full recompute
    (prefill and decode apply the same wavelengths at the same absolute
    positions)."""
    cfg = dataclasses.replace(BASE, rope_base=500_000.0)
    model = TransformerLM(cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(12), (2, 5), 0, cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]

    default_model = TransformerLM(BASE)
    assert not np.allclose(
        np.asarray(model.apply({"params": params}, prompt)),
        np.asarray(default_model.apply({"params": params}, prompt)),
    )

    got = generate(model, params, prompt, max_new_tokens=6)
    want = naive_greedy(model, params, prompt, 6)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_generate_prefill_chunk_exact():
    """Chunked prefill through generate(): identical tokens to the
    single-slab prefill for standard AND rolling(+sinks) caches, at chunk
    sizes that divide the prompt, don't, and exceed it."""
    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=4, d_ff=64,
        max_seq=48, dtype=jnp.float32, attention="reference",
    )
    model = TransformerLM(cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(11), (2, 13), 0, 64)
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]
    want = np.asarray(generate(model, params, prompt, 8))
    for chunk in (1, 4, 5, 13, 64):
        got = np.asarray(
            generate(model, params, prompt, 8, prefill_chunk=chunk)
        )
        np.testing.assert_array_equal(got, want, err_msg=f"chunk={chunk}")

    rolling_cfg = dataclasses.replace(
        cfg, sliding_window=16, attention_sinks=2, rolling_cache=True
    )
    rolling = TransformerLM(rolling_cfg)
    ref_cfg = dataclasses.replace(rolling_cfg, rolling_cache=False)
    want = np.asarray(generate(TransformerLM(ref_cfg), params, prompt, 8))
    for chunk in (4, 7):
        got = np.asarray(
            generate(rolling, params, prompt, 8, prefill_chunk=chunk)
        )
        np.testing.assert_array_equal(got, want, err_msg=f"rolling chunk={chunk}")

    with pytest.raises(ValueError, match="prefill_chunk"):
        generate(model, params, prompt, 4, prefill_chunk=0)


def test_rolling_prefill_chunk1_streams_past_capacity():
    """prefill_chunk=1 streams a prompt LONGER than the rolling cache's
    capacity, exactly: token-by-token writes evict only the position just
    outside each query's band.  Oracle: the standard (full-length) cache
    with the same window+sinks mask — old positions are masked identically,
    just not physically evicted."""
    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=4, d_ff=64,
        max_seq=48, dtype=jnp.float32, attention="reference",
        sliding_window=6, attention_sinks=2,
    )
    model = TransformerLM(cfg)
    rolling = TransformerLM(dataclasses.replace(cfg, rolling_cache=True))
    prompt = jax.random.randint(jax.random.PRNGKey(13), (2, 20), 0, 64)
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]
    want = np.asarray(generate(model, params, prompt, 8))
    got = np.asarray(
        generate(rolling, params, prompt, 8, prefill_chunk=1)
    )
    np.testing.assert_array_equal(got, want)
    # Wider chunks (<= window) are exact too since r4: multi-token slabs
    # attend the pre-write ring snapshot + the slab, so a wrapping write
    # can no longer erase band-edge entries (chunk 4 does not divide 20,
    # exercising the ragged last slab; unset = auto window-wide chunks).
    np.testing.assert_array_equal(
        np.asarray(generate(rolling, params, prompt, 8, prefill_chunk=4)),
        want,
    )
    np.testing.assert_array_equal(
        np.asarray(generate(rolling, params, prompt, 8)), want
    )
    # Wider-than-window chunks would double-book ring slots: refused.
    with pytest.raises(ValueError, match="exceed sliding_window"):
        generate(rolling, params, prompt, 8, prefill_chunk=7)


def test_min_p_filter_semantics():
    """Keep tokens with prob >= min_p * max prob; mask the rest."""
    from covalent_tpu_plugin.models.decode import _filter_min_p

    logits = jnp.log(jnp.asarray([[0.5, 0.25, 0.2, 0.05]]))
    # Floor = min_p * max_prob: 0.3 * 0.5 = 0.15 keeps 0.5/0.25/0.2.
    kept = np.asarray(_filter_min_p(logits, 0.3)) > -1e29
    np.testing.assert_array_equal(kept[0], [True, True, True, False])
    # 0.4999 * 0.5 ~ 0.25 keeps the top two (0.2 falls below).
    kept = np.asarray(_filter_min_p(logits, 0.4999)) > -1e29
    np.testing.assert_array_equal(kept[0], [True, True, False, False])
    # 0.05 * 0.5 = 0.025 keeps everything.
    kept = np.asarray(_filter_min_p(logits, 0.05)) > -1e29
    np.testing.assert_array_equal(kept[0], [True, True, True, True])
    # A peaked distribution tightens the floor adaptively.
    peaked = jnp.log(jnp.asarray([[0.9, 0.05, 0.03, 0.02]]))
    kept = np.asarray(_filter_min_p(peaked, 0.3)) > -1e29
    np.testing.assert_array_equal(kept[0], [True, False, False, False])


def test_repetition_penalty_semantics():
    """HF/CTRL convention: appeared tokens' positive logits divide by the
    penalty, negative multiply; pads (-1) and unseen tokens untouched;
    token id 0 is only penalised when genuinely present."""
    from covalent_tpu_plugin.models.decode import _apply_repetition_penalty

    logits = jnp.asarray([[2.0, -2.0, 1.0, -1.0]])
    seen = jnp.asarray([[1, 2, -1, -1]])  # tokens 1 and 2 appeared
    out = np.asarray(_apply_repetition_penalty(logits, seen, 2.0))
    np.testing.assert_allclose(out[0], [2.0, -4.0, 0.5, -1.0])
    # Buffer pads masked to -1 must NOT penalise token 0.
    seen = jnp.asarray([[-1, -1, -1, -1]])
    out = np.asarray(_apply_repetition_penalty(logits, seen, 2.0))
    np.testing.assert_allclose(out[0], np.asarray(logits)[0])
    seen = jnp.asarray([[0, -1, -1, -1]])
    out = np.asarray(_apply_repetition_penalty(logits, seen, 2.0))
    np.testing.assert_allclose(out[0], [1.0, -2.0, 1.0, -1.0])


def test_generate_with_penalty_and_min_p():
    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=4, d_ff=64,
        max_seq=48, dtype=jnp.float32, attention="reference",
    )
    model = TransformerLM(cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(21), (2, 5), 0, 64)
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]
    plain = np.asarray(generate(model, params, prompt, 10))
    # Greedy + repetition penalty: jittable, valid, and actually biting
    # (the untrained model's greedy continuation revisits tokens).
    pen = np.asarray(
        jax.jit(
            lambda p, t: generate(
                model, p, t, 10, repetition_penalty=5.0
            )
        )(params, prompt)
    )
    assert pen.shape == plain.shape
    assert (pen >= 0).all() and (pen < 64).all()
    assert not np.array_equal(pen, plain)
    # Sampling with min_p runs and stays in range.
    sampled = np.asarray(
        generate(
            model, params, prompt, 10, temperature=0.8, min_p=0.1,
            rng=jax.random.PRNGKey(5),
        )
    )
    assert (sampled >= 0).all() and (sampled < 64).all()
    with pytest.raises(ValueError, match="min_p"):
        generate(model, params, prompt, 4, min_p=0.1)
    with pytest.raises(ValueError, match="repetition_penalty"):
        generate(model, params, prompt, 4, repetition_penalty=0.0)
