"""Executor ↔ resident-agent integration over the real local transport.

The agent tier of the dispatch path: a TPUExecutor with ``use_agent=True``
must compile the agent once, launch the harness through it, receive the
pushed exit event (no status-probe polling), and fall back cleanly when the
agent can't be built.
"""

import asyncio
import shutil
import sys

import pytest

from covalent_tpu_plugin import TPUExecutor

from .helpers import pin_cpu_task_env

pytestmark = pytest.mark.skipif(
    all(shutil.which(cc) is None for cc in ("g++", "c++", "clang++")),
    reason="no C++ compiler",
)

METADATA = {"dispatch_id": "dA", "node_id": 0}


@pytest.fixture(scope="module")
def shared_cache(tmp_path_factory):
    """One remote cache for the module so the agent compiles exactly once."""
    return tmp_path_factory.mktemp("agent-exec")


def make_agent_executor(shared_cache, **kwargs):
    kwargs.setdefault("transport", "local")
    kwargs.setdefault("cache_dir", str(shared_cache / "cache"))
    kwargs.setdefault("remote_cache", str(shared_cache / "remote"))
    kwargs.setdefault("python_path", sys.executable)
    kwargs.setdefault("poll_freq", 0.2)
    kwargs.setdefault("use_agent", True)
    return TPUExecutor(**pin_cpu_task_env(kwargs))


def test_agent_run_returns_result_without_status_polling(shared_cache, run_async):
    async def flow():
        ex = make_agent_executor(shared_cache)
        result = await ex.run(lambda a, b: a * b, [6, 7], {}, METADATA)
        agent = ex._agents.get("localhost")
        timings = ex.last_timings
        await ex.close()
        return result, agent, timings

    result, agent, timings = run_async(flow())
    assert result == 42
    assert agent is not None  # the agent path was actually taken
    assert "execute" in timings


def test_agent_reused_across_electrons_and_exceptions_reraise(shared_cache, run_async):
    async def flow():
        ex = make_agent_executor(shared_cache)
        assert await ex.run(lambda: "one", [], {}, METADATA) == "one"
        first_agent = ex._agents.get("localhost")

        def boom():
            raise KeyError("agent-boom")

        try:
            await ex.run(boom, [], {}, {"dispatch_id": "dB", "node_id": 1})
            raised = False
        except KeyError as err:
            raised = "agent-boom" in str(err)
        second_agent = ex._agents.get("localhost")
        await ex.close()
        return raised, first_agent is second_agent

    raised, same_agent = run_async(flow())
    assert raised
    assert same_agent  # one resident agent serves many electrons


def test_agent_unavailable_falls_back_to_polling(tmp_path, run_async):
    """A worker where the compile fails must degrade to nohup+poll, once."""

    async def flow():
        ex = TPUExecutor(
            transport="local",
            cache_dir=str(tmp_path / "cache"),
            remote_cache=str(tmp_path / "remote"),
            python_path=sys.executable,
            poll_freq=0.2,
            use_agent=True,
        )
        # Force both resident runtimes to fail: no pool, no compiler.
        from covalent_tpu_plugin import tpu as tpu_mod

        async def no_agent(*args, **kwargs):
            raise tpu_mod.AgentError("scripted: unavailable")

        orig_binary = tpu_mod.ensure_agent_binary
        orig_pool = tpu_mod.start_pool_server
        tpu_mod.ensure_agent_binary = no_agent
        tpu_mod.start_pool_server = no_agent
        try:
            result = await ex.run(lambda: "polled", [], {}, METADATA)
        finally:
            tpu_mod.ensure_agent_binary = orig_binary
            tpu_mod.start_pool_server = orig_pool
        cached = ex._agents.get("localhost", "missing")
        await ex.close()
        return result, cached

    result, cached = run_async(flow())
    assert result == "polled"
    assert cached is None  # failure remembered; no per-electron re-probe


def test_agent_cancel_kills_running_task(shared_cache, run_async):
    async def flow():
        ex = make_agent_executor(shared_cache, task_timeout=30.0)

        def sleeper():
            import time

            time.sleep(30)
            return "never"

        run_task = asyncio.ensure_future(
            ex.run(sleeper, [], {}, {"dispatch_id": "dC", "node_id": 2})
        )
        try:
            # Wait until the task is registered as active, then cancel
            # it.  Generous bound: under a fully loaded 4-worker CI box
            # the pool spawn + registration can exceed the old 10 s
            # window, making cancel a no-op and the test flake (observed
            # in the round-5 full-suite runs; passes standalone in
            # seconds).
            for _ in range(300):
                if ex._active.get("dC_2"):
                    break
                await asyncio.sleep(0.2)
            assert ex._active.get("dC_2"), "task never registered"
            await ex.cancel("dC_2")
            try:
                await asyncio.wait_for(run_task, 30.0)
                outcome = "returned"
            except asyncio.CancelledError:
                outcome = "cancelled"
            except Exception:  # noqa: BLE001
                outcome = "raised"
        finally:
            # A failed assert must not leak the 30 s sleeper / pool
            # process into the rest of the session.
            if not run_task.done():
                run_task.cancel()
                try:
                    await run_task
                except BaseException:  # noqa: BLE001
                    pass
            await ex.close()
        return outcome

    # A cancelled task must terminate promptly and surface as CANCELLATION
    # (not a failure, which could trigger the local-fallback re-run),
    # rather than sleeping out the full 30 s.
    assert run_async(flow()) == "cancelled"
