"""Model + sharded-train-step tests on the 8-device CPU mesh.

Covers the BASELINE shapes: MNIST data-parallel training (config 4) and the
transformer LM under real dp/fsdp/tp shardings (config 5's single-host
analog).  Tiny dimensions keep the tier fast; the structure (mesh, rules,
scan, remat) is exactly what runs at size on TPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from covalent_tpu_plugin.models import (
    MLP,
    MnistCNN,
    TransformerConfig,
    TransformerLM,
    synthetic_mnist,
)
from covalent_tpu_plugin.models.train import (
    TrainState,
    classifier_loss,
    cross_entropy_loss,
    lm_loss,
    make_sharded_train_state,
    make_train_step,
)
from covalent_tpu_plugin.parallel import MeshPlan, make_mesh, shard_batch

TINY_LM = TransformerConfig(
    vocab_size=256,
    d_model=64,
    n_layers=2,
    n_heads=4,
    d_ff=128,
    max_seq=32,
    dtype=jnp.float32,
    attention="reference",
)


def test_synthetic_mnist_shapes_and_determinism():
    batch = synthetic_mnist(32, seed=7)
    again = synthetic_mnist(32, seed=7)
    assert batch["image"].shape == (32, 28, 28, 1)
    assert batch["label"].shape == (32,)
    np.testing.assert_array_equal(batch["image"], again["image"])


def test_mlp_and_cnn_forward():
    batch = synthetic_mnist(4)
    for model in (MLP(), MnistCNN()):
        params = model.init(jax.random.PRNGKey(0), batch["image"])
        logits = model.apply(params, batch["image"])
        assert logits.shape == (4, 10)


def test_cross_entropy_masked():
    logits = jnp.zeros((2, 3, 5))
    labels = jnp.zeros((2, 3), jnp.int32)
    mask = jnp.array([[1, 1, 0], [0, 0, 0]], jnp.float32)
    loss = cross_entropy_loss(logits, labels, mask)
    np.testing.assert_allclose(float(loss), np.log(5), rtol=1e-5)


def test_mnist_data_parallel_training_loss_decreases():
    mesh = make_mesh(MeshPlan(data=8))
    model = MLP(features=(64,))
    batch = shard_batch(synthetic_mnist(64, seed=1), mesh)
    state, shardings = make_sharded_train_state(
        model, optax.adam(1e-2), jax.random.PRNGKey(0), batch["image"], mesh
    )
    step = make_train_step(classifier_loss, mesh, shardings)
    losses = []
    for i in range(10):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] * 0.7, losses
    assert int(state.step) == 10


def test_lm_forward_shape_and_param_sharding():
    mesh = make_mesh(MeshPlan(data=2, fsdp=2, tensor=2))
    model = TransformerLM(TINY_LM)
    tokens = shard_batch(np.zeros((8, 16), np.int32), mesh)
    state, shardings = make_sharded_train_state(
        model, optax.adamw(1e-3), jax.random.PRNGKey(0), tokens, mesh
    )
    # scanned layers: params stacked on the layers axis
    attn_kernel = state.params["layers"]["attention"]["q_proj"]["kernel"]
    assert attn_kernel.value.shape == (2, 64, 4, 16)  # (layers, embed, heads, kv)
    # heads sharded over tensor, embed over fsdp (DEFAULT_RULES)
    assert attn_kernel.value.sharding.spec == P(None, "fsdp", "tensor", None)
    embedding = state.params["embedding"]
    assert embedding.value.sharding.spec == P("tensor", "fsdp")

    with mesh:
        logits = model.apply({"params": state.params}, tokens)
    assert logits.shape == (8, 16, 256)


@pytest.mark.parametrize("remat", [False, True])
def test_lm_train_step_dp_fsdp_tp(remat):
    mesh = make_mesh(MeshPlan(data=2, fsdp=2, tensor=2))
    cfg = TransformerConfig(
        vocab_size=128,
        d_model=32,
        n_layers=2,
        n_heads=2,
        d_ff=64,
        dtype=jnp.float32,
        attention="reference",
        remat=remat,
    )
    model = TransformerLM(cfg)
    rng = np.random.default_rng(0)
    batch = shard_batch(
        {"tokens": rng.integers(0, 128, size=(8, 17)).astype(np.int32)}, mesh
    )
    state, shardings = make_sharded_train_state(
        model, optax.adamw(1e-2), jax.random.PRNGKey(0), batch["tokens"][:, :-1], mesh
    )
    step = make_train_step(lm_loss, mesh, shardings)
    losses = []
    for _ in range(5):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses


def test_lm_ring_attention_trains_on_seq_mesh():
    """Context parallelism through the whole model: mesh with a seq axis,
    attention='ring', one train step runs and matches the reference-attention
    loss on the same init."""
    mesh = make_mesh(MeshPlan(data=2, seq=4))
    base = dict(
        vocab_size=128, d_model=32, n_layers=2, n_heads=2, d_ff=64,
        dtype=jnp.float32, scan_layers=True,
    )
    rng = np.random.default_rng(1)
    tokens = rng.integers(0, 128, size=(4, 17)).astype(np.int32)

    losses = {}
    for impl in ("reference", "ring"):
        cfg = TransformerConfig(
            **base, attention=impl, mesh=mesh if impl == "ring" else None
        )
        model = TransformerLM(cfg)
        batch = shard_batch({"tokens": tokens}, mesh)
        state, shardings = make_sharded_train_state(
            model, optax.adamw(1e-2), jax.random.PRNGKey(0), batch["tokens"][:, :-1], mesh
        )
        step = make_train_step(lm_loss, mesh, shardings)
        _, metrics = step(state, batch)
        losses[impl] = float(metrics["loss"])
    np.testing.assert_allclose(losses["ring"], losses["reference"], rtol=1e-4)


def test_lm_gqa_trains_under_tensor_parallelism():
    """kv heads (2) smaller than the tensor axis (4): the kv projections
    take the replicated "kv_heads" logical axis, so the sharded init and
    train step compile instead of demanding an impossible 4-way shard of a
    size-2 axis."""
    mesh = make_mesh(MeshPlan(data=2, tensor=4))
    cfg = TransformerConfig(
        vocab_size=128,
        d_model=32,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        d_ff=64,
        dtype=jnp.float32,
        attention="reference",
    )
    model = TransformerLM(cfg)
    rng = np.random.default_rng(2)
    batch = shard_batch(
        {"tokens": rng.integers(0, 128, size=(4, 17)).astype(np.int32)}, mesh
    )
    state, shardings = make_sharded_train_state(
        model, optax.adamw(1e-2), jax.random.PRNGKey(0), batch["tokens"][:, :-1], mesh
    )
    step = make_train_step(lm_loss, mesh, shardings)
    state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))


def test_gradient_accumulation_matches_full_batch():
    """accumulate_steps=2 over half-size microbatches must produce exactly
    the full-batch update (mean loss + linear gradients)."""
    mesh = make_mesh(MeshPlan(data=2))
    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=2, d_ff=64,
        max_seq=16, dtype=jnp.float32, attention="reference",
    )
    model = TransformerLM(cfg)
    rng = np.random.default_rng(4)
    tokens = rng.integers(0, 64, size=(8, 17)).astype(np.int32)

    def build():
        batch = shard_batch({"tokens": tokens}, mesh)
        state, shardings = make_sharded_train_state(
            model, optax.sgd(1e-2), jax.random.PRNGKey(0),
            batch["tokens"][:, :-1], mesh,
        )
        return state, shardings

    state_full, shardings = build()
    step_full = make_train_step(lm_loss, mesh, shardings)
    state_full, metrics_full = step_full(
        state_full, shard_batch({"tokens": tokens}, mesh)
    )

    state_acc, shardings = build()
    step_acc = make_train_step(lm_loss, mesh, shardings, accumulate_steps=2)
    micro = {"tokens": tokens.reshape(2, 4, 17)}  # leading accumulation axis
    state_acc, metrics_acc = step_acc(
        state_acc, jax.tree_util.tree_map(jnp.asarray, micro)
    )

    np.testing.assert_allclose(
        float(metrics_acc["loss"]), float(metrics_full["loss"]), rtol=1e-5
    )
    full_leaves = jax.tree_util.tree_leaves(state_full.params)
    acc_leaves = jax.tree_util.tree_leaves(state_acc.params)
    for a, b in zip(acc_leaves, full_leaves):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-6, rtol=1e-5
        )


def test_synthetic_lm_stream_is_deterministic_and_learnable():
    from covalent_tpu_plugin.models import synthetic_lm_batch, synthetic_lm_batches

    a = synthetic_lm_batch(4, 32, 64, seed=3)
    b = synthetic_lm_batch(4, 32, 64, seed=3)
    np.testing.assert_array_equal(a["tokens"], b["tokens"])
    assert a["tokens"].dtype == np.int32
    assert a["tokens"].min() >= 0 and a["tokens"].max() < 64
    # the affine bigram rule dominates: most transitions follow it
    toks = a["tokens"].astype(np.int64)
    follows = ((toks[:, :-1] * 7 + 3) % 64 == toks[:, 1:]).mean()
    assert follows > 0.85, follows
    batches = list(synthetic_lm_batches(3, 2, 8, 64, seed=0))
    assert len(batches) == 3
    assert not np.array_equal(batches[0]["tokens"], batches[1]["tokens"])


def test_shard_batch_per_process_single_process_degenerates():
    """With one process, per-process feeding must equal global feeding."""
    from covalent_tpu_plugin.parallel import (
        process_local_slice,
        shard_batch_per_process,
    )

    mesh = make_mesh(MeshPlan(data=4, fsdp=2))
    batch = {"tokens": np.arange(8 * 4, dtype=np.int32).reshape(8, 4),
             "scale": np.float32(2.0)}
    local = process_local_slice(batch)  # 1 process -> identity
    np.testing.assert_array_equal(local["tokens"], batch["tokens"])
    placed = shard_batch_per_process(local, mesh)
    assert placed["tokens"].shape == (8, 4)
    np.testing.assert_array_equal(np.asarray(placed["tokens"]), batch["tokens"])
    # dim 0 sharded over data x fsdp (8 ways), scalar replicated
    assert len(placed["tokens"].sharding.device_set) == 8
    np.testing.assert_array_equal(np.asarray(placed["scale"]), 2.0)


def test_lm_flash_sharded_under_tp_mesh():
    """attention='flash' with config.mesh: the model routes through the
    shard_map kernel path and one sharded train step matches the dense
    reference loss on the same init."""
    import dataclasses

    mesh = make_mesh(MeshPlan(data=2, tensor=2))
    base = TransformerConfig(
        vocab_size=128, d_model=32, n_layers=2, n_heads=4, d_ff=64,
        max_seq=128, dtype=jnp.float32, attention="reference",
    )
    rng = np.random.default_rng(3)
    tokens = rng.integers(0, 128, size=(4, 129)).astype(np.int32)

    losses = {}
    for impl in ("reference", "flash"):
        cfg = dataclasses.replace(
            base, attention=impl, mesh=mesh if impl == "flash" else None
        )
        model = TransformerLM(cfg)
        batch = shard_batch({"tokens": tokens}, mesh)
        state, shardings = make_sharded_train_state(
            model, optax.adamw(1e-2), jax.random.PRNGKey(0),
            batch["tokens"][:, :-1], mesh,
        )
        step = make_train_step(lm_loss, mesh, shardings)
        _, metrics = step(state, batch)
        losses[impl] = float(metrics["loss"])
    np.testing.assert_allclose(losses["flash"], losses["reference"], rtol=1e-4)


def test_lm_gqa_heads():
    """n_kv_heads < n_heads: params carry the smaller kv projections and
    training still runs (llama-class grouped-query attention)."""
    import dataclasses

    import optax

    cfg = dataclasses.replace(TINY_LM, n_kv_heads=2)
    model = TransformerLM(cfg)
    tokens = jnp.zeros((2, 16), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    kv_kernel = params["layers"]["attention"]["k_proj"]["kernel"]
    q_kernel = params["layers"]["attention"]["q_proj"]["kernel"]
    # scan stacks a layer axis in front: (layers, embed, heads, head_dim)
    assert kv_kernel.value.shape[-2] == 2
    assert q_kernel.value.shape[-2] == 4
    logits = model.apply({"params": params}, tokens)
    assert logits.shape == (2, 16, cfg.vocab_size)

    state = TrainState.create(
        apply_fn=model.apply, params=params, tx=optax.sgd(1e-2)
    )
    loss, grads = jax.value_and_grad(
        lambda p: lm_loss(p, state.apply_fn, {"tokens": jnp.ones((2, 17), jnp.int32)})
    )(state.params)
    assert jnp.isfinite(loss)


def test_lm_gqa_flash_matches_reference_path():
    """The flash (interpret) and dense paths agree under GQA inside the
    full model, pinning the kernel's head-group convention end to end."""
    import dataclasses

    cfg_ref = dataclasses.replace(
        TINY_LM, n_kv_heads=2, max_seq=128, dtype=jnp.float32
    )
    cfg_flash = dataclasses.replace(cfg_ref, attention="flash")
    tokens = jax.random.randint(jax.random.PRNGKey(3), (1, 128), 0, 256)
    model_ref = TransformerLM(cfg_ref)
    params = model_ref.init(jax.random.PRNGKey(0), tokens)["params"]
    out_ref = model_ref.apply({"params": params}, tokens)
    out_flash = TransformerLM(cfg_flash).apply({"params": params}, tokens)
    np.testing.assert_allclose(out_ref, out_flash, atol=2e-4, rtol=2e-4)


def test_lm_unscanned_matches_structure():
    cfg = TransformerConfig(
        vocab_size=64, d_model=16, n_layers=2, n_heads=2, d_ff=32,
        dtype=jnp.float32, attention="reference", scan_layers=False,
    )
    model = TransformerLM(cfg)
    tokens = jnp.zeros((2, 8), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), tokens)
    assert "layer_0" in variables["params"] and "layer_1" in variables["params"]
    assert model.apply(variables, tokens).shape == (2, 8, 64)
