"""TPU pod worker discovery via gcloud metadata (tpu_name/zone/project).

The gcloud binary is substituted through ``COVALENT_TPU_GCLOUD_CMD`` (the
same override pattern as the pip/test contract), so these tests exercise
the real subprocess + JSON parsing path without the Cloud SDK.
"""

import json
import shlex
import sys

import pytest

from covalent_tpu_plugin.discovery import DiscoveryError, discover_tpu_workers

DESCRIBE = {
    "name": "projects/p/locations/us-west4-a/nodes/my-tpu",
    "state": "READY",
    "networkEndpoints": [
        {"ipAddress": "10.0.0.2", "accessConfig": {"externalIp": "34.1.1.1"}},
        {"ipAddress": "10.0.0.3", "accessConfig": {"externalIp": "34.1.1.2"}},
    ],
}


def _fake_gcloud(tmp_path, monkeypatch, payload, record_to=None, exit_code=0):
    out = tmp_path / "payload.json"
    out.write_text(payload if isinstance(payload, str) else json.dumps(payload))
    record = record_to or (tmp_path / "argv.json")
    monkeypatch.setenv(
        "COVALENT_TPU_GCLOUD_CMD",
        f"{shlex.quote(sys.executable)} -c "
        + shlex.quote(
            "import json,sys; json.dump(sys.argv[1:], open("
            + repr(str(record)) + ", 'w'));"
            + "sys.stdout.write(open(" + repr(str(out)) + ").read());"
            + f"sys.exit({exit_code})"
        ),
    )
    return record


def test_discovers_workers_in_order(tmp_path, monkeypatch):
    record = _fake_gcloud(tmp_path, monkeypatch, DESCRIBE)
    workers = discover_tpu_workers("my-tpu", zone="us-west4-a", project="p")
    assert workers == ["34.1.1.1", "34.1.1.2"]
    argv = json.loads(record.read_text())
    assert argv[:5] == ["compute", "tpus", "tpu-vm", "describe", "my-tpu"]
    assert "--zone=us-west4-a" in argv and "--project=p" in argv


def test_prefers_internal_when_asked(tmp_path, monkeypatch):
    _fake_gcloud(tmp_path, monkeypatch, DESCRIBE)
    workers = discover_tpu_workers("my-tpu", prefer_external=False)
    assert workers == ["10.0.0.2", "10.0.0.3"]


def test_gcloud_failure_raises_discovery_error(tmp_path, monkeypatch):
    _fake_gcloud(tmp_path, monkeypatch, DESCRIBE, exit_code=1)
    with pytest.raises(DiscoveryError, match="describe failed"):
        discover_tpu_workers("my-tpu")


def test_no_endpoints_raises(tmp_path, monkeypatch):
    _fake_gcloud(
        tmp_path, monkeypatch, {"state": "CREATING", "networkEndpoints": []}
    )
    with pytest.raises(DiscoveryError, match="CREATING"):
        discover_tpu_workers("my-tpu")


def test_executor_uses_discovery_and_caches_it(tmp_path, monkeypatch):
    from covalent_tpu_plugin import TPUExecutor

    _fake_gcloud(tmp_path, monkeypatch, DESCRIBE)
    key = tmp_path / "key"
    key.write_text("")
    ex = TPUExecutor(
        transport="ssh",
        tpu_name="my-tpu",
        zone="us-west4-a",
        project="p",
        ssh_key_file=str(key),
        cache_dir=str(tmp_path / "cache"),
        use_agent=False,
    )
    assert ex._worker_addresses() == ["34.1.1.1", "34.1.1.2"]
    assert ex._num_processes() == 2
    # Control plane dials external IPs; the coordinator must be INTERNAL
    # (VPC-reachable), or workers hang in jax.distributed.initialize.
    assert ex._coordinator_address() == f"10.0.0.2:{ex.coordinator_port}"
    # Second call must hit the cache, not re-invoke gcloud.
    monkeypatch.setenv("COVALENT_TPU_GCLOUD_CMD", "/nonexistent-gcloud")
    assert ex._worker_addresses() == ["34.1.1.1", "34.1.1.2"]


def test_executor_internal_ip_mode(tmp_path, monkeypatch):
    from covalent_tpu_plugin import TPUExecutor

    _fake_gcloud(tmp_path, monkeypatch, DESCRIBE)
    key = tmp_path / "key"
    key.write_text("")
    ex = TPUExecutor(
        transport="ssh",
        tpu_name="my-tpu",
        use_internal_ips=True,
        ssh_key_file=str(key),
        cache_dir=str(tmp_path / "cache"),
        use_agent=False,
    )
    assert ex._worker_addresses() == ["10.0.0.2", "10.0.0.3"]


def test_explicit_workers_override_discovery(tmp_path, monkeypatch):
    from covalent_tpu_plugin import TPUExecutor

    monkeypatch.setenv("COVALENT_TPU_GCLOUD_CMD", "/nonexistent-gcloud")
    ex = TPUExecutor(
        transport="local",
        tpu_name="my-tpu",
        workers=["w0", "w1"],
        cache_dir=str(tmp_path / "cache"),
        use_agent=False,
    )
    assert ex._worker_addresses() == ["w0", "w1"]  # gcloud never consulted