"""Fleet scheduler tier: queue fairness, admission, placement, autoscale.

Covers ISSUE 7's acceptance surface with no real transports where
possible: the DRR queue and placement engine run against stub pools
(deterministic, fake-clock-friendly), while the end-to-end tests drive
real ``TPUExecutor`` pools over the local transport through the workflow
engine — proving warm-gang bin-packing (connects < electrons), the
``GangLease`` seam, and breaker-aware rerouting.
"""

from __future__ import annotations

import asyncio
import json
import shlex
import sys

import pytest

import covalent_tpu_plugin.workflow as ct
from covalent_tpu_plugin.fleet import (
    FairWorkQueue,
    FleetExecutor,
    FleetScheduler,
    GangLease,
    LocalPoolAutoscaler,
    Pool,
    PoolRegistry,
    PoolSpec,
    QueueFullError,
    WorkItem,
    parse_pool_specs,
)
from covalent_tpu_plugin.fleet.scheduler import SCHED_DECISIONS_TOTAL
from covalent_tpu_plugin.resilience import FaultClass, classify_error

from .helpers import make_local_executor


def item(tenant: str, n: int = 0, **metadata) -> WorkItem:
    return WorkItem(
        fn=lambda: n,
        args=(),
        kwargs={},
        task_metadata={"dispatch_id": "d", "node_id": n, **metadata},
        tenant=tenant,
    )


# ---------------------------------------------------------------------------
# FairWorkQueue: deficit round-robin fairness
# ---------------------------------------------------------------------------


def test_drr_interleaves_equal_weight_tenants():
    queue = FairWorkQueue()
    for n in range(100):
        queue.put(item("heavy", n))
    for n in range(5):
        queue.put(item("light", 1000 + n))
    order = [queue.pop().tenant for _ in range(len(queue))]
    # The light tenant's entire backlog drains within the first rounds:
    # a 100-deep heavy lane cannot starve a 5-deep light one.
    assert order.index("light") <= 2
    assert all(t == "heavy" for t in order[12:])
    assert order[:10].count("light") == 5


def test_drr_respects_weights():
    queue = FairWorkQueue(weights={"a": 3.0, "b": 1.0})
    for n in range(40):
        queue.put(item("a", n))
        queue.put(item("b", 100 + n))
    first = [queue.pop().tenant for _ in range(16)]
    # Unit-cost DRR with quantum 1: service ratio is exactly the weights.
    assert first.count("a") == 12 and first.count("b") == 4


def test_drr_weight_must_be_positive():
    with pytest.raises(ValueError, match="weight"):
        FairWorkQueue(weights={"a": 0.0})


def test_quantum_must_be_positive():
    # quantum <= 0 would earn no lane any credit and spin pop() forever.
    with pytest.raises(ValueError, match="quantum"):
        FairWorkQueue(quantum=0.0)


def test_queue_backlog_and_oldest_age_use_injected_clock():
    now = [100.0]
    queue = FairWorkQueue(clock=lambda: now[0])
    queue.put(item("a", 1))
    now[0] += 7.5
    queue.put(item("b", 2))
    assert queue.backlog() == {"a": 1, "b": 1}
    assert queue.oldest_age() == pytest.approx(7.5)
    queue.pop()
    assert queue.depth == 1


# ---------------------------------------------------------------------------
# Admission control
# ---------------------------------------------------------------------------


def test_admission_reject_at_depth_bound_is_classified_permanent():
    queue = FairWorkQueue(max_depth=2)
    queue.put(item("a", 1))
    queue.put(item("a", 2))
    with pytest.raises(QueueFullError) as err:
        queue.put(item("a", 3))
    fault, label = classify_error(err.value)
    assert fault is FaultClass.PERMANENT
    assert label == "admission_shed"


def test_admission_shed_oldest_returns_victim():
    queue = FairWorkQueue(max_depth=2, policy="shed_oldest")
    first = item("a", 1)
    queue.put(first)
    queue.put(item("b", 2))
    shed = queue.put(item("a", 3))
    assert shed == [first]
    assert queue.depth == 2
    assert queue.backlog() == {"a": 1, "b": 1}


def test_drained_tenant_lane_and_gauge_series_retire():
    """Tenant strings are user-derived: drained lanes (and their queue-
    depth gauge series) must not accumulate for the process lifetime."""
    from covalent_tpu_plugin.obs.metrics import REGISTRY

    queue = FairWorkQueue()
    queue.put(item("ephemeral-tenant-xyz", 1))
    assert "ephemeral-tenant-xyz" in queue._lanes
    queue.pop()
    assert "ephemeral-tenant-xyz" not in queue._lanes
    gauge = REGISTRY.get("covalent_tpu_queue_depth")
    tenants = {labels["tenant"] for labels, _child in gauge._series()}
    assert "ephemeral-tenant-xyz" not in tenants


def test_facade_rejects_queue_without_pools():
    with pytest.raises(ValueError, match="require pools="):
        FleetExecutor(queue=FairWorkQueue(max_depth=1))


def test_remove_prunes_matching_items():
    queue = FairWorkQueue()
    keep = item("a", 1)
    drop = item("b", 2)
    queue.put(keep)
    queue.put(drop)
    removed = queue.remove(lambda i: i.tenant == "b")
    assert removed == [drop]
    assert queue.pop() is keep and queue.pop() is None


# ---------------------------------------------------------------------------
# Placement engine (stub pools: no transports)
# ---------------------------------------------------------------------------


class StubExecutor:
    """Duck-typed executor: records runs, controllable warmth/breakers."""

    def __init__(self, warm=False, breakers=None, delay=0.0, gate=None):
        self.warm = warm
        self.breakers = dict(breakers or {})
        self.delay = delay
        self.gate = gate  # optional event the run blocks on
        self.ran: list[dict] = []
        self.cancelled: list[str] = []
        self.concurrent = 0
        self.max_concurrent = 0

    @property
    def is_warm(self):
        return self.warm

    def gang_state(self):
        return {"warm": self.warm, "breakers": dict(self.breakers)}

    async def run(self, fn, args, kwargs, task_metadata):
        self.ran.append(dict(task_metadata))
        self.concurrent += 1
        self.max_concurrent = max(self.max_concurrent, self.concurrent)
        try:
            if self.gate is not None:
                await self.gate.wait()
            elif self.delay:
                await asyncio.sleep(self.delay)
            return fn(*args, **kwargs)
        finally:
            self.concurrent -= 1

    async def cancel(self, operation_id=None):
        self.cancelled.append(operation_id)

    async def close(self):
        self.closed = True


def stub_registry(**pools) -> tuple[PoolRegistry, dict[str, StubExecutor]]:
    registry = PoolRegistry()
    executors = {}
    for name, (executor, capacity, fallback) in pools.items():
        registry.register(
            PoolSpec(name=name, capacity=capacity, fallback=fallback,
                     transport="local"),
            executor=executor,
        )
        executors[name] = executor
    return registry, executors


def test_placement_prefers_warm_pool(run_async):
    warm = StubExecutor(warm=True)
    cold = StubExecutor(warm=False)
    registry, _ = stub_registry(cold=(cold, 2, False), warm=(warm, 2, False))
    scheduler = FleetScheduler(registry)

    async def go():
        out = await scheduler.run(lambda: "ok", (), {}, {"node_id": 1})
        await scheduler.close()
        return out

    assert run_async(go()) == "ok"
    assert len(warm.ran) == 1 and not cold.ran


def test_placement_prefers_accelerator_over_fallback(run_async):
    accel = StubExecutor(warm=False)
    cpu = StubExecutor(warm=True)  # warm fallback must still rank last
    registry, _ = stub_registry(cpu=(cpu, 2, True), accel=(accel, 2, False))
    scheduler = FleetScheduler(registry)

    async def go():
        await scheduler.run(lambda: 1, (), {}, {"node_id": 1})
        await scheduler.close()

    run_async(go())
    assert len(accel.ran) == 1 and not cpu.ran


def test_placement_honors_pool_pin(run_async):
    a = StubExecutor(warm=True)
    b = StubExecutor()
    registry, _ = stub_registry(a=(a, 2, False), b=(b, 2, False))
    scheduler = FleetScheduler(registry)

    async def go():
        await scheduler.run(
            lambda: 1, (), {}, {"node_id": 1, "pool": "b"}
        )
        await scheduler.close()

    run_async(go())
    assert len(b.ran) == 1 and not a.ran


def test_capacity_bounds_concurrency_and_bin_packs(run_async):
    pool_exec = StubExecutor(delay=0.05)
    registry, _ = stub_registry(only=(pool_exec, 2, False))
    scheduler = FleetScheduler(registry)

    async def go():
        results = await asyncio.gather(*(
            scheduler.run(lambda i=i: i, (), {}, {"node_id": i})
            for i in range(6)
        ))
        await scheduler.close()
        return results

    assert run_async(go()) == [0, 1, 2, 3, 4, 5]
    # Bin-packing: all six electrons rode ONE pool, never more than
    # `capacity` at a time.
    assert len(pool_exec.ran) == 6
    assert pool_exec.max_concurrent == 2


def test_open_breaker_reroutes_to_fallback(run_async):
    quarantined = StubExecutor(warm=True, breakers={"w1": "open"})
    fallback = StubExecutor()
    registry, _ = stub_registry(
        tpu=(quarantined, 2, False), cpu=(fallback, 2, True)
    )
    scheduler = FleetScheduler(registry)
    before = SCHED_DECISIONS_TOTAL.labels(outcome="rerouted").value

    async def go():
        out = await scheduler.run(lambda: "routed", (), {}, {"node_id": 1})
        await scheduler.close()
        return out

    assert run_async(go()) == "routed"
    assert len(fallback.ran) == 1 and not quarantined.ran
    assert scheduler.decisions["rerouted"] == 1
    assert scheduler.decisions.get("placed", 0) == 0
    assert SCHED_DECISIONS_TOTAL.labels(outcome="rerouted").value == before + 1


def test_open_breaker_below_the_winner_counts_placed_not_rerouted(run_async):
    """A quarantined pool that would NOT have won placement anyway must
    not flip the decision to `rerouted` — only a changed choice counts."""
    winner = StubExecutor(warm=True)
    loser = StubExecutor(warm=True, breakers={"w1": "open"})
    registry, _ = stub_registry(
        # winner ranks first on free slots (4 vs 1) before breakers are
        # even consulted; the open loser diverts nothing.
        a=(winner, 4, False), z=(loser, 1, False)
    )
    scheduler = FleetScheduler(registry)

    async def go():
        out = await scheduler.run(lambda: "ok", (), {}, {"node_id": 1})
        await scheduler.close()
        return out

    assert run_async(go()) == "ok"
    assert len(winner.ran) == 1
    assert scheduler.decisions["placed"] == 1
    assert scheduler.decisions["rerouted"] == 0


def test_select_pool_waits_when_everything_is_open():
    quarantined = StubExecutor(breakers={"w1": "open"})
    registry, _ = stub_registry(tpu=(quarantined, 2, False))
    scheduler = FleetScheduler(registry)
    pool, rerouted = scheduler._select_pool(item("a", 1))
    assert pool is None and rerouted is False


def test_half_open_breaker_is_placeable(run_async):
    probing = StubExecutor(breakers={"w1": "half_open"})
    registry, _ = stub_registry(tpu=(probing, 1, False))
    scheduler = FleetScheduler(registry)

    async def go():
        out = await scheduler.run(lambda: 7, (), {}, {"node_id": 1})
        await scheduler.close()
        return out

    assert run_async(go()) == 7
    assert len(probing.ran) == 1


def test_shed_policy_fails_oldest_queued_future(run_async):
    gate = asyncio.Event
    blocker = StubExecutor()
    registry, _ = stub_registry(only=(blocker, 1, False))
    scheduler = FleetScheduler(
        registry,
        queue=FairWorkQueue(max_depth=1, policy="shed_oldest"),
    )

    async def go():
        blocker.gate = asyncio.Event()
        running = asyncio.ensure_future(
            scheduler.run(lambda: "running", (), {}, {"node_id": 0})
        )
        await asyncio.sleep(0.05)  # pump places it; the slot is now busy
        queued = asyncio.ensure_future(
            scheduler.run(lambda: "queued", (), {}, {"node_id": 1})
        )
        await asyncio.sleep(0.01)  # item 1 sits at the depth bound
        newest = asyncio.ensure_future(
            scheduler.run(lambda: "newest", (), {}, {"node_id": 2})
        )
        await asyncio.sleep(0.01)
        with pytest.raises(QueueFullError, match="shed"):
            await queued
        blocker.gate.set()
        assert await running == "running"
        assert await newest == "newest"
        await scheduler.close()

    run_async(go())
    assert scheduler.decisions["shed"] == 1


def test_cancel_queued_electron_never_places_it(run_async):
    blocker = StubExecutor()
    registry, _ = stub_registry(only=(blocker, 1, False))
    scheduler = FleetScheduler(registry)

    async def go():
        blocker.gate = asyncio.Event()
        running = asyncio.ensure_future(
            scheduler.run(lambda: 1, (), {}, {"dispatch_id": "d",
                                              "node_id": 0})
        )
        await asyncio.sleep(0.05)
        queued = asyncio.ensure_future(
            scheduler.run(lambda: 2, (), {}, {"dispatch_id": "d",
                                              "node_id": 1})
        )
        await asyncio.sleep(0.01)
        await scheduler.cancel("d_1")
        with pytest.raises(asyncio.CancelledError):
            await queued
        blocker.gate.set()
        assert await running == 1
        # The in-flight electron's executor got the cancel fan-out only
        # for ids it owns; the queued one never reached a pool.
        assert len(blocker.ran) == 1
        await scheduler.close()

    run_async(go())


def test_caller_cancellation_tears_down_placed_electron(run_async):
    """Cancelling the await of scheduler.run (wait_for timeout, task
    cancel) must reach the placed electron: the owning executor's cancel
    fires and the capacity slot comes back — no detached run burning a
    slot to completion with the result discarded."""
    blocker = StubExecutor()
    registry, _ = stub_registry(only=(blocker, 1, False))
    scheduler = FleetScheduler(registry)

    async def go():
        blocker.gate = asyncio.Event()
        running = asyncio.ensure_future(
            scheduler.run(lambda: 1, (), {}, {"dispatch_id": "d",
                                              "node_id": 0})
        )
        await asyncio.sleep(0.05)
        assert len(blocker.ran) == 1  # placed, blocked on the gate
        running.cancel()
        with pytest.raises(asyncio.CancelledError):
            await running
        for _ in range(50):  # detached cleanup task fans out cancel
            if blocker.cancelled:
                break
            await asyncio.sleep(0.01)
        assert blocker.cancelled == ["d_0"]
        # The stub doesn't abort on cancel; release the gate and the
        # slot must come back even though the caller is long gone.
        blocker.gate.set()
        for _ in range(50):
            if registry.get("only").in_use == 0:
                break
            await asyncio.sleep(0.01)
        assert registry.get("only").in_use == 0
        await scheduler.close()

    run_async(go())


def test_errors_propagate_to_the_submitter(run_async):
    class Boom(RuntimeError):
        pass

    def explode():
        raise Boom("user code")

    registry, _ = stub_registry(only=(StubExecutor(), 1, False))
    scheduler = FleetScheduler(registry)

    async def go():
        with pytest.raises(Boom):
            await scheduler.run(explode, (), {}, {"node_id": 1})
        await scheduler.close()

    run_async(go())


def test_shared_facade_refuses_blanket_cancel(run_async):
    """cancel() with no operation id on a facade riding a SHARED scheduler
    must be a refused no-op — other dispatches share that queue."""
    blocker = StubExecutor()
    registry, _ = stub_registry(only=(blocker, 1, False))
    scheduler = FleetScheduler(registry)
    facade = FleetExecutor(scheduler=scheduler)

    async def go():
        blocker.gate = asyncio.Event()
        running = asyncio.ensure_future(
            facade.run(lambda: 1, (), {}, {"node_id": 0})
        )
        await asyncio.sleep(0.05)
        queued = asyncio.ensure_future(
            facade.run(lambda: 2, (), {}, {"node_id": 1})
        )
        await asyncio.sleep(0.01)
        await facade.cancel()  # no op id + shared scheduler: refused
        assert scheduler.queue.depth == 1
        blocker.gate.set()
        assert await running == 1
        assert await queued == 2
        await scheduler.close()

    run_async(go())


def test_scheduler_clock_threads_into_default_queue():
    registry, _ = stub_registry(only=(StubExecutor(), 1, False))
    now = [50.0]
    scheduler = FleetScheduler(registry, clock=lambda: now[0])
    # One clock for placement events AND queue aging — a fake-clock test
    # must never mix time.monotonic into queue_wait_s / oldest_age.
    scheduler.queue.put(item("a", 1))
    now[0] += 4.0
    assert scheduler.queue.oldest_age() == pytest.approx(4.0)


def test_register_replace_closes_displaced_executor(run_async):
    old_exec = StubExecutor()
    registry = PoolRegistry()
    registry.register(
        PoolSpec(name="p", capacity=1, transport="local"), executor=old_exec
    )
    _ = registry.get("p").executor  # started

    async def go():
        registry.register(
            PoolSpec(name="p", capacity=2, transport="local"),
            executor=StubExecutor(),
        )
        await asyncio.sleep(0)  # let the displaced-close task run
        assert getattr(old_exec, "closed", False) is True
        assert registry.get("p").capacity == 2

    run_async(go())


# ---------------------------------------------------------------------------
# Autoscale watermarks
# ---------------------------------------------------------------------------


def test_autoscale_watermarks_fire_edge_triggered(run_async):
    blocker = StubExecutor()
    registry, _ = stub_registry(only=(blocker, 1, False))
    # cooldown_s=0: this test exercises the edge-triggered watermark
    # wiring on a real clock; the anti-thrash dwell has its own
    # fake-clock regression test in test_autoscale.py.
    autoscaler = LocalPoolAutoscaler(
        "only", step=2, max_capacity=4, cooldown_s=0.0
    )
    scheduler = FleetScheduler(
        registry, autoscale=autoscaler, high_watermark=2, low_watermark=0
    )

    async def go():
        blocker.gate = asyncio.Event()
        futures = [
            asyncio.ensure_future(
                scheduler.run(lambda i=i: i, (), {}, {"node_id": i})
            )
            for i in range(4)
        ]
        await asyncio.sleep(0.05)
        # Backlog crossed the high watermark exactly once.
        assert autoscaler.scale_ups == 1
        assert registry.get("only").capacity == 3
        blocker.gate.set()
        assert await asyncio.gather(*futures) == [0, 1, 2, 3]
        await asyncio.sleep(0.05)
        await scheduler.close()

    run_async(go())
    # Draining back to the low watermark fired exactly one scale-down.
    assert autoscaler.scale_downs == 1
    assert registry.get("only").capacity == 1


def test_default_autoscale_hook_is_noop(run_async):
    registry, _ = stub_registry(only=(StubExecutor(), 1, False))
    scheduler = FleetScheduler(registry, high_watermark=1)

    async def go():
        out = await scheduler.run(lambda: 5, (), {}, {"node_id": 1})
        await scheduler.close()
        return out

    assert run_async(go()) == 5  # no hook, no crash


# ---------------------------------------------------------------------------
# Pool specs / registry / discovery wiring
# ---------------------------------------------------------------------------


def test_parse_compact_pool_specs():
    specs = parse_pool_specs(
        "v5e=10.0.0.1+10.0.0.2@4; spare=tpu:my-v5e-8@2; cpu=local@3"
    )
    by_name = {s.name: s for s in specs}
    assert by_name["v5e"].workers == ("10.0.0.1", "10.0.0.2")
    assert by_name["v5e"].capacity == 4
    assert by_name["spare"].tpu_name == "my-v5e-8"
    assert by_name["cpu"].transport == "local"
    assert by_name["cpu"].fallback and by_name["cpu"].capacity == 3


def test_parse_pool_spec_roles():
    """A trailing '!role' marks the serving role (disaggregated
    placement); it composes with capacity and user@host addresses and
    rides the JSON form as a first-class field."""
    specs = parse_pool_specs(
        "pre=10.0.0.1@2!prefill; dec=ubuntu@10.0.0.2@4!decode; n=10.0.0.3"
    )
    by_name = {s.name: s for s in specs}
    assert by_name["pre"].role == "prefill" and by_name["pre"].capacity == 2
    assert by_name["dec"].role == "decode"
    assert by_name["dec"].workers == ("ubuntu@10.0.0.2",)
    assert by_name["n"].role == ""
    [json_spec] = parse_pool_specs(
        json.dumps({"name": "p", "workers": ["w"], "role": "prefill"})
    )
    assert json_spec.role == "prefill"


def test_parse_json_pool_specs():
    specs = parse_pool_specs(json.dumps([
        {"name": "a", "workers": ["w1"], "capacity": 2},
        {"name": "cpu", "fallback": True},
    ]))
    assert specs[0].workers == ("w1",) and specs[0].capacity == 2
    assert specs[1].fallback


@pytest.mark.parametrize("bad", ["nameonly", "x=@", "a=w1@cap_zz", "y=@4"])
def test_parse_rejects_malformed_entries(bad):
    with pytest.raises(ValueError):
        parse_pool_specs(bad)


def test_parse_keeps_login_in_worker_addresses():
    """A trailing '@suffix' is capacity only when numeric; 'user@host'
    worker addresses survive intact (with or without an explicit @capN)."""
    specs = parse_pool_specs(
        "edge=ubuntu@10.0.0.9;v5e=ubuntu@10.0.0.1+root@10.0.0.2@4"
    )
    by_name = {s.name: s for s in specs}
    assert by_name["edge"].workers == ("ubuntu@10.0.0.9",)
    assert by_name["edge"].capacity == 1
    assert by_name["v5e"].workers == ("ubuntu@10.0.0.1", "root@10.0.0.2")
    assert by_name["v5e"].capacity == 4


def test_registry_from_environment(monkeypatch):
    monkeypatch.setenv("COVALENT_TPU_POOLS", "a=w1@2;cpu=local@1")
    registry = PoolRegistry.from_environment()
    assert {p.name for p in registry.pools()} == {"a", "cpu"}
    assert registry.fallback_pool().name == "cpu"
    assert registry.total_capacity() == 3


def test_ensure_fallback_is_idempotent():
    registry = PoolRegistry()
    first = registry.ensure_fallback()
    assert registry.ensure_fallback() is first
    assert first.fallback and first.spec.transport == "local"


def test_register_tpu_resolves_workers_via_discovery(tmp_path, monkeypatch):
    payload = tmp_path / "describe.json"
    payload.write_text(json.dumps({
        "state": "READY",
        "networkEndpoints": [
            {"ipAddress": "10.0.0.2",
             "accessConfig": {"externalIp": "34.1.1.1"}},
            {"ipAddress": "10.0.0.3",
             "accessConfig": {"externalIp": "34.1.1.2"}},
        ],
    }))
    monkeypatch.setenv(
        "COVALENT_TPU_GCLOUD_CMD",
        f"{shlex.quote(sys.executable)} -c " + shlex.quote(
            "import sys; sys.stdout.write(open("
            + repr(str(payload)) + ").read())"
        ),
    )
    registry = PoolRegistry()
    pool = registry.register_tpu("my-v5e", zone="us-west4-a", capacity=4)
    assert pool.spec.workers == ("34.1.1.1", "34.1.1.2")
    assert pool.capacity == 4 and pool.spec.tpu_name == "my-v5e"
    assert registry.get("my-v5e") is pool
    # Registration-time endpoints seed the executor's discovery cache:
    # no second gcloud subprocess at first dispatch (prove it by making
    # any further invocation fail loudly).
    assert pool.spec.endpoints == (
        ("34.1.1.1", "10.0.0.2"), ("34.1.1.2", "10.0.0.3"),
    )
    monkeypatch.setenv("COVALENT_TPU_GCLOUD_CMD", "false")
    assert pool.executor._coordinator_address() == "10.0.0.2:8476"
    assert pool.executor.gang_state()["workers"] == ["34.1.1.1", "34.1.1.2"]


def test_gang_state_never_runs_discovery(monkeypatch):
    """The scheduler pump reads gang_state() synchronously on the event
    loop; an undiscovered tpu_name must report no addresses rather than
    block on a gcloud subprocess."""
    from covalent_tpu_plugin import discovery
    from covalent_tpu_plugin.tpu import TPUExecutor

    def boom(*_args, **_kwargs):
        raise AssertionError("gang_state must not run discovery")

    monkeypatch.setattr(discovery, "discover_tpu_endpoints", boom)
    ex = TPUExecutor(tpu_name="never-discovered", transport="ssh",
                     ssh_key_file="/dev/null")
    state = ex.gang_state()
    assert state["workers"] == [] and state["warm"] is False


def test_pool_slot_accounting():
    pool = Pool(PoolSpec(name="p", capacity=2, transport="local"),
                executor=StubExecutor())
    assert pool.free_slots == 2
    pool.place()
    pool.place()
    assert pool.free_slots == 0 and pool.in_use == 2
    pool.release()
    assert pool.free_slots == 1 and pool.placed_total == 2
    status = pool.status()
    assert status["capacity"] == 2 and status["in_use"] == 1


# ---------------------------------------------------------------------------
# GangLease seam (real executor, local transport)
# ---------------------------------------------------------------------------


def test_lease_gang_warms_and_discard_cools(tmp_path, run_async):
    ex = make_local_executor(tmp_path)

    async def go():
        assert not ex.is_warm
        lease = await ex.lease_gang()
        assert isinstance(lease, GangLease)
        assert len(lease) == 1 and lease.owner is ex
        assert ex.is_warm
        state = ex.gang_state()
        assert state["warm"] is True
        assert set(state["breakers"].values()) <= {"closed"}
        await lease.discard()
        assert not ex.is_warm
        await ex.close()

    run_async(go())


def test_lease_gang_hands_dialed_conns_out_on_preflight_failure(
    tmp_path, run_async, monkeypatch
):
    """A pre-flight failure must still expose the dialed channels via the
    `dialed` out-param — the retry driver discards exactly those before a
    redial, or the next attempt reuses the broken pooled transports."""
    from covalent_tpu_plugin.transport import TransportError

    ex = make_local_executor(tmp_path)

    async def broken_preflight(conn, key=None):
        raise TransportError("preflight exploded")

    monkeypatch.setattr(ex, "_preflight", broken_preflight)

    async def go():
        dialed = []
        with pytest.raises(TransportError, match="preflight exploded"):
            await ex.lease_gang(dialed=dialed)
        assert len(dialed) == 1  # the connect succeeded and is exposed
        await ex.close()

    run_async(go())


def test_pump_rebind_releases_orphaned_slots(run_async):
    """Loop migration must give in-flight slots back: the old loop's
    _run_item finallys never ran, and leaked in_use would deadlock."""
    pool_exec = StubExecutor()
    registry, _ = stub_registry(only=(pool_exec, 2, False))
    scheduler = FleetScheduler(registry)
    pool = registry.get("only")
    dead_loop = asyncio.new_event_loop()
    dead_loop.close()
    pool.place()
    scheduler._loop = dead_loop
    scheduler._running["orphan_0"] = (pool, item("a", 0), None)

    async def go():
        out = await scheduler.run(lambda: "alive", (), {}, {"node_id": 9})
        await scheduler.close()
        return out

    assert run_async(go()) == "alive"
    assert pool.in_use == 0  # orphaned slot was released on rebind


def test_private_fleet_honors_queue_config(tmp_config):
    from covalent_tpu_plugin.utils.config import update_config

    update_config(
        {"queue_depth": 7, "admission": "shed_oldest",
         "tenant_weights": {"batch": 2.0}},
        section="fleet",
    )
    fleet = FleetExecutor(
        pools=[{"name": "p", "transport": "local", "capacity": 1}],
        ensure_fallback=False,
    )
    queue = fleet.scheduler.queue
    assert queue.max_depth == 7
    assert queue.policy == "shed_oldest"
    assert queue.weight("batch") == 2.0


def test_run_attempt_rides_the_lease_seam(tmp_path, run_async):
    """An electron through run() leaves the executor warm: the attempt
    machine acquired its gang through lease_gang, not ad-hoc dials."""
    ex = make_local_executor(tmp_path)

    async def go():
        out = await ex.run(lambda x: x + 1, [41], {},
                           {"dispatch_id": "lease", "node_id": 0})
        warm = ex.is_warm
        await ex.close()
        return out, warm

    out, warm = run_async(go())
    assert out == 42 and warm


# ---------------------------------------------------------------------------
# End to end: FleetExecutor over real local pools
# ---------------------------------------------------------------------------


def local_pool_spec(tmp_path, name: str, capacity: int, fallback=False):
    return {
        "name": name,
        "transport": "local",
        "capacity": capacity,
        "fallback": fallback,
        "executor": {
            "cache_dir": str(tmp_path / f"cache_{name}"),
            "remote_cache": str(tmp_path / f"remote_{name}"),
            "python_path": sys.executable,
            "poll_freq": 0.2,
            "use_agent": False,
            "prewarm": False,
            "task_env": {"JAX_PLATFORMS": "cpu"},
        },
    }


def pool_connects() -> float:
    from covalent_tpu_plugin.obs.metrics import REGISTRY

    counter = REGISTRY.get("covalent_tpu_pool_acquires_total")
    if counter is None:
        return 0.0
    return sum(
        value.value
        for labels, value in counter._series()
        if labels.get("result") == "miss"
    )


def test_fleet_bin_packs_mixed_tenants_onto_warm_gangs(tmp_path, run_async):
    """The acceptance workflow, scaled for the unit tier: 8 electrons,
    2 tenants, 2 pools — every electron completes, connects < electrons
    (warm-gang reuse), placements spread over both pools."""
    fleet = FleetExecutor(
        pools=[
            local_pool_spec(tmp_path, "a", 2),
            local_pool_spec(tmp_path, "b", 2),
        ],
        ensure_fallback=False,
    )
    connects_before = pool_connects()

    async def go():
        results = await asyncio.gather(*(
            fleet.run(
                lambda i=i: i * i, (), {},
                {"dispatch_id": "fleet-e2e", "node_id": i,
                 "tenant": "heavy" if i % 2 else "light"},
            )
            for i in range(8)
        ))
        status = fleet.scheduler.status()
        await fleet.close()
        return results, status

    results, status = run_async(go())
    assert results == [i * i for i in range(8)]
    placed = {
        name: view["placed_total"]
        for name, view in status["pools"].items()
    }
    assert sum(placed.values()) == 8
    assert all(count > 0 for count in placed.values()), placed
    # Warm-gang reuse: 8 electrons over 2 single-worker local pools dial
    # at most once per pool — strictly fewer connects than electrons.
    connects = pool_connects() - connects_before
    assert 0 < connects <= 2, connects


def test_fleet_executor_through_workflow_engine(tmp_path):
    """@ct.electron(executor=<FleetExecutor>) + tenant metadata: the
    runner threads electron metadata into task_metadata, and the whole
    lattice completes through the queue."""
    fleet = FleetExecutor(
        pools=[local_pool_spec(tmp_path, "wf", 2)],
        ensure_fallback=False,
    )

    @ct.electron(executor=fleet, metadata={"tenant": "batch"})
    def square(i):
        return i * i

    @ct.lattice
    def flow(n):
        return [square(i) for i in range(n)]

    result = ct.dispatch_sync(flow)(4)
    assert result.status is ct.Status.COMPLETED, result.error
    assert result.result == [0, 1, 4, 9]
    pool = fleet.scheduler.registry.get("wf")
    assert pool.placed_total == 4
    # Every electron ran under its metadata tenant.
    assert fleet.scheduler.queue.backlog() == {}

    # Teardown on the loop that owns the pooled transports.
    from covalent_tpu_plugin.workflow import runner as runner_mod

    asyncio.run_coroutine_threadsafe(
        fleet.close(), runner_mod._dispatcher_loop()
    ).result(30)


def test_metadata_cannot_smuggle_runner_keys():
    """Electron metadata must not inject runner-managed keys: pip_deps is
    DepsPip's contract, and dispatch/node identity is never user-set."""
    recorder = StubExecutor()

    @ct.electron(
        executor=recorder,
        metadata={"pip_deps": ["evil-pkg"], "tenant": "t", "node_id": 99},
    )
    def task():
        return 1

    @ct.lattice
    def flow():
        return task()

    result = ct.dispatch_sync(flow)()
    assert result.status is ct.Status.COMPLETED, result.error
    metadata = recorder.ran[0]
    assert "pip_deps" not in metadata
    assert metadata["tenant"] == "t"
    assert metadata["node_id"] == 0  # the runner's id, not the user's


def test_fleet_alias_resolves(tmp_path, monkeypatch):
    """executor="fleet" resolves to a FleetExecutor over the default
    scheduler (pools from COVALENT_TPU_POOLS + auto fallback)."""
    from covalent_tpu_plugin.fleet import executor as fleet_executor_mod
    from covalent_tpu_plugin.workflow.executors import resolve_executor

    monkeypatch.setenv("COVALENT_TPU_POOLS", "")
    fleet_executor_mod.reset_default_scheduler()
    try:
        instance = resolve_executor("fleet")
        assert isinstance(instance, FleetExecutor)
        scheduler = instance.scheduler
        assert scheduler.registry.fallback_pool() is not None
    finally:
        fleet_executor_mod.reset_default_scheduler()


def test_ops_status_carries_fleet_section(run_async):
    """The scheduler's registered provider surfaces as a top-level
    `fleet` section in the ops /status payload."""
    from covalent_tpu_plugin.obs import opsserver

    registry, _ = stub_registry(only=(StubExecutor(), 2, False))
    scheduler = FleetScheduler(registry)
    server = opsserver.OpsServer(0)
    try:
        status = server.status()
        assert "fleet" in status
        fleet_view = status["fleet"]
        assert fleet_view["queue"]["depth"] == 0
        assert fleet_view["pools"]["only"]["capacity"] == 2
        assert "decisions" in fleet_view
    finally:
        server.close()

    async def go():
        await scheduler.close()

    run_async(go())


def test_parse_pool_spec_spot_tag():
    """'!spot' (or '!preemptible') marks spot capacity; it stacks with a
    serving role and rides the JSON form as a first-class field."""
    specs = parse_pool_specs(
        "cheap=10.0.0.1@4!spot; mixed=10.0.0.2@2!decode!spot; s=10.0.0.3"
    )
    by_name = {s.name: s for s in specs}
    assert by_name["cheap"].preemptible and by_name["cheap"].capacity == 4
    assert by_name["mixed"].preemptible and by_name["mixed"].role == "decode"
    assert not by_name["s"].preemptible
    [json_spec] = parse_pool_specs(
        json.dumps({"name": "p", "workers": ["w"], "preemptible": True})
    )
    assert json_spec.preemptible


def test_placement_prefers_stable_over_spot_unless_opted_in(run_async):
    """Spot pools rank after stable ones for ordinary electrons; a
    'spot_ok' electron takes the (warm) spot pool — checkpoint-tolerant
    work rides cheap capacity, everything else pins to stable."""
    spot = StubExecutor(warm=True)  # warm spot must STILL lose...
    stable = StubExecutor(warm=False)
    registry = PoolRegistry()
    registry.register(
        PoolSpec(name="spot", capacity=2, transport="local",
                 preemptible=True),
        executor=spot,
    )
    registry.register(
        PoolSpec(name="stable", capacity=2, transport="local"),
        executor=stable,
    )
    scheduler = FleetScheduler(registry)

    async def go():
        await scheduler.run(lambda: 1, (), {}, {"node_id": 1})
        await scheduler.run(
            lambda: 2, (), {}, {"node_id": 2, "spot_ok": True}
        )
        await scheduler.close()

    run_async(go())
    assert len(stable.ran) == 1  # ordinary electron avoided spot
    assert len(spot.ran) == 1    # opted-in electron took the warm spot pool


def test_preemptible_pool_defaults_to_checkpoint_heavy_dispatch(tmp_path):
    """A spot pool's real executor gets checkpoint-heavy dispatch by
    default (reclaims resume, not recompute); explicit kwargs win."""
    from covalent_tpu_plugin.fleet.pools import _default_executor_factory

    spec = PoolSpec(
        name="spot", transport="local", preemptible=True,
        executor={"cache_dir": str(tmp_path / "c")},
    )
    ex = _default_executor_factory(spec)
    assert ex.checkpoint_interval_s == 60.0
    spec_explicit = PoolSpec(
        name="spot2", transport="local", preemptible=True,
        executor={
            "cache_dir": str(tmp_path / "c2"),
            "checkpoint_interval_s": 5.0,
        },
    )
    assert _default_executor_factory(
        spec_explicit
    ).checkpoint_interval_s == 5.0
    spec_stable = PoolSpec(
        name="stable", transport="local",
        executor={"cache_dir": str(tmp_path / "c3")},
    )
    assert _default_executor_factory(spec_stable).checkpoint_interval_s == 0.0
