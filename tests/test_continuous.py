"""Continuous batching: admission order must never change tokens.

The oracle is plain ``generate()`` per prompt — a slot's vmapped lane
computes exactly what a batch-1 decode computes (no cross-batch
reductions), so greedy outputs must be BIT-identical however requests
share slots.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from covalent_tpu_plugin.models import (
    TransformerConfig,
    TransformerLM,
    continuous_generate,
    generate,
)

CFG = TransformerConfig(
    vocab_size=64,
    d_model=32,
    n_layers=2,
    n_heads=4,
    d_ff=64,
    max_seq=48,
    dtype=jnp.float32,
    attention="reference",
)


def build(seed=0):
    model = TransformerLM(CFG)
    params = model.init(
        jax.random.PRNGKey(seed), jnp.zeros((1, 4), jnp.int32)
    )["params"]
    return model, params


#: One shared model/params for the whole module (round-5 test-tier
#: speedup: this file alone ran 8+ minutes, dominated by per-test inits
#: and UNJITTED oracle decodes — eager while_loops pay hundreds of op
#: dispatches per token).
MODEL, PARAMS = None, None


def shared():
    global MODEL, PARAMS
    if MODEL is None:
        MODEL, PARAMS = build()
    return MODEL, PARAMS


_jit_oracle = {}


def oracle(model, params, prompt, cap, eos=None):
    """Jitted per-shape batch-1 generate(), cached across tests: the
    oracle for every bit-equality assertion here.  Keyed on the model
    OBJECT (flax modules are hashable dataclasses), not ``id(model)`` —
    a GC'd model's id can be reused by a different module, which would
    silently serve the wrong compiled oracle."""
    key = (model, prompt.size, cap, eos)
    if key not in _jit_oracle:
        _jit_oracle[key] = jax.jit(
            lambda pp, t: generate(
                model, pp, t, cap, eos_token_id=eos
            )
        )
    return np.asarray(_jit_oracle[key](params, jnp.asarray(prompt[None])))[0]


def ragged_prompts(n, base_seed=0):
    return [
        np.asarray(
            jax.random.randint(
                jax.random.PRNGKey(base_seed + i), (3 + i % 4,), 0,
                CFG.vocab_size,
            ),
            np.int32,
        )
        for i in range(n)
    ]


@pytest.mark.parametrize("prefill", ["batched", "stream"])
@pytest.mark.parametrize("max_batch,sync_steps", [(1, 1), (2, 4), (3, 8)])
def test_greedy_bit_equal_to_generate(max_batch, sync_steps, prefill):
    """Every served output == the standalone greedy continuation, across
    slot counts (1 = fully serial), sync granularities, both admission
    prefill modes (one padded batched pass vs chunk-1 streaming), and
    ragged prompt lengths that force multiple admission waves."""
    model, params = shared()
    prompts = ragged_prompts(5)
    outs = continuous_generate(
        model, params, prompts, 8, max_batch=max_batch,
        sync_steps=sync_steps, prefill=prefill,
    )
    assert len(outs) == len(prompts)
    for p, o in zip(prompts, outs):
        np.testing.assert_array_equal(o, oracle(model, params, p, 8))


@pytest.mark.parametrize("prefill", ["batched", "stream"])
def test_eos_frees_slots_early(prefill):
    """Rows stop at their own EOS (token included, output trimmed), and
    the freed slot serves later queue entries — outputs still match the
    per-prompt oracle up to and including EOS.  Covers both admission
    modes: batched admission has its own first-token EOS check."""
    model, params = shared()
    prompts = ragged_prompts(6, base_seed=20)
    # Pick an eos id that actually occurs in some greedy continuations:
    # try a few ids and use the one hit most often.  One jitted decode
    # per prompt length, shared across all eight candidate ids.
    conts = [oracle(model, params, p, 10)[p.size:] for p in prompts]
    hits = {
        eos: sum(int((c == eos).any()) for c in conts) for eos in range(8)
    }
    eos = max(hits, key=hits.get)
    outs = continuous_generate(
        model, params, prompts, 10, max_batch=2, eos_token_id=eos,
        sync_steps=3, prefill=prefill,
    )
    for p, o in zip(prompts, outs):
        want_full = oracle(model, params, p, 10, eos=eos)
        gen = o[p.size:]
        eos_pos = np.where(gen == eos)[0]
        if eos_pos.size:  # trimmed at (and including) the first EOS
            assert gen[-1] == eos and (gen[:-1] != eos).all()
        np.testing.assert_array_equal(o, want_full[: o.size])


def test_per_request_token_budgets():
    """Each request can carry its own max_new_tokens; row i must equal
    generate(prompt_i, cap_i) bit-for-bit, and a slot freed by a small
    budget serves later queue entries (5 requests, 2 slots)."""
    model, params = shared()
    prompts = ragged_prompts(5, base_seed=60)
    caps = [3, 12, 5, 8, 1]
    outs = continuous_generate(
        model, params, prompts, caps, max_batch=2, sync_steps=4
    )
    for p, c, o in zip(prompts, caps, outs):
        np.testing.assert_array_equal(o, oracle(model, params, p, c))
    with pytest.raises(ValueError, match="entries for"):
        continuous_generate(model, params, prompts, [4, 4], max_batch=2)
    with pytest.raises(ValueError, match=">= 1"):
        continuous_generate(model, params, prompts, [4, 4, 0, 4, 4])


@pytest.mark.parametrize("prefill", ["batched", "stream"])
def test_sampling_deterministic_per_rng(prefill):
    model, params = shared()
    prompts = ragged_prompts(3, base_seed=40)
    kwargs = dict(
        max_batch=2, temperature=0.8, top_k=16,
        rng=jax.random.PRNGKey(7), sync_steps=4, prefill=prefill,
    )
    a = continuous_generate(model, params, prompts, 6, **kwargs)
    b = continuous_generate(model, params, prompts, 6, **kwargs)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)
    # Tokens stay in-vocab and outputs are full length (no EOS set).
    for p, x in zip(prompts, a):
        assert x.size == p.size + 6
        assert (x >= 0).all() and (x < CFG.vocab_size).all()


def test_composes_with_quantized_serving_stack():
    """The serving matrix closes: continuous batching over an int8-weight
    + int8-KV model is bit-identical to that quantized model's own
    plain decode per prompt."""
    from covalent_tpu_plugin.models import quantize_lm

    model = TransformerLM(dataclasses.replace(CFG, scan_layers=False))
    prompts = ragged_prompts(4, base_seed=80)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )["params"]
    qmodel, qparams = quantize_lm(model, params)
    qmodel = TransformerLM(
        dataclasses.replace(qmodel.config, quantized_kv_cache=True)
    )
    outs = continuous_generate(
        qmodel, qparams, prompts, 8, max_batch=2, sync_steps=4
    )
    for p, o in zip(prompts, outs):
        np.testing.assert_array_equal(
            o, oracle(qmodel, qparams, p, 8)
        )


def test_validation():
    model, params = shared()
    prompts = ragged_prompts(2)
    with pytest.raises(ValueError, match="rolling_cache"):
        rolling = TransformerLM(dataclasses.replace(
            CFG, sliding_window=6, rolling_cache=True
        ))
        continuous_generate(rolling, params, prompts, 4)
    with pytest.raises(ValueError, match="max_seq"):
        continuous_generate(model, params, prompts, 1000)
    with pytest.raises(ValueError, match="requires rng"):
        continuous_generate(model, params, prompts, 4, temperature=0.5)
    with pytest.raises(ValueError, match="top_k requires"):
        continuous_generate(model, params, prompts, 4, top_k=4)
    with pytest.raises(ValueError, match="at least one token"):
        continuous_generate(model, params, [np.zeros(0, np.int32)], 4)
    with pytest.raises(ValueError, match="prefill must be"):
        continuous_generate(model, params, prompts, 4, prefill="turbo")
    assert continuous_generate(model, params, [], 4) == []


# ---------------------------------------------------------------------------
# ContinuousEngine: the same fixed-slot loop turned inside out for a
# resident serving session (ISSUE 9).  Oracle discipline is identical —
# whatever order requests are admitted, streamed, cancelled, every
# completed stream must be BIT-equal to the standalone greedy decode.
# ---------------------------------------------------------------------------


def drive_engine(engine, requests, max_steps=400):
    """Admit ``{rid: (prompt, cap)}`` as lanes free up and run the step
    loop dry; returns (streamed tokens per rid, chunk counts per rid)."""
    queue = list(requests.items())
    streams = {rid: [] for rid in requests}
    chunks = {rid: 0 for rid in requests}
    done = set()
    for _ in range(max_steps):
        while queue and engine.busy < engine.slots:
            rid, (prompt, cap) = queue.pop(0)
            engine.admit(rid, prompt, {"max_new_tokens": cap})
        for event in engine.step():
            streams[event["rid"]].extend(event["tokens"])
            chunks[event["rid"]] += 1
            if event["done"]:
                done.add(event["rid"])
        if len(done) == len(requests) and not queue:
            return streams, chunks
    raise AssertionError(f"engine never drained: {sorted(done)}")


def test_engine_streams_bit_equal_to_generate():
    """5 ragged requests through 2 slots, admitted incrementally as lanes
    free: every streamed sequence == the batch-1 greedy oracle, and the
    sync-chunked delivery is genuinely incremental (multiple chunks per
    request, first one carrying the admission-prefill token)."""
    from covalent_tpu_plugin.models.serve import ContinuousEngine

    model, params = shared()
    prompts = ragged_prompts(5, base_seed=40)
    engine = ContinuousEngine(
        model, params, max_batch=2, sync_steps=3, max_new_tokens=8,
    )
    streams, chunks = drive_engine(
        engine, {f"r{i}": (p, 8) for i, p in enumerate(prompts)},
    )
    for i, p in enumerate(prompts):
        want = oracle(model, params, p, 8)[p.size:]
        np.testing.assert_array_equal(streams[f"r{i}"], want)
        assert chunks[f"r{i}"] >= 2  # 8 tokens / sync_steps=3: chunked
    engine.close()


def test_engine_per_request_budgets_and_cancel():
    """Per-request max_new_tokens, a cancelled lane freed mid-decode, and
    the freed slot re-admitting a queued request — survivors still match
    the oracle bit-for-bit."""
    from covalent_tpu_plugin.models.serve import ContinuousEngine

    model, params = shared()
    prompts = ragged_prompts(3, base_seed=50)
    engine = ContinuousEngine(
        model, params, max_batch=2, sync_steps=2, max_new_tokens=6,
    )
    engine.admit("keep", prompts[0], {"max_new_tokens": 6})
    engine.admit("drop", prompts[1], {"max_new_tokens": 6})
    engine.step()  # both prefilled, one chunk decoded
    engine.cancel("drop")  # deadline/disconnect: lane freed mid-decode
    engine.admit("late", prompts[2], {"max_new_tokens": 3})
    streams = {"keep": [], "late": []}
    for _ in range(100):
        events = engine.step()
        for event in events:
            if event["rid"] in streams:
                streams[event["rid"]].extend(event["tokens"])
        if not engine.busy:
            break
    # `keep`'s first chunk landed before the cancel; recover it from the
    # oracle prefix to assert the TAIL decoded after the perturbation.
    want_keep = oracle(model, params, prompts[0], 6)[prompts[0].size:]
    assert streams["keep"] == list(want_keep)[-len(streams["keep"]):]
    np.testing.assert_array_equal(
        streams["late"],
        oracle(model, params, prompts[2], 6)[prompts[2].size:][:3],
    )
    engine.close()


def test_engine_validation_and_typed_rolling_refusal():
    """Admission guards reject malformed requests with the lane intact,
    and a rolling_cache model is refused with the TYPED error carrying
    the PERMANENT duck-tags the serving RPC forwards."""
    from covalent_tpu_plugin.models.serve import (
        ContinuousEngine,
        RollingCacheUnsupported,
        lm_engine_factory,
    )
    from covalent_tpu_plugin.resilience import FaultClass, classify_error

    model, params = shared()
    engine = lm_engine_factory(
        model, params, max_batch=1, sync_steps=2, max_new_tokens=4,
    )()
    assert isinstance(engine, ContinuousEngine)
    engine.admit("r1", np.asarray([1, 2, 3], np.int32))
    with pytest.raises(ValueError, match="already admitted"):
        engine.admit("r1", np.asarray([4], np.int32))
    with pytest.raises(RuntimeError, match="no free lane"):
        engine.admit("r2", np.asarray([4], np.int32))
    with pytest.raises(ValueError, match="at least one token"):
        engine.admit("r3", np.zeros(0, np.int32))
    with pytest.raises(ValueError, match="exceeds the"):
        engine.admit("r4", np.asarray([1], np.int32),
                     {"max_new_tokens": 10_000})
    engine.close()

    rolling = TransformerLM(dataclasses.replace(
        CFG, sliding_window=6, rolling_cache=True
    ))
    with pytest.raises(RollingCacheUnsupported) as refusal:
        ContinuousEngine(rolling, params, max_batch=1)
    fault, label = classify_error(refusal.value)
    assert fault is FaultClass.PERMANENT
    assert label == "serve_model_unsupported"
    assert isinstance(refusal.value, ValueError)  # back-compat surface


# ---------------------------------------------------------------------------
# Shared-prefix prefill reuse (ISSUE 11): the prefix is prefilled once
# per engine and its KV reused across requests that share it — greedy
# streams must stay BIT-equal to both the no-reuse engine and the
# batch-1 oracle, with strictly less prefill work, and a mismatched
# prefix must fall back to the full-prefill path silently.
# ---------------------------------------------------------------------------


def test_engine_shared_prefix_streams_bit_equal():
    """Greedy streams with and without prefix reuse are bit-equal on
    ContinuousEngine (and equal to the generate() oracle), while the
    reuse engine pays measurably fewer prefill positions."""
    from covalent_tpu_plugin.models.serve import ContinuousEngine

    model, params = shared()
    prefix = np.asarray([5, 9, 2, 7, 11, 3, 8, 1, 4, 6], np.int32)
    suffixes = [[12, 13], [20], [31, 32, 33], [40, 41]]
    prompts = [
        np.concatenate([prefix, np.asarray(s, np.int32)])
        for s in suffixes
    ]
    requests = {f"r{i}": (p, 8) for i, p in enumerate(prompts)}

    plain = ContinuousEngine(
        model, params, max_batch=2, sync_steps=3, max_new_tokens=8,
    )
    plain_streams, _ = drive_engine(plain, dict(requests))
    plain.close()

    reuse = ContinuousEngine(
        model, params, max_batch=2, sync_steps=3, max_new_tokens=8,
        shared_prefix=prefix,
    )
    reuse_streams, _ = drive_engine(reuse, dict(requests))
    reuse.close()

    for i, p in enumerate(prompts):
        want = oracle(model, params, p, 8)[p.size:]
        np.testing.assert_array_equal(plain_streams[f"r{i}"], want)
        np.testing.assert_array_equal(reuse_streams[f"r{i}"], want)
    assert reuse.stats["prefix_hits"] == len(prompts)
    assert reuse.stats["prefix_misses"] == 0
    # The whole point: suffix-bucket prefill, not full-prompt prefill.
    assert 0 < reuse.stats["prefill_positions"] < (
        plain.stats["prefill_positions"]
    ), (reuse.stats, plain.stats)


def test_engine_shared_prefix_mismatch_falls_back():
    """Prefix-tree semantics (ISSUE 13 generalization): a diverging
    prompt reuses the COMMON part of a cached prefix (the lane rewinds
    to the divergence point), an equal prompt reuses all but its last
    token, and only a prompt whose usable common prefix is shorter than
    ``prefix_min_tokens`` takes the full-prefill road — all of them
    matching the oracle bit-for-bit, hits and misses mixing freely in
    one admission flush."""
    from covalent_tpu_plugin.models.serve import ContinuousEngine

    model, params = shared()
    prefix = np.asarray([5, 9, 2, 7, 11, 3], np.int32)
    hit = np.concatenate([prefix, np.asarray([21, 22], np.int32)])
    diverged = np.concatenate(
        [prefix[:-1], np.asarray([60, 21, 22], np.int32)]
    )  # rewound hit at the 5-token common prefix
    exact = prefix.copy()          # rewound hit at prefix[:-1]
    short = prefix[:3].copy()      # usable prefix < prefix_min_tokens
    requests = {
        "hit": (hit, 6), "div": (diverged, 6),
        "exact": (exact, 6), "short": (short, 6),
    }
    engine = ContinuousEngine(
        model, params, max_batch=2, sync_steps=2, max_new_tokens=6,
        shared_prefix=prefix,
    )
    streams, _ = drive_engine(engine, dict(requests))
    engine.close()
    for rid, (prompt, cap) in requests.items():
        want = oracle(model, params, prompt, cap)[prompt.size:]
        np.testing.assert_array_equal(streams[rid], want)
    assert engine.stats["prefix_hits"] == 3
    assert engine.stats["prefix_misses"] == 1


def test_engine_shared_prefix_sampling_deterministic():
    """Sampled streams draw from the per-admission key chain split in
    admission order BEFORE the prefix partition: a reuse engine and a
    plain engine with the same rng emit identical sampled tokens."""
    from covalent_tpu_plugin.models.serve import ContinuousEngine

    model, params = shared()
    prefix = np.asarray([5, 9, 2, 7], np.int32)
    prompts = [
        np.concatenate([prefix, np.asarray([12, 13], np.int32)]),
        np.asarray([9, 9, 9], np.int32),  # miss, interleaved with a hit
        np.concatenate([prefix, np.asarray([30], np.int32)]),
    ]
    requests = {f"r{i}": (p, 5) for i, p in enumerate(prompts)}
    kwargs = dict(
        max_batch=2, sync_steps=2, max_new_tokens=5,
        temperature=0.8, top_k=16, rng=jax.random.PRNGKey(11),
    )
    plain = ContinuousEngine(model, params, **kwargs)
    plain_streams, _ = drive_engine(plain, dict(requests))
    plain.close()
    reuse = ContinuousEngine(
        model, params, shared_prefix=prefix, **kwargs
    )
    reuse_streams, _ = drive_engine(reuse, dict(requests))
    reuse.close()
    for rid in requests:
        np.testing.assert_array_equal(
            plain_streams[rid], reuse_streams[rid]
        )


def test_engine_shared_prefix_validation():
    """An empty prefix and one leaving no suffix/generation room are
    refused at construction, not at first admission."""
    from covalent_tpu_plugin.models.serve import ContinuousEngine

    model, params = shared()
    with pytest.raises(ValueError, match="at least one token"):
        ContinuousEngine(
            model, params, max_batch=1, shared_prefix=np.zeros(0, np.int32)
        )
    with pytest.raises(ValueError, match="no room"):
        ContinuousEngine(
            model, params, max_batch=1, length=8,
            shared_prefix=np.arange(1, 8, dtype=np.int32),
        )


# ---------------------------------------------------------------------------
# Disaggregated prefill/decode (ISSUE 13): prefill_only on one engine,
# admit_from_kv on another — greedy streams must stay BIT-equal to one
# engine doing both phases (and to the batch-1 oracle), the decode
# engine must pay ZERO prefill positions, and the prefix tree must turn
# repeated prompts and shared prefixes into warm-KV hits.
# ---------------------------------------------------------------------------


def test_engine_kv_disaggregated_streams_bit_equal():
    """prefill_only -> serialized bundle -> admit_from_kv on a separate
    decode engine: streams bit-equal to the oracle AND to a single
    non-disaggregated engine, with no prefill work on the decode side."""
    from covalent_tpu_plugin.models.serve import ContinuousEngine

    model, params = shared()
    prompts = ragged_prompts(5, base_seed=77)
    requests = {f"r{i}": (p, 6) for i, p in enumerate(prompts)}

    joint = ContinuousEngine(
        model, params, max_batch=2, sync_steps=3, max_new_tokens=6,
    )
    joint_streams, _ = drive_engine(joint, dict(requests))
    joint.close()

    prefill_engine = ContinuousEngine(
        model, params, max_batch=2, sync_steps=3, max_new_tokens=6,
    )
    decode_engine = ContinuousEngine(
        model, params, max_batch=2, sync_steps=3, max_new_tokens=6,
    )
    bundles = {
        rid: prefill_engine.prefill_only(p, {"max_new_tokens": cap})
        for rid, (p, cap) in requests.items()
    }
    assert all(isinstance(b, bytes) for b in bundles.values())

    queue = list(requests.items())
    streams = {rid: [] for rid in requests}
    done = set()
    for _ in range(400):
        while queue and decode_engine.busy < decode_engine.slots:
            rid, (_p, cap) = queue.pop(0)
            decode_engine.admit_from_kv(
                rid, bundles[rid], {"max_new_tokens": cap}
            )
        for event in decode_engine.step():
            streams[event["rid"]].extend(event["tokens"])
            if event["done"]:
                done.add(event["rid"])
        if len(done) == len(requests) and not queue:
            break
    else:
        raise AssertionError("decode engine never drained")

    for rid, (p, cap) in requests.items():
        want = oracle(model, params, p, cap)[p.size:]
        np.testing.assert_array_equal(joint_streams[rid], want)
        np.testing.assert_array_equal(streams[rid], want)
    assert decode_engine.stats["kv_admits"] == len(requests)
    # The disaggregation contract: ALL prefill positions were paid on
    # the prefill tier, none on the decode tier.
    assert decode_engine.stats["prefill_positions"] == 0
    assert prefill_engine.stats["prefill_positions"] > 0
    assert prefill_engine.stats["kv_exports"] == len(requests)
    prefill_engine.close()
    decode_engine.close()


def test_engine_prefix_tree_repeated_and_shared_prompts():
    """The LRU prefix tree without ANY shared_prefix configuration: a
    repeated prompt hits (the previous admission's lane rewound one
    position), a prompt sharing a long prefix hits, and streams stay
    oracle-exact; the bound evicts oldest-first with the counter moving."""
    from covalent_tpu_plugin.models.serve import ContinuousEngine

    model, params = shared()
    base = np.asarray([7, 3, 9, 1, 12, 5, 8, 2], np.int32)
    repeat = base.copy()
    shared_tail = np.concatenate([base[:6], np.asarray([40, 41], np.int32)])
    engine = ContinuousEngine(
        model, params, max_batch=1, sync_steps=2, max_new_tokens=5,
    )
    streams = {}
    for rid, prompt in (
        ("a", base), ("b", repeat), ("c", shared_tail)
    ):
        engine.admit(rid, prompt, {"max_new_tokens": 5})
        got = []
        for _ in range(100):
            events = engine.step()
            for event in events:
                got.extend(event["tokens"])
                if event["done"]:
                    break
            else:
                continue
            break
        streams[rid] = got
    for rid, prompt in (("a", base), ("b", repeat), ("c", shared_tail)):
        want = oracle(model, params, prompt, 5)[prompt.size:]
        np.testing.assert_array_equal(streams[rid], want)
    # a: cold miss (tree empty — not even counted as a miss);
    # b: repeated prompt -> rewound hit; c: shared 6-token prefix -> hit.
    assert engine.stats["prefix_hits"] == 2
    assert engine.stats["prefix_misses"] == 0

    # LRU bound: a cache of 1 entry evicts oldest-first as fresh
    # admissions insert their lanes.
    small = ContinuousEngine(
        model, params, max_batch=1, sync_steps=2, max_new_tokens=3,
        prefix_cache_size=1,
    )
    for i, seed in enumerate((50, 51, 52)):
        prompt = np.asarray(
            jax.random.randint(
                jax.random.PRNGKey(seed), (6,), 0, CFG.vocab_size
            ),
            np.int32,
        )
        small.admit(f"e{i}", prompt, {"max_new_tokens": 3})
        for _ in range(50):
            if any(ev["done"] for ev in small.step()):
                break
    assert small.stats["prefix_evictions"] >= 1
    small.close()
    engine.close()


def test_engine_admit_from_kv_validation():
    """Garbage bytes, a bundle from a different model shape, duplicate
    rids, and over-budget admissions are refused with ValueError —
    never scattered into live lanes."""
    from covalent_tpu_plugin.models.serve import ContinuousEngine

    model, params = shared()
    engine = ContinuousEngine(
        model, params, max_batch=2, sync_steps=2, max_new_tokens=4,
    )
    prompt = np.asarray([3, 1, 4, 1, 5], np.int32)
    bundle = engine.prefill_only(prompt)
    with pytest.raises(Exception):
        engine.admit_from_kv("bad", b"not a pickle")
    other_cfg = dataclasses.replace(CFG, d_model=16, n_heads=2)
    other = TransformerLM(other_cfg)
    other_params = other.init(
        jax.random.PRNGKey(3), jnp.zeros((1, 4), jnp.int32)
    )["params"]
    other_engine = ContinuousEngine(
        other, other_params, max_batch=1, sync_steps=2, max_new_tokens=4,
    )
    with pytest.raises(ValueError, match="cache layout|lane leaf"):
        other_engine.admit_from_kv("r1", bundle)
    other_engine.close()
    engine.admit_from_kv("r1", bundle)
    with pytest.raises(ValueError, match="already admitted"):
        engine.admit_from_kv("r1", bundle)
    with pytest.raises(ValueError, match="exceeds"):
        engine.admit_from_kv(
            "r2", bundle, {"max_new_tokens": 1000}
        )
    # The valid admission still decodes oracle-exact after the refusals.
    got = []
    for _ in range(100):
        events = engine.step()
        for event in events:
            got.extend(event["tokens"])
            if event["done"]:
                break
        else:
            continue
        break
    want = oracle(model, params, prompt, 4)[prompt.size:]
    np.testing.assert_array_equal(got, want)
    engine.close()


# ---------------------------------------------------------------------------
# Speculative decoding + decode-mode lane groups (0.17)
# ---------------------------------------------------------------------------


DRAFT_CFG = dataclasses.replace(
    CFG, d_model=16, n_layers=1, n_heads=2, d_ff=32
)


def build_draft(seed=7):
    draft = TransformerLM(DRAFT_CFG)
    dparams = draft.init(
        jax.random.PRNGKey(seed), jnp.zeros((1, 4), jnp.int32)
    )["params"]
    return draft, dparams


def test_engine_spec_self_draft_bit_equal_full_accept():
    """Draft == target: every proposal agrees, so the accept rate is
    exactly 1.0 — and the streams are STILL the plain engine's, token
    for token (spec commits only the target's greedy picks)."""
    from covalent_tpu_plugin.models.serve import ContinuousEngine

    model, params = shared()
    prompts = ragged_prompts(5, base_seed=91)
    engine = ContinuousEngine(
        model, params, max_batch=2, sync_steps=6, max_new_tokens=8,
        length=40, draft_model=model, draft_params=params, draft_len=3,
    )
    assert engine._spec_refusal is None
    streams, _ = drive_engine(
        engine, {f"r{i}": (p, 8) for i, p in enumerate(prompts)},
    )
    for i, p in enumerate(prompts):
        want = oracle(model, params, p, 8)[p.size:]
        np.testing.assert_array_equal(streams[f"r{i}"], want)
    assert engine.stats["spec_rounds"] > 0
    assert engine.stats["spec_proposed"] > 0
    assert engine.stats["spec_accepted"] == engine.stats["spec_proposed"]
    assert engine.stats["spec_refusals"] == 0
    engine.close()


def test_engine_spec_disagreeing_draft_bit_equal_and_prefix_compose():
    """An unrelated tiny draft (worst case for speedup): streams stay
    bit-equal to the oracle, and a second pass over the same prompts
    rides the prefix tree (hits > 0) with identical streams — spec
    composes with warm-KV admission."""
    from covalent_tpu_plugin.models.serve import ContinuousEngine

    model, params = shared()
    draft, dparams = build_draft()
    prompts = ragged_prompts(4, base_seed=17)
    engine = ContinuousEngine(
        model, params, max_batch=2, sync_steps=6, max_new_tokens=6,
        length=40, draft_model=draft, draft_params=dparams, draft_len=2,
    )
    assert engine._spec_refusal is None
    first, _ = drive_engine(
        engine, {f"a{i}": (p, 6) for i, p in enumerate(prompts)},
    )
    hits_before = engine.stats["prefix_hits"]
    second, _ = drive_engine(
        engine, {f"b{i}": (p, 6) for i, p in enumerate(prompts)},
    )
    for i, p in enumerate(prompts):
        want = oracle(model, params, p, 6)[p.size:]
        np.testing.assert_array_equal(first[f"a{i}"], want)
        np.testing.assert_array_equal(second[f"b{i}"], want)
    assert engine.stats["prefix_hits"] > hits_before
    assert engine.stats["spec_proposed"] >= engine.stats["spec_accepted"]
    engine.close()


def test_engine_sampled_spec_refuses_and_matches_plain_sampled():
    """A sampled session refuses the draft (the continuous verify path
    is greedy-only; ``speculative_sample`` is the offline sampled road,
    distribution-tested in test_speculative.py) — and the fallback is
    byte-equal to the same engine built without a draft, because the
    rng chains are untouched by the refusal."""
    from covalent_tpu_plugin.models.serve import ContinuousEngine

    model, params = shared()
    draft, dparams = build_draft()
    prompts = ragged_prompts(3, base_seed=55)
    requests = {f"r{i}": (p, 6) for i, p in enumerate(prompts)}

    kwargs = dict(
        max_batch=2, sync_steps=4, max_new_tokens=6, length=40,
        temperature=0.8, rng=jax.random.PRNGKey(11),
    )
    spec = ContinuousEngine(
        model, params, draft_model=draft, draft_params=dparams,
        draft_len=2, **kwargs,
    )
    assert spec._spec_refusal is not None and "sampled" in spec._spec_refusal
    assert spec.stats["spec_refusals"] == 1
    spec_streams, _ = drive_engine(spec, dict(requests))
    spec.close()

    plain = ContinuousEngine(model, params, **kwargs)
    plain_streams, _ = drive_engine(plain, dict(requests))
    plain.close()
    assert spec_streams == plain_streams


def test_engine_spec_headroom_refusal_falls_back_bit_equal():
    """length == max_seq leaves no scratch room for the verify slab:
    the draft is refused by name and the session serves the plain loop,
    oracle-exact."""
    from covalent_tpu_plugin.models.serve import ContinuousEngine

    model, params = shared()
    draft, dparams = build_draft()
    prompts = ragged_prompts(2, base_seed=23)
    engine = ContinuousEngine(
        model, params, max_batch=2, sync_steps=4, max_new_tokens=6,
        draft_model=draft, draft_params=dparams, draft_len=4,
    )
    assert engine._spec_refusal is not None
    assert "max_seq" in engine._spec_refusal
    assert engine.stats["spec_refusals"] == 1
    streams, _ = drive_engine(
        engine, {f"r{i}": (p, 6) for i, p in enumerate(prompts)},
    )
    for i, p in enumerate(prompts):
        want = oracle(model, params, p, 6)[p.size:]
        np.testing.assert_array_equal(streams[f"r{i}"], want)
    engine.close()


def test_engine_quality_routing_unknown_and_refused_fall_back():
    """The quality knob never rejects: an unknown mode and a mode whose
    lane group refused to build (int8 on this scanned model) both land
    on the fp lane bit-exact, each counting a mode_refusal; kv_quant
    requests land on their own group and its tokens are counted."""
    from covalent_tpu_plugin.models.serve import ContinuousEngine

    model, params = shared()
    assert model.config.scan_layers  # int8 must refuse on this model
    prompts = ragged_prompts(3, base_seed=31)
    engine = ContinuousEngine(
        model, params, max_batch=4, sync_steps=4, max_new_tokens=6,
        decode_modes=("fp", "int8", "kv_quant"),
    )
    # int8's variant refused at construction (scan_layers), kv_quant up.
    assert "int8" in engine._mode_refusal
    assert "kv_quant" in engine._subs

    streams = {}
    done = set()
    quality = {"exact": "exact", "weird": "int4", "i8": "int8",
               "qkv": "kv_quant"}
    for rid, q in quality.items():
        engine.admit(
            rid, prompts[hash(rid) % 3], {"max_new_tokens": 6, "quality": q}
        )
        streams[rid] = []
    for _ in range(200):
        for event in engine.step():
            streams[event["rid"]].extend(event["tokens"])
            if event["done"]:
                done.add(event["rid"])
        if len(done) == len(quality):
            break
    assert done == set(quality)
    # exact/unknown/refused-int8 are all the fp lane: oracle-exact.
    for rid in ("exact", "weird", "i8"):
        p = prompts[hash(rid) % 3]
        want = oracle(model, params, p, 6)[p.size:]
        np.testing.assert_array_equal(streams[rid], want)
    # unknown + refused-mode requests each counted a refusal.
    assert engine.stats["mode_refusals"] >= 2
    assert engine.stats["mode_tokens_fp"] >= 18
    assert engine.stats["mode_tokens_kv_quant"] >= 6
    assert len(streams["qkv"]) == 6
    engine.close()


def test_engine_kv_quant_bundle_fingerprint_mismatch_degrades():
    """The disagg quantization fingerprint: a kv_quant prefill bundle
    ships int8 KV (smaller on the wire), a decode engine WITHOUT that
    lane group refuses it by fingerprint, and the caller-side degrade —
    a plain full-prefill admit — streams byte-equal to the fp oracle.
    A decode engine WITH the group admits it and streams byte-equal to
    a joint kv_quant engine (same-mode disagg exactness)."""
    import pickle

    from covalent_tpu_plugin.models.serve import ContinuousEngine

    model, params = shared()
    prompt = np.asarray([7, 3, 9, 2, 6], np.int32)
    mk = lambda modes: ContinuousEngine(
        model, params, max_batch=2, sync_steps=3, max_new_tokens=6,
        decode_modes=modes,
    )

    prefill = mk(("fp", "kv_quant"))
    raw_fp = prefill.prefill_only(prompt)
    raw_q = prefill.prefill_only(prompt, {"quality": "kv_quant"})
    bundle_q = pickle.loads(raw_q)
    assert bundle_q["quant"] == "kv_quant"
    assert pickle.loads(raw_fp)["quant"] == "fp"
    # int8 KV leaves make the quantized bundle smaller on the wire.
    assert any(
        np.asarray(leaf).dtype == np.int8 for leaf in bundle_q["leaves"]
    )
    assert len(raw_q) < len(raw_fp)
    prefill.close()

    # fp-only decode tier: fingerprint mismatch refuses, degrade path
    # (full prefill) is byte-equal to the oracle.
    fp_only = mk(("fp",))
    with pytest.raises(ValueError, match="quantization fingerprint"):
        fp_only.admit_from_kv("r1", raw_q)
    fp_only.admit("r1", prompt, {"max_new_tokens": 6})
    got = []
    for _ in range(100):
        for event in fp_only.step():
            got.extend(event["tokens"])
            if event["done"]:
                break
        else:
            continue
        break
    want = oracle(model, params, prompt, 6)[prompt.size:]
    np.testing.assert_array_equal(got, want)
    assert fp_only.stats["kv_admits"] == 0
    fp_only.close()

    # Matching decode tier: the bundle routes to the kv_quant group and
    # streams byte-equal to a joint (non-disagg) kv_quant engine.
    joint = mk(("fp", "kv_quant"))
    joint_streams, _ = drive_engine(joint, {"j": (prompt, 6)})
    joint.close()
    joint_q = mk(("fp", "kv_quant"))
    streams = {}
    done = set()
    joint_q.admit("q", prompt, {"max_new_tokens": 6, "quality": "kv_quant"})
    streams["q"] = []
    for _ in range(100):
        for event in joint_q.step():
            streams[event["rid"]].extend(event["tokens"])
            if event["done"]:
                done.add(event["rid"])
        if done:
            break
    joint_q.close()

    decode = mk(("fp", "kv_quant"))
    decode.admit_from_kv("d", raw_q, {"max_new_tokens": 6})
    dstream = []
    for _ in range(100):
        for event in decode.step():
            dstream.extend(event["tokens"])
            if event["done"]:
                break
        else:
            continue
        break
    assert decode.stats["kv_admits"] == 1
    np.testing.assert_array_equal(dstream, streams["q"])
    decode.close()
