"""Benchmark: electron wall-clock + dispatch overhead (BASELINE.json metric).

Runs the north-star workload end-to-end through the REAL framework path —
workflow dispatch -> TPUExecutor -> staged harness subprocess -> result
fetch — on whatever accelerator is present (the driver runs this on TPU):

  1. overhead probe: several trivial electrons through the full lifecycle;
     per-electron dispatch overhead comes from the executor's stage timers
     (connect/preflight amortised by the pooled transport).
  2. training electron: Flax MLP on synthetic MNIST, jitted train steps on
     the accelerator, through the same dispatch path.

Prints ONE JSON line.  ``value`` is the median per-electron dispatch
overhead in seconds; the reference's own defaults bound its per-electron
overhead at >= its 15 s poll interval + ~10 sequential SSH round-trips
(BASELINE.md; reference ssh.py:87 poll_freq=15, SURVEY §3.1), and the north
star demands < 2 s, so ``vs_baseline`` is reported as target/actual:
2.0 / value (> 1 beats the target; higher is better).
"""

from __future__ import annotations

import asyncio
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from covalent_tpu_plugin import TPUExecutor  # noqa: E402

OVERHEAD_PROBES = 5
TRAIN_STEPS = 100
TRAIN_BATCH = 512


def trivial_electron(i: int) -> int:
    return i * i


def matmul_electron(n: int, iters: int) -> dict:
    """BASELINE config 2: n×n bf16 einsum on the accelerator, TFLOP/s."""
    import time

    import jax
    import jax.numpy as jnp

    x = jnp.ones((n, n), jnp.bfloat16)
    y = jnp.ones((n, n), jnp.bfloat16)

    @jax.jit
    def mm(a, b):
        return jnp.einsum("ij,jk->ik", a, b)

    jax.device_get(mm(x, y)[0, 0])  # compile + warm
    t0 = time.perf_counter()
    out = x
    for _ in range(iters):
        out = mm(out, y)
    # device_get, not block_until_ready: proxy/tunnel backends can make the
    # latter a no-op, and a fetched scalar can't lie about completion.
    jax.device_get(out[0, 0])
    elapsed = time.perf_counter() - t0
    return {
        "tflops": (2 * n**3 * iters) / elapsed / 1e12,
        "backend": jax.devices()[0].platform,
    }


def attention_electron(seq_len: int) -> dict:
    """Pallas flash attention vs the fused-XLA dense path, on the chip."""
    import time

    import jax
    import jax.numpy as jnp

    from covalent_tpu_plugin.ops.attention import flash_attention, mha_reference

    b, h, d = 2, 16, 64
    q = jax.random.normal(jax.random.PRNGKey(0), (b, h, seq_len, d), jnp.bfloat16)
    k = jax.random.normal(jax.random.PRNGKey(1), (b, h, seq_len, d), jnp.bfloat16)
    v = jax.random.normal(jax.random.PRNGKey(2), (b, h, seq_len, d), jnp.bfloat16)

    def bench(fn, iters=10):
        f = jax.jit(fn)
        jax.device_get(f(q, k, v)[0, 0, 0, 0])  # compile + warm
        t0 = time.perf_counter()
        for _ in range(iters):
            out = f(q, k, v)
        jax.device_get(out[0, 0, 0, 0])
        return (time.perf_counter() - t0) / iters

    ref = bench(lambda q, k, v: mha_reference(q, k, v, causal=True))
    flash = bench(lambda q, k, v: flash_attention(q, k, v, causal=True))
    return {"ref_ms": ref * 1e3, "flash_ms": flash * 1e3, "speedup": ref / flash}


def mnist_train_electron(steps: int, batch_size: int) -> dict:
    """Train the Flax MLP on synthetic MNIST; returns loss curve + rate.

    Self-contained (imports inside) so it unpickles on any worker with jax
    installed, per the harness contract.
    """
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from flax.training import train_state

    import flax.linen as nn

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = x.reshape((x.shape[0], -1))
            x = nn.relu(nn.Dense(256)(x))
            x = nn.relu(nn.Dense(128)(x))
            return nn.Dense(10)(x)

    rng = np.random.default_rng(0)
    labels = rng.integers(0, 10, size=(batch_size,))
    yy, xx = np.mgrid[0:28, 0:28].astype(np.float32) / 28.0
    templates = np.stack(
        [np.sin(2 * np.pi * (xx * (1 + c % 5) + yy * (1 + c // 5)) + c) for c in range(10)]
    )
    images = (
        templates[labels] + 0.3 * rng.standard_normal((batch_size, 28, 28))
    ).astype(np.float32)[..., None]
    batch = {"image": jnp.asarray(images), "label": jnp.asarray(labels)}

    model = MLP()
    state = train_state.TrainState.create(
        apply_fn=model.apply,
        params=model.init(jax.random.PRNGKey(0), batch["image"])["params"],
        tx=optax.adam(1e-3),
    )

    @jax.jit
    def step(state, batch):
        def loss_fn(params):
            logits = state.apply_fn({"params": params}, batch["image"])
            return optax.softmax_cross_entropy_with_integer_labels(
                logits.astype(jnp.float32), batch["label"]
            ).mean()

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        return state.apply_gradients(grads=grads), loss

    state, loss = step(state, batch)  # compile
    loss.block_until_ready()
    t0 = time.perf_counter()
    for _ in range(steps):
        state, loss = step(state, batch)
    final_loss = float(loss)
    elapsed = time.perf_counter() - t0
    return {
        "final_loss": final_loss,
        "steps_per_s": steps / elapsed,
        "backend": jax.devices()[0].platform,
    }


async def main() -> dict:
    workdir = f"/tmp/covalent-tpu-bench-{os.getpid()}"
    repo_root = os.path.dirname(os.path.abspath(__file__))
    executor = TPUExecutor(
        transport="local",
        cache_dir=f"{workdir}/cache",
        remote_cache=f"{workdir}/remote",
        python_path=sys.executable,
        poll_freq=0.2,
        pool_preload="cloudpickle",
        task_env={
            "PYTHONPATH": repo_root + os.pathsep + os.environ.get("PYTHONPATH", "")
        },
    )

    # Warm the pooled connection + preflight cache (steady-state overhead is
    # what an N-electron lattice pays per electron).
    await executor.run(trivial_electron, [0], {}, {"dispatch_id": "warm", "node_id": 0})

    overheads = []
    for i in range(OVERHEAD_PROBES):
        await executor.run(
            trivial_electron, [i], {}, {"dispatch_id": "probe", "node_id": i}
        )
        overheads.append(executor.last_timings["overhead"])

    # BASELINE config 3: 8-electron fan-out. Eight independent electrons
    # dispatched concurrently through one executor; the figure of merit is
    # amortised per-electron wall time (concurrency hides each other's
    # round-trips; the reference's async interleaving is the same idea at
    # 15 s poll granularity).  A single-electron wall measure first, so the
    # speedup factor separates framework concurrency from host noise (e.g.
    # sandboxes where interpreter startup alone costs seconds).
    single_start = time.perf_counter()
    await executor.run(trivial_electron, [0], {}, {"dispatch_id": "solo", "node_id": 0})
    single_wall = time.perf_counter() - single_start

    fanout_start = time.perf_counter()
    await asyncio.gather(
        *(
            executor.run(trivial_electron, [i], {}, {"dispatch_id": "fan", "node_id": i})
            for i in range(8)
        )
    )
    fanout_wall = time.perf_counter() - fanout_start

    # BASELINE config 2: single-electron 4k×4k einsum on the chip.
    matmul_stats = await executor.run(
        matmul_electron, [4096, 64], {}, {"dispatch_id": "mm", "node_id": 0}
    )

    # Long-context hot op: flash kernel vs dense path at S=4096.
    attn_stats = await executor.run(
        attention_electron, [4096], {}, {"dispatch_id": "attn", "node_id": 0}
    )

    wall_start = time.perf_counter()
    train_stats = await executor.run(
        mnist_train_electron,
        [TRAIN_STEPS, TRAIN_BATCH],
        {},
        {"dispatch_id": "mnist", "node_id": 0},
    )
    electron_wall = time.perf_counter() - wall_start
    train_overhead = executor.last_timings["overhead"]
    await executor.close()

    overhead = statistics.median(overheads)
    return {
        "metric": "dispatch_overhead_s",
        "value": round(overhead, 4),
        "unit": "s",
        "vs_baseline": round(2.0 / max(overhead, 1e-9), 2),
        "mnist_steps_per_s": round(train_stats["steps_per_s"], 2),
        "mnist_final_loss": round(train_stats["final_loss"], 4),
        "mnist_electron_wall_s": round(electron_wall, 3),
        "mnist_dispatch_overhead_s": round(train_overhead, 4),
        "fanout8_wall_s": round(fanout_wall, 3),
        "fanout8_per_electron_s": round(fanout_wall / 8, 4),
        "fanout8_speedup_vs_serial": round(8 * single_wall / fanout_wall, 2),
        "matmul4k_tflops": round(matmul_stats["tflops"], 2),
        "flash_attn_4k_speedup": round(attn_stats["speedup"], 2),
        "flash_attn_4k_ms": round(attn_stats["flash_ms"], 2),
        "train_backend": train_stats["backend"],
    }


if __name__ == "__main__":
    print(json.dumps(asyncio.run(main())))
